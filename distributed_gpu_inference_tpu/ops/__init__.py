"""TPU kernels and compute ops.

First-party replacements for the native kernels the reference borrows from
vLLM (PagedAttention CUDA) and SGLang (RadixAttention Triton) — SURVEY §2.3:
Pallas paged-attention over HBM block tables with a pure-XLA gather fallback,
flash-style prefill attention, and on-device sampling.
"""

from distributed_gpu_inference_tpu.ops.attention import (  # noqa: F401
    dense_causal_attention,
    paged_attention,
)
from distributed_gpu_inference_tpu.ops.sampling import (  # noqa: F401
    sample_tokens,
    sample_tokens_per_slot,
)
