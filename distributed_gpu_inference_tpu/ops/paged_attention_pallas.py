"""Pallas TPU paged-attention kernel (decode path).

Replaces vLLM's PagedAttention CUDA kernel (SURVEY §2.3) with a TPU kernel
reading KV pages from HBM via block tables. Until the hand-written kernel
lands (ops task #3), this module exposes the same signature backed by the
XLA gather implementation so TPU execution is always correct.
"""

from __future__ import annotations

import jax


def paged_attention_pallas(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    kv_lens: jax.Array,
    block_size: int = 16,
    window=None,
) -> jax.Array:
    from distributed_gpu_inference_tpu.ops.attention import paged_attention_xla

    return paged_attention_xla(
        q, k_pool, v_pool, block_tables, positions, kv_lens, block_size,
        window=window,
    )
