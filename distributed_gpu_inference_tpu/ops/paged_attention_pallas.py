"""Pallas TPU paged-attention decode kernel (fused KV-write + attention).

First-party replacement for vLLM's PagedAttention CUDA kernel (SURVEY §2.3).
Decode (S = 1) is HBM-bandwidth-bound; this kernel owns the WHOLE per-layer
decode KV path:

- **Fused token write**: the new K/V rows for the step are DMA'd into their
  page slots inside the kernel (pools are input/output-aliased), replacing
  the XLA scatter. Round-2 profiling showed the scatter forced a
  scatter-preferred pool layout inside the decode loop while the kernel
  required the natural layout — XLA reconciled them by COPYING both pools
  every step (~10-20 ms/step at serving pool sizes, scaling with pool size).
- **Full-pool operands + layer index**: the kernel takes the stacked
  ``[L, N, Hkv, Bk, D]`` pools and a scalar ``layer_idx`` instead of a
  per-layer slice — a custom-call operand must be materialized, so the old
  single-layer API made XLA copy the layer slice (pool_bytes/L per layer per
  pool per step) just to pass it in.
- Walks only the **live** page groups of each sequence — the grid is
  ``(B, max_groups)`` and dead cells skip in a few cycles,
- DMAs each KV page HBM→VMEM exactly once (whole ``[Hkv, Bk, D]`` pages stay
  contiguous) and runs flash-style online softmax per page group,
- **Pipelines DMA across the whole (sequence, group) walk** — while group g
  of sequence b computes, the next live group's pages (even of sequence
  b+1) are in flight into the other buffer slot (mutable scalar
  ``buffer_index``/``init_flag``, the standard TPU pattern, cf.
  jax.experimental.pallas.ops.tpu.paged_attention). Round-1's kernel
  double-buffered only within one sequence, so short contexts ran DMA and
  compute serialized and lost to the XLA gather path (ADVICE r1 #3),
- sizes page groups by a VMEM byte budget instead of a fixed token count
  (ADVICE r1 #2: Gemma-7B-geometry pages are 16x llama pages),
- computes every (kv-head, GQA-query-group) in one batched MXU contraction
  per group, in the pool dtype (bf16 in, f32 accumulation) — converting
  staged pages to f32 was a VPU-bound relayout that dominated large-batch
  steps.

Write/read ordering: all token writes are issued AND waited in the first
grid cell, before any read DMA is issued (read prefetches only start in live
cells, which come later in the sequential grid), so a step's written token is
visible to its own attention (its position is within ``kv_lens``).

Correctness contract is identical to ``paged_attention_xla`` over the
written pool (same masking semantics, including window and padded-query
handling); parametrized parity tests drive both through the same cases
(CPU: interpret mode).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Pallas-TPU API drift shims: older releases name the off-chip memory space
# ANY (HBM arrived later) and the compiler-params dataclass TPUCompilerParams.
# Semantics are identical for our usage (full-array HBM-resident operands the
# kernels DMA page-wise), so alias rather than pin a jax version.
_HBM = getattr(pltpu, "HBM", pltpu.ANY)
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

_NEG_INF = -1e30
# VMEM budget for the four KV staging buffers (2 pools x 2 slots); the rest
# of VMEM stays free for q/out blocks and compute temporaries.
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024


def _pages_per_group(
    block_size: int, hkv: int, head_dim: int, itemsize: int, max_pages: int,
    staging_pages: int = 0, scale_page_bytes: int = 0,
) -> int:
    """Pages DMA'd per loop iteration.

    Target ~512-token groups (the grid step has a fixed cost of ~2us on
    v5e, amortized against ~0.6us/128-token HBM transfer), but scale DOWN so
    2 slots x G pages x 2 pools — plus ``staging_pages`` write-staging pages
    — fits the VMEM budget regardless of page geometry, and never exceed the
    static table width. ``scale_page_bytes``: per-page bytes of the int8
    path's bf16 scale buffers ([Bk, D] per page, staged AND double-buffered
    alongside the data pages) — at MQA-ish hkv they rival the int8 data
    pages, so they must count against the same budget."""
    page_bytes = hkv * block_size * head_dim * itemsize + scale_page_bytes
    budget = _VMEM_BUDGET_BYTES - staging_pages * page_bytes
    g = max(1, budget // (4 * page_bytes))
    g = min(g, max(512 // block_size, 1), max_pages)
    return max(g, 1)


def _quantize_token_rows(x: jax.Array, axes) -> Tuple[jax.Array, jax.Array]:
    """THE scalar int8-KV quantization contract, shared by the host-side
    pool quantizer (:func:`quantize_kv_pool`) and the kernel's fused token
    write so the two can never drift: one scale per token over every
    (head, channel) element — amax over ``axes`` floored at 1e-6, /127,
    ROUNDED TO bf16 BEFORE quantizing (the stored int8 must match the
    stored bf16 scale exactly) — real = int * scale. Returns (int8 like x,
    f32 scale with ``axes`` kept as size-1 dims)."""
    amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    scale = (jnp.maximum(amax, 1e-6) / 127.0).astype(jnp.bfloat16).astype(
        jnp.float32
    )
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _decode_kernel(
    # scalar prefetch (SMEM; bidx/init are MUTABLE and persist across the
    # sequential grid — they carry the DMA pipeline state)
    bt_ref,        # [B, M] int32 block tables
    lens_ref,      # [B] int32 kv lengths (incl. the token written this step)
    pos_ref,       # [B] int32 query positions (kv_len - 1; <0 = inactive)
    wpos_ref,      # [B] int32 write positions (<0 = no write for this row)
    layer_ref,     # [1] int32 layer index into the stacked pools
    bidx_ref,      # [1] int32 current double-buffer slot
    init_ref,      # [1] int32 1 until the first live chunk issues its DMA
    # blocked operands
    q_ref,         # [1, 1, Nh, D] — this sequence's query heads
    newk_ref,      # [B, Hkv, D] new K rows (VMEM; whole-batch block)
    newv_ref,      # [B, Hkv, D]
    k_hbm,         # [L, N, Hkv, Bk, D] full stacked pool (ANY/HBM, aliased)
    v_hbm,         # [L, N, Hkv, Bk, D]
    *rest,         # [ks_hbm, vs_hbm,] out_ref, ko_hbm, vo_hbm, scratch...
    batch: int,
    block_size: int,
    pages_per_group: int,
    max_pages: int,
    window: Optional[int],
    scale: float,
    fused_write: bool,
    quantized: bool,
):
    # int8 pools carry per-(page, token) scale pages ([L, N, Bk, D] bf16,
    # lane-replicated): staged tiles dequantize IN PAGE LAYOUT during the
    # upcast — int8→bf16 is a native VPU convert (unlike fp8, which v5e
    # emulates in software: the round-3 2.2x loss) and the scale multiply
    # rides the same elementwise pass before the bf16 MXU dot
    if quantized:
        (_ks_in, _vs_in, out_ref, ko_hbm, vo_hbm, kso_hbm, vso_hbm,
         kbuf, vbuf, ksbuf, vsbuf, sems, ssems, wsems,
         wk_stage, wv_stage, wks_stage, wvs_stage,
         m_scr, l_scr, acc_scr) = rest
    else:
        (out_ref, ko_hbm, vo_hbm,
         kbuf, vbuf, sems, wsems,
         wk_stage, wv_stage, m_scr, l_scr, acc_scr) = rest
        kso_hbm = vso_hbm = ksbuf = vsbuf = ssems = None
        wks_stage = wvs_stage = None
    b = pl.program_id(0)
    i = pl.program_id(1)
    gp = pages_per_group
    gsz = gp * block_size
    nh, d = q_ref.shape[2], q_ref.shape[3]
    hkv = k_hbm.shape[2]
    qpk = nh // hkv
    layer = layer_ref[0]
    max_groups = pl.num_programs(1)

    def num_groups(s):
        s = jnp.clip(s, 0, batch - 1)
        # clamp to the grid bound: a kv_len beyond the table capacity (caller
        # bug) must not leave a prefetched DMA un-waited at kernel exit —
        # that wedges the chip with a hung semaphore instead of just
        # returning garbage for the out-of-range tail
        return jnp.minimum(pl.cdiv(lens_ref[s], gsz), max_groups)

    def start_group(s):
        if window is None:
            return jnp.int32(0)
        s = jnp.clip(s, 0, batch - 1)
        # first visible key = max(0, pos - window + 1) → its group
        return jnp.maximum(pos_ref[s] - window + 1, 0) // gsz

    ng_b = num_groups(b)
    start_b = start_group(b)
    live = (i >= start_b) & (i < ng_b)

    if fused_write:
        # ---- token writes: ALL rows handled in the FIRST grid cell,
        # strictly before any read DMA is issued (reads start in live cells,
        # which are at or after (0,0) in the sequential grid). The HBM pool
        # is (8,128)-tiled on its last two dims, so a single token slot is
        # not DMA-addressable — each row's page is staged whole into VMEM,
        # the slot row is blended in with a vectorized select (no dynamic
        # sublane store), and the page is written back whole. All four DMA
        # phases are issued batch-wide before being waited, so latency is
        # paid ~twice, not 4B times. Distinct rows never share a page (each
        # sequence owns its block chain and CoW gives writers exclusive
        # pages), so whole-page write-back cannot clobber a sibling write.
        n_stage = wk_stage.shape[0]

        def row_page(r):
            wpos = wpos_ref[r]
            safe = jnp.maximum(wpos, 0)
            page = bt_ref[r, jnp.minimum(safe // block_size, max_pages - 1)]
            return wpos >= 0, page, safe % block_size

        def stage_copies(r, dst_first):
            valid, page, _ = row_page(r)
            st = r % n_stage

            def cp(hbm, stage, sem):
                return pltpu.make_async_copy(
                    hbm.at[layer, page], stage.at[st], sem
                ) if dst_first else pltpu.make_async_copy(
                    stage.at[st], hbm.at[layer, page], sem
                )

            copies = [cp(ko_hbm, wk_stage, wsems.at[0, st]),
                      cp(vo_hbm, wv_stage, wsems.at[1, st])]
            if quantized:
                copies += [cp(kso_hbm, wks_stage, wsems.at[2, st]),
                           cp(vso_hbm, wvs_stage, wsems.at[3, st])]
            return valid, copies

        @pl.when((b == 0) & (i == 0))
        def _():
            # rows are processed in chunks of n_stage staging pages so the
            # scratch footprint stays within the VMEM budget at any
            # batch x page geometry; within a chunk the four DMA phases are
            # issued batch-wide before being waited
            for c0 in range(0, batch, n_stage):
                rows = range(c0, min(c0 + n_stage, batch))
                for r in rows:  # static unroll over rows
                    valid, copies = stage_copies(r, dst_first=True)

                    @pl.when(valid)
                    def _():
                        for c in copies:
                            c.start()

                for r in rows:
                    valid, copies = stage_copies(r, dst_first=True)

                    @pl.when(valid)
                    def _():
                        for c in copies:
                            c.wait()

                for r in rows:
                    valid, _page, slot = row_page(r)
                    st = r % n_stage

                    @pl.when(valid)
                    def _():
                        sel = (
                            lax.broadcasted_iota(
                                jnp.int32, (hkv, block_size, d), 1
                            )
                            == slot
                        )
                        if quantized:
                            # quantize the new rows IN-KERNEL through the
                            # shared contract: one scale over the token's
                            # whole (Hkv, D) row block
                            newk = newk_ref[r].astype(jnp.float32)
                            newv = newv_ref[r].astype(jnp.float32)
                            ki, sk = _quantize_token_rows(newk, (0, 1))
                            vi, sv = _quantize_token_rows(newv, (0, 1))
                            sk, sv = sk[0, 0], sv[0, 0]
                            wk_stage[st] = jnp.where(
                                sel, ki[:, None, :], wk_stage[st]
                            )
                            wv_stage[st] = jnp.where(
                                sel, vi[:, None, :], wv_stage[st]
                            )
                            sel_s = (
                                lax.broadcasted_iota(
                                    jnp.int32, (block_size, d), 0
                                )
                                == slot
                            )
                            wks_stage[st] = jnp.where(
                                sel_s, sk.astype(jnp.bfloat16), wks_stage[st]
                            )
                            wvs_stage[st] = jnp.where(
                                sel_s, sv.astype(jnp.bfloat16), wvs_stage[st]
                            )
                        else:
                            wk_stage[st] = jnp.where(
                                sel, newk_ref[r][:, None, :], wk_stage[st]
                            )
                            wv_stage[st] = jnp.where(
                                sel, newv_ref[r][:, None, :], wv_stage[st]
                            )

                for r in rows:
                    valid, copies = stage_copies(r, dst_first=False)

                    @pl.when(valid)
                    def _():
                        for c in copies:
                            c.start()

                for r in rows:
                    valid, copies = stage_copies(r, dst_first=False)

                    @pl.when(valid)
                    def _():
                        for c in copies:
                            c.wait()

    def start_dma(s, j, slot):
        """Issue the page DMAs of group j of sequence s into buffer slot.
        Reads go through the ALIASED output refs so they observe the token
        writes above."""
        for p in range(gp):  # static unroll: G paired page DMAs
            idx = jnp.minimum(j * gp + p, max_pages - 1)  # clamp, mask later
            page = bt_ref[jnp.clip(s, 0, batch - 1), idx]
            # whole-page slice [Hkv, Bk, D]: contiguous, tiling-safe
            pltpu.make_async_copy(
                ko_hbm.at[layer, page], kbuf.at[slot, p], sems.at[0, slot, p]
            ).start()
            pltpu.make_async_copy(
                vo_hbm.at[layer, page], vbuf.at[slot, p], sems.at[1, slot, p]
            ).start()
            if quantized:
                # via the ALIASED outputs: this step's written scales must
                # be visible to its own attention, like the data pages
                pltpu.make_async_copy(
                    kso_hbm.at[layer, page], ksbuf.at[slot, p],
                    ssems.at[0, slot, p],
                ).start()
                pltpu.make_async_copy(
                    vso_hbm.at[layer, page], vsbuf.at[slot, p],
                    ssems.at[1, slot, p],
                ).start()

    def wait_dma(s, j, slot):
        for p in range(gp):
            idx = jnp.minimum(j * gp + p, max_pages - 1)
            page = bt_ref[jnp.clip(s, 0, batch - 1), idx]
            pltpu.make_async_copy(
                ko_hbm.at[layer, page], kbuf.at[slot, p], sems.at[0, slot, p]
            ).wait()
            pltpu.make_async_copy(
                vo_hbm.at[layer, page], vbuf.at[slot, p], sems.at[1, slot, p]
            ).wait()
            if quantized:
                pltpu.make_async_copy(
                    kso_hbm.at[layer, page], ksbuf.at[slot, p],
                    ssems.at[0, slot, p],
                ).wait()
                pltpu.make_async_copy(
                    vso_hbm.at[layer, page], vsbuf.at[slot, p],
                    ssems.at[1, slot, p],
                ).wait()

    def next_chunk(s, j):
        """Grid-order successor of live chunk (s, j): (s, j+1) within the
        sequence, else the first live group of the next non-empty sequence;
        (batch, 0) when the walk is done."""

        def advance_seq():
            def step(_, ss):
                return jnp.where(
                    (ss < batch) & (num_groups(ss) == 0), ss + 1, ss
                )

            ns = lax.fori_loop(0, batch, step, s + 1)
            return ns, jnp.where(ns < batch, start_group(ns), 0)

        return lax.cond(
            j + 1 < num_groups(s), lambda: (s, j + 1), advance_seq
        )

    # inactive sequence: its output block must still be written once
    @pl.when((ng_b == 0) & (i == 0))
    def _():
        out_ref[0, 0] = jnp.zeros((nh, d), out_ref.dtype)

    @pl.when(live)
    def _():
        slot = bidx_ref[0]

        # very first live chunk of the whole walk: nothing prefetched it
        @pl.when(init_ref[0] == 1)
        def _():
            start_dma(b, i, slot)

        init_ref[0] = 0

        # pipeline: issue the NEXT live chunk (possibly of the next
        # sequence) into the other slot before waiting on this one
        nb, ni = next_chunk(b, i)

        @pl.when(nb < batch)
        def _():
            start_dma(nb, ni, 1 - slot)

        bidx_ref[0] = 1 - slot

        wait_dma(b, i, slot)

        @pl.when(i == start_b)
        def _():
            m_scr[...] = jnp.full((hkv, qpk), _NEG_INF, jnp.float32)
            l_scr[...] = jnp.zeros((hkv, qpk), jnp.float32)
            acc_scr[...] = jnp.zeros((hkv, qpk, d), jnp.float32)

        kv_len = lens_ref[b]
        pos = pos_ref[b]
        # [Hkv, qpk, D] — GQA head h = g*qpk + j belongs to kv head g.
        # The dot runs in the pool dtype when it is MXU-native (bf16 with
        # f32 accumulation; converting the staged K/V pages to f32 in VMEM
        # is a VPU-bound relayout of megabytes per grid cell that dominated
        # the kernel at large batch). An fp8 pool (kv_cache_dtype="fp8") is
        # NOT MXU-native on v5e — pages are upcast to bf16 in VMEM right at
        # the dot operand, so HBM still only saw the fp8 bytes. The softmax
        # scale is applied to the f32 scores so q carries no extra rounding.
        cdt = jnp.bfloat16 if kbuf.dtype.itemsize == 1 else kbuf.dtype
        qf = q_ref[0, 0].reshape(hkv, qpk, d).astype(cdt)

        # [G, Hkv, Bk, D] → [Hkv, G*Bk, D] (leading-dim relabel, no relayout)
        if quantized:
            # dequantize in the page layout during the upcast: the int8→bf16
            # convert is a native VPU op (unlike fp8, which v5e emulates) and
            # the scale rides the same elementwise pass. Scale pages store
            # one per-(page, token) scale LANE-REPLICATED as [Bk, D] bf16 —
            # the only layout that is both HBM-DMA-sliceable (last dim 128)
            # and broadcastable over the Hkv sublane dim without a Mosaic
            # relayout (a packed [Hkv, Bk] tile is neither).
            kq = kbuf[slot].astype(cdt) * ksbuf[slot][:, None, :, :]
            vq = vbuf[slot].astype(cdt) * vsbuf[slot][:, None, :, :]
            k = kq.transpose(1, 0, 2, 3).reshape(hkv, gsz, d)
            v = vq.transpose(1, 0, 2, 3).reshape(hkv, gsz, d)
        else:
            k = kbuf[slot].transpose(1, 0, 2, 3).reshape(hkv, gsz, d).astype(cdt)
            v = vbuf[slot].transpose(1, 0, 2, 3).reshape(hkv, gsz, d).astype(cdt)
        scores = lax.dot_general(
            qf, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                         # [Hkv, qpk, gsz]
        col = i * gsz + lax.broadcasted_iota(jnp.int32, (hkv, qpk, gsz), 2)
        valid = (col < kv_len) & (col <= pos)
        if window is not None:
            valid &= col > pos - window
        scores = jnp.where(valid, scores, _NEG_INF)

        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1))   # [Hkv, qpk]
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(scores - m_new[..., None])
        probs = jnp.where(valid, probs, 0.0)
        l_new = l_prev * alpha + jnp.sum(probs, axis=-1)
        # P·V in the pool dtype (f32 accumulation): bf16 probs is the
        # standard flash-attention trade — error is bounded by the softmax
        # normalization and the parity tests hold at bf16 tolerance
        acc_new = acc_scr[...] * alpha[..., None] + lax.dot_general(
            probs.astype(cdt), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                 # [Hkv, qpk, D]
        m_scr[...], l_scr[...], acc_scr[...] = m_new, l_new, acc_new

        # last live group of this sequence: normalize and emit
        @pl.when(i == ng_b - 1)
        def _():
            safe_l = jnp.where(l_new > 0, l_new, 1.0)[..., None]
            # minor-dim insertion on i1 vectors is unsupported by Mosaic —
            # expand the f32 operand and compare after
            out = jnp.where(safe_l > 0, acc_new / safe_l, 0.0)
            out = jnp.where(l_new[..., None] > 0, out, 0.0)
            out_ref[0, 0] = out.reshape(nh, d).astype(out_ref.dtype)


def _call_decode_kernel(
    q: jax.Array,             # [B, 1, Nh, D]
    new_k: jax.Array,         # [B, Hkv, D]
    new_v: jax.Array,
    k_pool: jax.Array,        # [L, N, Hkv, Bk, D] stacked pools
    v_pool: jax.Array,
    layer_idx: jax.Array,     # scalar int32
    block_tables: jax.Array,  # [B, M] int32
    positions: jax.Array,     # [B] int32 query positions (-1 = inactive)
    write_positions: jax.Array,  # [B] int32 (-1 = no write)
    kv_lens: jax.Array,       # [B] int32
    block_size: int,
    window: Optional[int],
    fused_write: bool,
    interpret: bool,
    k_scale: Optional[jax.Array] = None,   # [L, N, Bk, D] bf16 lane-replicated
    v_scale: Optional[jax.Array] = None,   # (int8 pools; see paged_attention_pallas)
) -> Tuple[jax.Array, ...]:
    # → (out, k_pool, v_pool) — plus (k_scale, v_scale) when quantized
    b, s, nh, d = q.shape
    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "int8-KV pools need BOTH k_scale and v_scale (or neither): a "
            "lone scale would silently treat the other pool's raw int8 "
            "codes as real values"
        )
    quantized = k_scale is not None
    if s != 1:
        raise ValueError("pallas paged attention is the decode (S=1) kernel")
    if d % 128 != 0 and not interpret:
        # XLA:TPU pads HBM arrays to 128 lanes; a page slice of a narrower
        # head_dim is not expressible without relayout — dispatch keeps such
        # models on the XLA path (ops/attention.py impl="auto")
        raise ValueError(f"pallas decode kernel needs head_dim % 128 == 0, got {d}")
    L, n, hkv, bk, _ = k_pool.shape
    if bk != block_size:
        raise ValueError(f"pool block dim {bk} != block_size {block_size}")
    m = block_tables.shape[1]
    # write staging: up to `b` pages per pool, capped so 2 pools of staging
    # never take more than half the VMEM budget (rows are chunked through
    # the staging pages when b exceeds the cap). int8 pools stage a bf16
    # [Bk, D] scale page per data page (buffers AND staging), which at
    # MQA-ish hkv rivals the int8 page itself — count it.
    scale_page_bytes = block_size * d * 2 if quantized else 0
    page_bytes = hkv * block_size * d * k_pool.dtype.itemsize \
        + scale_page_bytes
    if fused_write:
        n_stage = max(1, min(b, _VMEM_BUDGET_BYTES // 2 // (2 * page_bytes)))
    else:
        n_stage = 1
    gp = _pages_per_group(
        block_size, hkv, d, k_pool.dtype.itemsize, m,
        staging_pages=2 * n_stage, scale_page_bytes=scale_page_bytes,
    )
    max_groups = -(-m // gp)

    in_specs = [
        pl.BlockSpec(
            (1, 1, nh, d),
            lambda i, j, *_refs: (i, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(memory_space=pltpu.VMEM),   # new_k (whole array)
        pl.BlockSpec(memory_space=pltpu.VMEM),   # new_v
        # pools must STAY in HBM (ANY lets the compiler pull the whole
        # pool into VMEM, where the padded lane dim breaks page slices)
        pl.BlockSpec(memory_space=_HBM),
        pl.BlockSpec(memory_space=_HBM),
    ]
    scratch = [
        pltpu.VMEM((2, gp, hkv, block_size, d), k_pool.dtype),
        pltpu.VMEM((2, gp, hkv, block_size, d), v_pool.dtype),
    ]
    out_specs = [
        pl.BlockSpec(
            (1, 1, nh, d),
            lambda i, j, *_refs: (i, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(memory_space=_HBM),
        pl.BlockSpec(memory_space=_HBM),
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec(memory_space=_HBM),   # k_scale
            pl.BlockSpec(memory_space=_HBM),   # v_scale
        ]
        out_specs += [
            pl.BlockSpec(memory_space=_HBM),   # k_scale (aliased)
            pl.BlockSpec(memory_space=_HBM),   # v_scale (aliased)
        ]
        scratch += [
            pltpu.VMEM((2, gp, block_size, d), jnp.bfloat16),    # ksbuf
            pltpu.VMEM((2, gp, block_size, d), jnp.bfloat16),    # vsbuf
        ]
    scratch += [pltpu.SemaphoreType.DMA((2, 2, gp))]             # sems
    if quantized:
        scratch += [pltpu.SemaphoreType.DMA((2, 2, gp))]         # ssems
    scratch += [
        pltpu.SemaphoreType.DMA((4 if quantized else 2, b)),     # wsems
        pltpu.VMEM((n_stage, hkv, block_size, d), k_pool.dtype),
        pltpu.VMEM((n_stage, hkv, block_size, d), v_pool.dtype),
    ]
    if quantized:
        scratch += [
            pltpu.VMEM((n_stage, block_size, d), jnp.bfloat16),  # wks_stage
            pltpu.VMEM((n_stage, block_size, d), jnp.bfloat16),  # wvs_stage
        ]
    scratch += [
        pltpu.VMEM((hkv, nh // hkv), jnp.float32),
        pltpu.VMEM((hkv, nh // hkv), jnp.float32),
        pltpu.VMEM((hkv, nh // hkv, d), jnp.float32),
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7,
        grid=(b, max_groups),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _decode_kernel,
        batch=b,
        block_size=block_size,
        pages_per_group=gp,
        max_pages=m,
        window=window,
        scale=d**-0.5,
        fused_write=fused_write,
        quantized=quantized,
    )
    operands = [
        block_tables.astype(jnp.int32),
        kv_lens.astype(jnp.int32),
        positions.astype(jnp.int32),
        write_positions.astype(jnp.int32),
        jnp.asarray(layer_idx, jnp.int32).reshape(1),
        jnp.zeros((1,), jnp.int32),   # buffer_index
        jnp.ones((1,), jnp.int32),    # init_flag
        q, new_k, new_v, k_pool, v_pool,
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, 1, nh, d), q.dtype),
        jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
        jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
    ]
    # operand order: 7 scalar-prefetch args, then q, new_k, new_v,
    # k_pool (idx 10), v_pool (idx 11) → aliased to outputs 1, 2;
    # quantized adds scale pools (idx 12, 13) aliased to outputs 3, 4 so
    # the fused write's quantization scales land in place
    aliases = {10: 1, 11: 2}
    if quantized:
        operands += [k_scale.astype(jnp.bfloat16),
                     v_scale.astype(jnp.bfloat16)]
        out_shape += [
            jax.ShapeDtypeStruct(k_scale.shape, jnp.bfloat16),
            jax.ShapeDtypeStruct(v_scale.shape, jnp.bfloat16),
        ]
        aliases.update({12: 3, 13: 4})
    results = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        input_output_aliases=aliases,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return results  # (out, k, v[, k_scale, v_scale])


def paged_decode_attention_fused(
    q: jax.Array,             # [B, 1, Nh, D]
    new_k: jax.Array,         # [B, 1, Hkv, D] this step's K rows
    new_v: jax.Array,
    k_pool: jax.Array,        # [L, N, Hkv, Bk, D] stacked pools
    v_pool: jax.Array,
    layer_idx: jax.Array,     # scalar int32
    block_tables: jax.Array,  # [B, M] int32
    positions: jax.Array,     # [B, 1] int32 (-1 = inactive); ALSO the write
                              # position of the new row
    kv_lens: jax.Array,       # [B] int32, INCLUDING the written token
    block_size: int = 16,
    window: Optional[int] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,   # [L, N, Bk, D] bf16 (int8 pools)
    v_scale: Optional[jax.Array] = None,
):
    """The per-layer decode step: write this step's K/V rows into their page
    slots AND attend over the updated paged context, in one kernel with the
    pools aliased in place. → (attn [B, 1, Nh, D], k_pool, v_pool) — plus
    (k_scale, v_scale) when the pools are int8 (the kernel quantizes the
    new rows in place and the step's scales ride the aliased scale
    pools)."""
    pos = positions[:, 0]
    return _call_decode_kernel(
        q, new_k[:, 0], new_v[:, 0], k_pool, v_pool, layer_idx,
        block_tables, pos, pos, kv_lens, block_size, window,
        fused_write=True, interpret=interpret,
        k_scale=k_scale, v_scale=v_scale,
    )


def quantize_kv_pool(pool: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """bf16/f32 pool [N, Hkv, Bk, D] → (int8 pool, [N, Bk, D] bf16 scales).

    The STORAGE layout of the int8-KV kernel path (tests and benchmarks
    import it so it cannot drift): one scale per (page, token), amax over
    (Hkv, D) shared across KV heads, stored lane-replicated over D as
    bf16; real = int * scale. The scalar contract itself lives in
    ``_quantize_token_rows`` — shared with the kernel's fused token
    write."""
    n, _, bk, d = pool.shape
    q, scale = _quantize_token_rows(pool.astype(jnp.float32), (1, 3))
    return q, jnp.broadcast_to(
        scale[:, 0, :, 0, None].astype(jnp.bfloat16), (n, bk, d)
    )


# --------------------------------------------------------------------------
# Ragged paged attention: one kernel invocation over a flattened row batch
# where decode rows (q_len = 1), speculative verify rows (q_len = 2..K+1)
# and prefill chunk rows (q_len up to the chunk width) coexist — the
# serving-side unification that lets admission append rows to a decode
# round instead of scheduling a competing prefill dispatch (Ragged Paged
# Attention, PAPERS.md). Since round 8 the verify-row shape is a SERVING
# path, not just a tested one: a spec-integrated engine's ragged_round
# dispatches its draft chains here as q_len = K+1 rows (contiguous
# positions lens..lens+K, per-row in-length bound lens+K+1), mixed with
# chunk rows — int8 pools dequant in-kernel on the same read, which is
# what lifted the models/llama.py int8 verify fence.
# --------------------------------------------------------------------------

# ceiling on (GQA queries per KV head) x (query tile) per grid cell: bounds
# the f32 score tile [Hkv, qpk*T, group] and the accumulator scratch so a
# wide prefill chunk never blows VMEM. Rows longer than the tile split into
# independent q-tiles (softmax state is per query, so tiles never talk);
# pages re-stage once per TILE, not once per query — the fix for the old
# multi-query path's per-query re-staging that capped it at q_len <= 8.
_RAGGED_QPK_TILE = 256


def _ragged_q_tile(s: int, qpk: int) -> int:
    t = max(1, min(s, _RAGGED_QPK_TILE // max(qpk, 1)))
    return 1 << (t.bit_length() - 1)     # power of two so buckets divide


def _ragged_kernel(
    # scalar prefetch (SMEM; bidx/init persist across the sequential grid)
    bt_ref,        # [B, M] int32 per-SEQUENCE block tables (q-tile rows of
                   # one sequence share its table: row // q_tiles indexes it
                   # — repeating the table per tile would multiply the SMEM
                   # footprint by the tile count, which at long-context
                   # table widths (32k = 2048 pages) is the difference
                   # between fitting and not)
    lens_ref,      # [B] int32 effective kv length per sequence
    qmax_ref,      # [R] int32 max valid query position (-1 = inactive row)
    qmin_ref,      # [R] int32 min valid query position (0 when inactive)
    bidx_ref,      # [1] int32 current double-buffer slot
    init_ref,      # [1] int32 1 until the first live chunk issues its DMA
    # blocked operands
    q_ref,         # [1, Hkv, qpk*T, D] — this row's query tile, GQA-grouped
    pos_ref,       # [1, T] int32 per-query positions (-1 = pad)
    k_hbm,         # [N, Hkv, Bk, D] single-layer pool (ANY/HBM)
    v_hbm,
    *rest,         # [ks_hbm, vs_hbm,] out_ref, kbuf, vbuf, [ksbuf, vsbuf,]
                   # sems, [ssems,] m_scr, l_scr, acc_scr
    rows: int,
    q_tiles: int,
    q_tile: int,
    block_size: int,
    pages_per_group: int,
    max_pages: int,
    window: Optional[int],
    scale: float,
    quantized: bool,
):
    if quantized:
        (_ks_in, _vs_in, out_ref, kbuf, vbuf, ksbuf, vsbuf,
         sems, ssems, m_scr, l_scr, acc_scr) = rest
        ks_hbm, vs_hbm = _ks_in, _vs_in
    else:
        (out_ref, kbuf, vbuf, sems, m_scr, l_scr, acc_scr) = rest
        ks_hbm = vs_hbm = ksbuf = vsbuf = ssems = None
    r = pl.program_id(0)
    i = pl.program_id(1)
    gp = pages_per_group
    gsz = gp * block_size
    hkv = k_hbm.shape[1]
    d = q_ref.shape[3]
    qpk = q_ref.shape[2] // q_tile
    max_groups = pl.num_programs(1)

    def num_groups(s_):
        s_ = jnp.clip(s_, 0, rows - 1)
        # a padded/inactive q-tile (qmax < 0) has zero live groups and its
        # grid cells skip in a few cycles — dead tiles of a short row in a
        # wide ragged batch cost nothing but the grid step
        needed = jnp.minimum(qmax_ref[s_] + 1, lens_ref[s_ // q_tiles])
        return jnp.minimum(pl.cdiv(needed, gsz), max_groups)

    def start_group(s_):
        if window is None:
            return jnp.int32(0)
        s_ = jnp.clip(s_, 0, rows - 1)
        return jnp.maximum(qmin_ref[s_] - window + 1, 0) // gsz

    ng_r = num_groups(r)
    start_r = start_group(r)
    live = (i >= start_r) & (i < ng_r)

    def start_dma(s_, j, slot):
        for p in range(gp):  # static unroll: G paired page DMAs
            idx = jnp.minimum(j * gp + p, max_pages - 1)
            page = bt_ref[jnp.clip(s_, 0, rows - 1) // q_tiles, idx]
            pltpu.make_async_copy(
                k_hbm.at[page], kbuf.at[slot, p], sems.at[0, slot, p]
            ).start()
            pltpu.make_async_copy(
                v_hbm.at[page], vbuf.at[slot, p], sems.at[1, slot, p]
            ).start()
            if quantized:
                pltpu.make_async_copy(
                    ks_hbm.at[page], ksbuf.at[slot, p], ssems.at[0, slot, p]
                ).start()
                pltpu.make_async_copy(
                    vs_hbm.at[page], vsbuf.at[slot, p], ssems.at[1, slot, p]
                ).start()

    def wait_dma(s_, j, slot):
        for p in range(gp):
            idx = jnp.minimum(j * gp + p, max_pages - 1)
            page = bt_ref[jnp.clip(s_, 0, rows - 1) // q_tiles, idx]
            pltpu.make_async_copy(
                k_hbm.at[page], kbuf.at[slot, p], sems.at[0, slot, p]
            ).wait()
            pltpu.make_async_copy(
                v_hbm.at[page], vbuf.at[slot, p], sems.at[1, slot, p]
            ).wait()
            if quantized:
                pltpu.make_async_copy(
                    ks_hbm.at[page], ksbuf.at[slot, p], ssems.at[0, slot, p]
                ).wait()
                pltpu.make_async_copy(
                    vs_hbm.at[page], vsbuf.at[slot, p], ssems.at[1, slot, p]
                ).wait()

    def next_chunk(s_, j):
        """Grid-order successor of live chunk (s_, j) — same walk as the
        decode kernel, over ragged rows instead of sequences."""

        def advance_row():
            def step(_, ss):
                return jnp.where(
                    (ss < rows) & (num_groups(ss) == 0), ss + 1, ss
                )

            ns = lax.fori_loop(0, rows, step, s_ + 1)
            return ns, jnp.where(ns < rows, start_group(ns), 0)

        return lax.cond(
            j + 1 < num_groups(s_), lambda: (s_, j + 1), advance_row
        )

    # inactive row (fully padded q-tile): its output block still writes once
    @pl.when((ng_r == 0) & (i == 0))
    def _():
        out_ref[0] = jnp.zeros((hkv, qpk * q_tile, d), out_ref.dtype)

    @pl.when(live)
    def _():
        slot = bidx_ref[0]

        @pl.when(init_ref[0] == 1)
        def _():
            start_dma(r, i, slot)

        init_ref[0] = 0

        nr, ni = next_chunk(r, i)

        @pl.when(nr < rows)
        def _():
            start_dma(nr, ni, 1 - slot)

        bidx_ref[0] = 1 - slot

        wait_dma(r, i, slot)

        @pl.when(i == start_r)
        def _():
            m_scr[...] = jnp.full((hkv, qpk * q_tile), _NEG_INF, jnp.float32)
            l_scr[...] = jnp.zeros((hkv, qpk * q_tile), jnp.float32)
            acc_scr[...] = jnp.zeros((hkv, qpk * q_tile, d), jnp.float32)

        kv_len = lens_ref[r // q_tiles]
        # the dot runs in the pool dtype (bf16 in, f32 accumulation) — the
        # same MXU contract as the decode kernel; int8 pages dequantize in
        # page layout during the upcast
        cdt = jnp.bfloat16 if kbuf.dtype.itemsize == 1 else kbuf.dtype
        qf = q_ref[0].astype(cdt)                         # [Hkv, qpk*T, D]
        if quantized:
            kq = kbuf[slot].astype(cdt) * ksbuf[slot][:, None, :, :]
            vq = vbuf[slot].astype(cdt) * vsbuf[slot][:, None, :, :]
            k = kq.transpose(1, 0, 2, 3).reshape(hkv, gsz, d)
            v = vq.transpose(1, 0, 2, 3).reshape(hkv, gsz, d)
        else:
            k = kbuf[slot].transpose(1, 0, 2, 3).reshape(hkv, gsz, d).astype(cdt)
            v = vbuf[slot].transpose(1, 0, 2, 3).reshape(hkv, gsz, d).astype(cdt)
        scores = lax.dot_general(
            qf, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                   # [Hkv, qpk*T, gsz]
        # per-query causal/in-length mask: split the flattened (qpk, T) row
        # axis (minor dim untouched — layout-free reshape), broadcast the
        # tile's position vector along it. THE per-row-group path selection:
        # a decode row (q_len = 1) and a prefill chunk row differ only in
        # this mask and in how many groups the walk gave them.
        scores4 = scores.reshape(hkv, qpk, q_tile, gsz)
        col = i * gsz + lax.broadcasted_iota(
            jnp.int32, (hkv, qpk, q_tile, gsz), 3
        )
        pos_b = pos_ref[0][None, None, :, None]     # [1, 1, T, 1]
        valid = (col < kv_len) & (col <= pos_b)
        if window is not None:
            valid &= col > pos_b - window
        scores4 = jnp.where(valid, scores4, _NEG_INF)

        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(
            m_prev, jnp.max(scores4, axis=-1).reshape(hkv, qpk * q_tile)
        )
        alpha = jnp.exp(m_prev - m_new)
        probs4 = jnp.exp(
            scores4 - m_new.reshape(hkv, qpk, q_tile)[..., None]
        )
        probs4 = jnp.where(valid, probs4, 0.0)
        l_new = l_prev * alpha + jnp.sum(probs4, axis=-1).reshape(
            hkv, qpk * q_tile
        )
        probs = probs4.reshape(hkv, qpk * q_tile, gsz)
        acc_new = acc_scr[...] * alpha[..., None] + lax.dot_general(
            probs.astype(cdt), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                           # [Hkv, qpk*T, D]
        m_scr[...], l_scr[...], acc_scr[...] = m_new, l_new, acc_new

        @pl.when(i == ng_r - 1)
        def _():
            safe_l = jnp.where(l_new > 0, l_new, 1.0)[..., None]
            out = jnp.where(safe_l > 0, acc_new / safe_l, 0.0)
            # fully-masked queries (padding inside a live tile) → exact 0,
            # the XLA-path contract
            out = jnp.where(l_new[..., None] > 0, out, 0.0)
            out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "window", "interpret"),
)
def ragged_paged_attention(
    q: jax.Array,             # [B, S, Nh, D] — per-row spans padded to S
    k_pool: jax.Array,        # [N, Hkv, Bk, D] (head-major pages, 1 layer)
    v_pool: jax.Array,
    block_tables: jax.Array,  # [B, M] int32
    positions: jax.Array,     # [B, S] int32 (-1 = pad)
    kv_lens: jax.Array,       # [B] int32 effective context per row
    block_size: int = 16,
    window: Optional[int] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,   # [N, Bk, D] bf16 lane-replicated
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Ragged paged attention: ONE kernel invocation over a flattened token
    batch in which each row carries its own (block table, query-span
    length, effective KV length). Decode rows (one valid query), spec
    verify rows (2..K+1) and prefill chunk rows (up to S) coexist in one
    grid; per-row bounds select each row's path inside the kernel — group
    walk length from ``min(max_pos + 1, kv_len)``, window start from the
    row's min position, causal masking per query. Masking semantics
    (causal, in-length, window, padded queries → exact zeros) are
    identical to ``paged_attention_xla`` over the same batch.

    Rows are split host-side into independent query tiles (softmax state
    is per query) sized so the f32 score tile stays inside VMEM; pages
    re-stage once per TILE — this replaces the old multi-query path, which
    re-staged pages once per QUERY and therefore capped q_len at 8."""
    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "int8-KV pools need BOTH k_scale and v_scale (or neither)"
        )
    quantized = k_scale is not None
    b, s, nh, d = q.shape
    n, hkv, bk, _ = k_pool.shape
    if bk != block_size:
        raise ValueError(f"pool block dim {bk} != block_size {block_size}")
    if d % 128 != 0 and not interpret:
        raise ValueError(
            f"ragged paged attention needs head_dim % 128 == 0, got {d}"
        )
    qpk = nh // hkv
    m = block_tables.shape[1]
    t = _ragged_q_tile(s, qpk)
    s_pad = -(-s // t) * t
    if s_pad != s:
        q = jnp.pad(q, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
        positions = jnp.pad(
            positions, ((0, 0), (0, s_pad - s)), constant_values=-1
        )
    qt = s_pad // t
    rows = b * qt
    # [B, S, Nh, D] → [R, Hkv, qpk*T, D] with the query index t fastest
    # inside each (kv-head, GQA-slot) group — the layout the kernel's one
    # batched MXU contraction per page group wants
    q_r = q.reshape(b, qt, t, hkv, qpk, d).transpose(0, 1, 3, 4, 2, 5) \
        .reshape(rows, hkv, qpk * t, d)
    pos_r = positions.reshape(rows, t).astype(jnp.int32)
    qmax_r = jnp.max(pos_r, axis=1)
    qmin_r = jnp.min(jnp.where(pos_r >= 0, pos_r, jnp.int32(2**30)), axis=1)
    qmin_r = jnp.where(qmax_r >= 0, qmin_r, 0)

    scale_page_bytes = block_size * d * 2 if quantized else 0
    gp = _pages_per_group(
        block_size, hkv, d, k_pool.dtype.itemsize, m,
        scale_page_bytes=scale_page_bytes,
    )
    max_groups = -(-m // gp)

    in_specs = [
        pl.BlockSpec(
            (1, hkv, qpk * t, d),
            lambda i, j, *_refs: (i, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, t), lambda i, j, *_refs: (i, 0), memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(memory_space=_HBM),   # k_pool
        pl.BlockSpec(memory_space=_HBM),   # v_pool
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec(memory_space=_HBM),   # k_scale
            pl.BlockSpec(memory_space=_HBM),   # v_scale
        ]
    out_specs = pl.BlockSpec(
        (1, hkv, qpk * t, d),
        lambda i, j, *_refs: (i, 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    scratch = [
        pltpu.VMEM((2, gp, hkv, block_size, d), k_pool.dtype),
        pltpu.VMEM((2, gp, hkv, block_size, d), v_pool.dtype),
    ]
    if quantized:
        scratch += [
            pltpu.VMEM((2, gp, block_size, d), jnp.bfloat16),    # ksbuf
            pltpu.VMEM((2, gp, block_size, d), jnp.bfloat16),    # vsbuf
        ]
    scratch += [pltpu.SemaphoreType.DMA((2, 2, gp))]             # sems
    if quantized:
        scratch += [pltpu.SemaphoreType.DMA((2, 2, gp))]         # ssems
    scratch += [
        pltpu.VMEM((hkv, qpk * t), jnp.float32),                 # m
        pltpu.VMEM((hkv, qpk * t), jnp.float32),                 # l
        pltpu.VMEM((hkv, qpk * t, d), jnp.float32),              # acc
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(rows, max_groups),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _ragged_kernel,
        rows=rows,
        q_tiles=qt,
        q_tile=t,
        block_size=block_size,
        pages_per_group=gp,
        max_pages=m,
        window=window,
        scale=d**-0.5,
        quantized=quantized,
    )
    # block tables and kv lens stay per-SEQUENCE ([B, M] / [B]): q-tile
    # rows index them via row // q_tiles inside the kernel. Repeating them
    # per tile (the old layout) multiplied the SMEM scalar-prefetch
    # footprint by the tile count — at 32k contexts (M = 2048 pages,
    # 2048-wide chunks → 32+ tiles) that is megabytes of SMEM tables for
    # kilobytes of real data
    operands = [
        block_tables.astype(jnp.int32), kv_lens.astype(jnp.int32),
        qmax_r, qmin_r,
        jnp.zeros((1,), jnp.int32),   # buffer_index
        jnp.ones((1,), jnp.int32),    # init_flag
        q_r, pos_r, k_pool, v_pool,
    ]
    if quantized:
        operands += [k_scale.astype(jnp.bfloat16),
                     v_scale.astype(jnp.bfloat16)]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, hkv, qpk * t, d), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    out = out.reshape(b, qt, hkv, qpk, t, d).transpose(0, 1, 4, 2, 3, 5) \
        .reshape(b, s_pad, nh, d)
    return out[:, :s]


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "window", "interpret"),
)
def paged_attention_pallas_multiquery(
    q: jax.Array,             # [B, S, Nh, D], small S (spec verify windows)
    k_pool: jax.Array,        # [N, Hkv, Bk, D] (head-major pages, 1 layer)
    v_pool: jax.Array,
    block_tables: jax.Array,  # [B, M] int32
    positions: jax.Array,     # [B, S] int32 (-1 = pad)
    kv_lens: jax.Array,       # [B] int32
    block_size: int = 16,
    window: Optional[int] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,   # [N, Bk, D] bf16 lane-replicated
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Small-q paged attention (speculative verify windows) — since round 6
    a thin alias of :func:`ragged_paged_attention` with uniform spans. The
    old implementation flattened every query into its own decode-kernel
    row, re-staging pages once per query, which capped q_len at 8; the
    ragged kernel stages pages once per query TILE, so the cap (and the
    separate dispatch path) is gone."""
    return ragged_paged_attention(
        q, k_pool, v_pool, block_tables, positions, kv_lens, block_size,
        window=window, interpret=interpret,
        k_scale=k_scale, v_scale=v_scale,
    )


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "window", "interpret"),
)
def paged_attention_pallas(
    q: jax.Array,             # [B, 1, Nh, D]
    k_pool: jax.Array,        # [N, Hkv, Bk, D] (head-major pages, 1 layer)
    v_pool: jax.Array,
    block_tables: jax.Array,  # [B, M] int32
    positions: jax.Array,     # [B, 1] int32 (-1 = inactive)
    kv_lens: jax.Array,       # [B] int32
    block_size: int = 16,
    window: Optional[int] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,   # [N, Bk, D] bf16 lane-replicated
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Read-only single-layer variant (micro-benchmarks, parity tests, and
    callers that manage KV writes themselves).

    ``k_scale``/``v_scale`` activate the int8-KV path: pools hold int8 rows
    with one scale per (page, token) — shared across KV heads, stored
    LANE-REPLICATED over D as bf16 (real = int * scale). That layout is
    what HBM DMA slicing and the Mosaic broadcast both accept; it costs
    +25% over pure int8 bytes, i.e. HBM sees ~62% of the bf16 bytes per
    token and page capacity is ~1.6x at equal pool bytes (VERDICT r3 #4)."""
    b, _, nh, d = q.shape
    hkv = k_pool.shape[1]
    zeros = jnp.zeros((b, hkv, d), jnp.bfloat16)
    results = _call_decode_kernel(
        q, zeros, zeros, k_pool[None], v_pool[None], jnp.int32(0),
        block_tables, positions[:, 0],
        jnp.full((b,), -1, jnp.int32),   # no writes
        kv_lens, block_size, window,
        fused_write=False, interpret=interpret,
        k_scale=None if k_scale is None else k_scale[None],
        v_scale=None if v_scale is None else v_scale[None],
    )
    return results[0]
