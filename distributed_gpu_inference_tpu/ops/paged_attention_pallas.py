"""Pallas TPU paged-attention decode kernel.

First-party replacement for vLLM's PagedAttention CUDA kernel (SURVEY §2.3).
Decode (S = 1) is HBM-bandwidth-bound: the XLA fallback in ``ops/attention.py``
materializes a gathered ``[B, J, Hkv, D]`` context (one full extra HBM
round-trip over the whole padded table width M), while this kernel

- walks only the **live** pages of each sequence (``fori_loop`` bound is the
  traced ``ceil(kv_len / group)``, not the static table width),
- DMAs each KV page HBM→VMEM exactly once (whole ``[Hkv, Bk, D]`` pages —
  a full-suffix slice stays contiguous, so no TPU-tiling constraint is hit)
  and runs flash-style online softmax accumulation per page group,
- skips page groups entirely behind a sliding window (Mistral), starting
  the walk at the window's first live group,
- computes every (kv-head, GQA-query-group) in one batched MXU contraction
  per group.

Correctness contract is identical to ``paged_attention_xla`` (same masking
semantics, including window and padded-query handling); the parametrized
parity tests drive both through the same cases (CPU: interpret mode).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _pages_per_group(block_size: int) -> int:
    """Pages DMA'd per loop iteration — targets 512-token groups: the
    fori_loop has a fixed per-iteration cost (semaphore waits, scalar loop
    bookkeeping) of ~2us on v5e, so groups must be large enough to amortize
    it against the ~0.6us/128-token HBM transfer."""
    return max(1, 512 // block_size)


def _decode_kernel(
    # scalar prefetch
    bt_ref,        # [B, M] int32 block tables
    lens_ref,      # [B] int32 kv lengths
    pos_ref,       # [B] int32 query positions (kv_len - 1; <0 = inactive)
    # blocked operands
    q_ref,         # [1, 1, Nh, D] — this sequence's query heads
    k_hbm,         # [N, Hkv, Bk, D] full pool (ANY/HBM)
    v_hbm,         # [N, Hkv, Bk, D]
    out_ref,       # [1, 1, Nh, D]
    # scratch
    kbuf,          # VMEM [2, G, Hkv, Bk, D] (double-buffered)
    vbuf,          # VMEM [2, G, Hkv, Bk, D]
    sems,          # DMA semaphores [2, 2, G]
    *,
    block_size: int,
    max_pages: int,
    window: Optional[int],
    scale: float,
):
    ib = pl.program_id(0)
    kv_len = lens_ref[ib]
    pos = pos_ref[ib]
    gp = _pages_per_group(block_size)
    gsz = gp * block_size
    nh, d = q_ref.shape[2], q_ref.shape[3]
    hkv = k_hbm.shape[1]
    qpk = nh // hkv

    # [Hkv, qpk, D] — GQA head h = g*qpk + j belongs to kv head g
    qf = q_ref[0, 0].astype(jnp.float32).reshape(hkv, qpk, d) * scale

    num_groups = pl.cdiv(kv_len, gsz)                     # traced bound
    if window is not None:
        # first visible key = max(0, pos - window + 1) → its group
        start = jnp.maximum(pos - window + 1, 0) // gsz
    else:
        start = jnp.int32(0)

    def _group_copies(j, slot):
        """The (deterministic) DMA descriptors of group j into buffer slot."""
        out = []
        for p in range(gp):  # static unroll: G paired page DMAs
            idx = jnp.minimum(j * gp + p, max_pages - 1)  # clamp, mask later
            page = bt_ref[ib, idx]
            # whole-page slice [Hkv, Bk, D]: contiguous, tiling-safe
            out.append((
                pltpu.make_async_copy(
                    k_hbm.at[page], kbuf.at[slot, p], sems.at[0, slot, p]
                ),
                pltpu.make_async_copy(
                    v_hbm.at[page], vbuf.at[slot, p], sems.at[1, slot, p]
                ),
            ))
        return out

    def _start(j, slot):
        for dk, dv in _group_copies(j, slot):
            dk.start()
            dv.start()

    # prologue: prefetch the first group
    @pl.when(start < num_groups)
    def _():
        _start(start, jax.lax.rem(start, 2))

    def group_step(j, carry):
        m_prev, l_prev, acc = carry
        slot = jax.lax.rem(j, 2)
        # overlap: launch group j+1 into the other buffer before waiting
        @pl.when(j + 1 < num_groups)
        def _():
            _start(j + 1, jax.lax.rem(j + 1, 2))
        for dk, dv in _group_copies(j, slot):
            dk.wait()
            dv.wait()

        # [G, Hkv, Bk, D] → [Hkv, G*Bk, D] (leading-dim relabel, no relayout)
        k = kbuf[slot].astype(jnp.float32).transpose(1, 0, 2, 3).reshape(hkv, gsz, d)
        v = vbuf[slot].astype(jnp.float32).transpose(1, 0, 2, 3).reshape(hkv, gsz, d)
        scores = jax.lax.dot_general(
            qf, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                 # [Hkv, qpk, gsz]
        col = j * gsz + jax.lax.broadcasted_iota(
            jnp.int32, (hkv, qpk, gsz), 2
        )
        valid = (col < kv_len) & (col <= pos)
        if window is not None:
            valid &= col > pos - window
        scores = jnp.where(valid, scores, _NEG_INF)

        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1))   # [Hkv, qpk]
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(scores - m_new[..., None])
        probs = jnp.where(valid, probs, 0.0)
        l_new = l_prev * alpha + jnp.sum(probs, axis=-1)
        acc_new = acc * alpha[..., None] + jax.lax.dot_general(
            probs, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                 # [Hkv, qpk, D]
        return m_new, l_new, acc_new

    m0 = jnp.full((hkv, qpk), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((hkv, qpk), jnp.float32)
    a0 = jnp.zeros((hkv, qpk, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(start, num_groups, group_step, (m0, l0, a0))

    # inactive slot (kv_len 0) or fully-masked rows → exact zeros
    safe_l = jnp.where(l > 0, l, 1.0)
    out = jnp.where((l > 0)[..., None], acc / safe_l[..., None], 0.0)
    out_ref[0, 0] = out.reshape(nh, d).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "window", "interpret"),
)
def paged_attention_pallas(
    q: jax.Array,             # [B, 1, Nh, D]
    k_pool: jax.Array,        # [N, Hkv, Bk, D] (head-major pages)
    v_pool: jax.Array,
    block_tables: jax.Array,  # [B, M] int32
    positions: jax.Array,     # [B, 1] int32 (-1 = inactive)
    kv_lens: jax.Array,       # [B] int32
    block_size: int = 16,
    window: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    b, s, nh, d = q.shape
    if s != 1:
        raise ValueError("pallas paged attention is the decode (S=1) kernel")
    if d % 128 != 0 and not interpret:
        # XLA:TPU pads HBM arrays to 128 lanes; a page slice of a narrower
        # head_dim is not expressible without relayout — dispatch keeps such
        # models on the XLA path (ops/attention.py impl="auto")
        raise ValueError(f"pallas decode kernel needs head_dim % 128 == 0, got {d}")
    n, hkv, bk, _ = k_pool.shape
    if bk != block_size:
        raise ValueError(f"pool block dim {bk} != block_size {block_size}")
    m = block_tables.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=[
            pl.BlockSpec(
                (1, 1, nh, d),
                lambda i, *_refs: (i, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            # pools must STAY in HBM (ANY lets the compiler pull the whole
            # pool into VMEM, where the padded lane dim breaks page slices)
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, nh, d),
            lambda i, *_refs: (i, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM(
                (2, _pages_per_group(block_size), hkv, block_size, d),
                k_pool.dtype,
            ),
            pltpu.VMEM(
                (2, _pages_per_group(block_size), hkv, block_size, d),
                v_pool.dtype,
            ),
            pltpu.SemaphoreType.DMA((2, 2, _pages_per_group(block_size))),
        ],
    )
    kernel = functools.partial(
        _decode_kernel,
        block_size=block_size,
        max_pages=m,
        window=window,
        scale=d**-0.5,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, nh, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        kv_lens.astype(jnp.int32),
        positions[:, 0].astype(jnp.int32),
        q, k_pool, v_pool,
    )
