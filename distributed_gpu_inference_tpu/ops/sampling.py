"""On-device token sampling: greedy / temperature / top-k / top-p.

The reference delegates sampling to HF ``generate`` / vLLM SamplingParams
(``worker/engines/llm.py``, ``llm_vllm.py:190``); here it is a single jitted
function with *traced* per-sequence controls, so one compiled graph serves any
mix of greedy and sampled requests in a batch (no recompiles, no host sync).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _nucleus_logits(
    logits: jax.Array,        # [B, V] float32
    temperature: jax.Array,   # [B] float32; <= 0 → greedy
    top_k: jax.Array,         # [B] int32; <= 0 → disabled
    top_p: jax.Array,         # [B] float32; >= 1 → disabled
):
    """Shared top-k/top-p masking → (greedy_tok, nucleus_logits).

    ONE descending sort serves both filters: masking entries below the k-th
    largest value to -inf preserves the sorted order, so the top-p pass can
    reuse the same sorted array with an index mask instead of re-sorting the
    masked copy (a second [B, V] sort costs ~1.5 ms/step at Llama-3 vocab on
    v5e — measured round 2, the decode-path hotspot this fuses away)."""
    b, v = logits.shape
    logits = logits.astype(jnp.float32)

    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]          # desc [B, V]

    # top-k: threshold at the k-th largest logit (k<=0 → keep all)
    k = jnp.where(top_k <= 0, v, jnp.minimum(top_k, v)).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)  # [B,1]
    masked = jnp.where(scaled >= kth, scaled, _NEG_INF)

    # top-p (nucleus) over the top-k-masked distribution: sort(masked) desc
    # == sorted_logits with ranks >= k forced to -inf (order is preserved
    # under the threshold mask), so no second sort is needed
    rank = jnp.arange(v, dtype=jnp.int32)[None, :]
    sorted_masked = jnp.where(rank < k[:, None], sorted_logits, _NEG_INF)
    probs_sorted = jax.nn.softmax(sorted_masked, axis=-1)
    cumprobs = jnp.cumsum(probs_sorted, axis=-1)
    p = jnp.clip(top_p, 0.0, 1.0)[:, None]
    # keep tokens whose *preceding* cumulative mass is < p (always ≥ 1 token)
    keep_sorted = (cumprobs - probs_sorted) < p
    cutoff_count = jnp.sum(keep_sorted.astype(jnp.int32), axis=-1)       # [B]
    cutoff_val = jnp.take_along_axis(
        sorted_masked, jnp.maximum(cutoff_count - 1, 0)[:, None], axis=-1
    )
    nucleus = jnp.where(masked >= cutoff_val, masked, _NEG_INF)
    return greedy_tok, nucleus


def sample_tokens(
    logits: jax.Array,        # [B, V] float32
    key: jax.Array,           # ONE PRNG key for the whole batch
    temperature: jax.Array,   # [B] float32; <= 0 → greedy
    top_k: jax.Array,         # [B] int32; <= 0 → disabled
    top_p: jax.Array,         # [B] float32; >= 1 → disabled
) -> jax.Array:
    """Returns sampled token ids [B] int32. Fully traced — no Python branches."""
    greedy_tok, nucleus = _nucleus_logits(logits, temperature, top_k, top_p)
    sampled_tok = jax.random.categorical(key, nucleus, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy_tok, sampled_tok)


def sample_tokens_per_slot(
    logits: jax.Array,        # [B, V] float32
    slot_keys: jax.Array,     # [B, 2] uint32 — one PRNG key per request
    positions: jax.Array,     # [B] int32 — folded in so each step differs
    temperature: jax.Array,   # [B] float32; <= 0 → greedy
    top_k: jax.Array,         # [B] int32; <= 0 → disabled
    top_p: jax.Array,         # [B] float32; >= 1 → disabled
) -> jax.Array:
    """Per-request randomness: each slot samples from ITS OWN key (folded
    with the position), so a seeded request reproduces exactly regardless
    of which other requests share the batch — the serving guarantee a
    single batch-wide key cannot give."""
    greedy_tok, nucleus = _nucleus_logits(logits, temperature, top_k, top_p)

    def _one(k, pos, lg):
        return jax.random.categorical(jax.random.fold_in(k, pos), lg)

    sampled_tok = jax.vmap(_one)(
        slot_keys, positions, nucleus
    ).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy_tok, sampled_tok)


def compute_logprobs(logits: jax.Array, token_ids: jax.Array) -> jax.Array:
    """Log-probability of chosen tokens. logits [B, V], token_ids [B] → [B]."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, token_ids[:, None].astype(jnp.int32), axis=-1)[
        :, 0
    ]
