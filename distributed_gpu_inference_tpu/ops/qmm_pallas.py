"""Pallas TPU quantized matmul: int8 HBM reads, bf16 MXU compute in-kernel.

Decode is weight-bandwidth-bound: every step streams the full weight tree
through the MXU at trivial arithmetic intensity (M = batch rows). Storing
weights int8 halves the bytes, but the XLA convert-on-read path
(``ops/quantization.matmul``) does not reliably realize the saving — the
converted bf16 operand can be materialized (measured round 2: int8 decode at
~1.2x bf16 instead of the ~1.9x the byte ratio predicts). This kernel closes
the gap by doing the convert AFTER the HBM read, in VMEM:

- **Blocked operands**: weight tiles ``[BK, BN]`` are DMA'd HBM→VMEM as int8
  (half the bytes on the wire), converted to the activation dtype in VMEM,
  and contracted on the MXU with f32 accumulation.
- **Stacked weights + scalar-prefetch layer index**: like the paged-attention
  kernel (``ops/paged_attention_pallas.py``), the kernel takes the whole
  stacked ``[L, K, N]`` weight and a scalar ``layer_idx`` — a custom-call
  operand must be materialized, so passing a per-layer slice (what
  ``lax.scan`` over stacked params produces) would make XLA copy the slice
  every layer, every step, erasing the bandwidth win. The layer scan in
  ``models/llama.py`` closes over the stacked tree and scans the index.
- **Per-output-channel scales** are applied once to the f32 accumulator on
  the final K tile (scale commutes with the K-sum).

Reference analogue: the int8/AWQ CUDA kernels the reference reaches through
vLLM engine flags (``worker/engines/llm_vllm.py:83-87``); here the kernel is
first-party and TPU-shaped.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed the compiler-params dataclass TPUCompilerParams →
# CompilerParams across releases; resolve whichever this jax ships (same
# shim as ops/paged_attention_pallas.py) so import/trace never AttributeErrors
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

# Tile menu. BN/BK must divide N/K exactly (no ragged K/N tiles: an
# out-of-bounds K read would contract garbage into real outputs). The lane
# dim of every block must be a multiple of 128.
_BN_CHOICES = (512, 256, 128)
_BK_CHOICES = (2048, 1024, 512, 256, 128)

# Bandwidth-bound regime bound: above this many activation rows the matmul
# is MXU-bound and XLA's native path (with its better K-parallel scheduling)
# is the right tool; below it the weight stream dominates and int8-on-the-
# wire wins. Decode (M = batch) and tree-verify (M = batch * nodes) qualify.
_MAX_ROWS = 256


def pick_tiles(k: int, n: int) -> Optional[tuple]:
    bn = next((t for t in _BN_CHOICES if n % t == 0), None)
    bk = next((t for t in _BK_CHOICES if k % t == 0), None)
    if bn is None or bk is None:
        return None
    return bk, bn


def _qmm_kernel(idx_ref, x_ref, qw_ref, scale_ref, o_ref, acc_ref, *, num_k):
    kk = pl.program_id(1)

    @pl.when(kk == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += lax.dot(
        x_ref[...],
        qw_ref[0].astype(x_ref.dtype),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kk == num_k - 1)
    def _():
        # scale [1, BN] broadcasts over the M rows of the f32 accumulator
        o_ref[...] = (acc_ref[...] * scale_ref[0]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def qmm_stacked_pallas(
    x: jax.Array,          # [M, K] activations (bf16/f32)
    qw: jax.Array,         # [L, K, N] quantized weights (int8 / float8_e4m3fn)
    scale: jax.Array,      # [L, 1, N] float32 per-output-channel scales
    layer_idx: jax.Array,  # scalar int32 — which layer's weight to use
    *,
    interpret: bool = False,
) -> jax.Array:
    """``x @ dequant(qw[layer_idx])`` with the int8→bf16 convert in VMEM.

    Returns [M, N] in x.dtype. K and N must tile (see ``pick_tiles``); M is
    padded to the sublane tile internally.
    """
    m, k = x.shape
    l, k2, n = qw.shape
    if k != k2:
        raise ValueError(f"x K {k} != weight K {k2}")
    tiles = pick_tiles(k, n)
    if tiles is None:
        raise ValueError(f"untileable qmm shape K={k} N={n}")
    bk, bn = tiles

    sublane = 16 if x.dtype == jnp.bfloat16 else 8
    mp = -(-m // sublane) * sublane
    if mp != m:
        x = jnp.pad(x, ((0, mp - m), (0, 0)))

    num_n, num_k = n // bn, k // bk
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_n, num_k),
        in_specs=[
            pl.BlockSpec((mp, bk), lambda ni, ki, idx: (0, ki)),
            pl.BlockSpec((1, bk, bn), lambda ni, ki, idx: (idx[0], ki, ni)),
            pl.BlockSpec((1, 1, bn), lambda ni, ki, idx: (idx[0], 0, ni)),
        ],
        out_specs=pl.BlockSpec((mp, bn), lambda ni, ki, idx: (0, ni)),
        scratch_shapes=[pltpu.VMEM((mp, bn), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_qmm_kernel, num_k=num_k),
        out_shape=jax.ShapeDtypeStruct((mp, n), x.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            # out blocks are revisited across the K walk (accumulator), so K
            # must be sequential; N tiles are independent
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        jnp.asarray(layer_idx, jnp.int32).reshape(1),
        x,
        qw,
        scale.astype(jnp.float32),
    )
    return out[:m] if mp != m else out


def qmm_rows_ok(m: int) -> bool:
    """True when M rows is in the bandwidth-bound regime this kernel wins."""
    return m <= _MAX_ROWS
