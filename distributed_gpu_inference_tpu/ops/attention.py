"""Attention over paged KV: XLA gather-based implementation + dense reference.

This is the correctness-first fallback path (SURVEY §7 "needs a pure-XLA
fallback (gather-based) for correctness testing"); the Pallas TPU kernel in
``ops/paged_attention_pallas.py`` is selected automatically on TPU backends
for the hot decode path.

Semantics shared by every implementation:

- KV lives in a paged pool ``[num_blocks, n_kv_heads, block_size, head_dim]``
  per layer (head-major pages — a (page, head) slice is one contiguous
  [Bk, D] tile, the layout the Pallas kernel DMAs); a sequence's context is
  the concatenation of its block table's pages, valid up to ``kv_lens[b]``
  tokens.
- Queries carry explicit ``positions`` (``-1`` = padding); causal masking is
  positional: query at position p attends to context positions ``j <= p``.
- GQA: ``n_heads`` queries share ``n_kv_heads`` KV heads.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _use_pallas() -> bool:
    if os.environ.get("DGI_DISABLE_PALLAS"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


# Measured model-level crossover on v5e (llama3-3b, batch 8, round 2): the
# XLA gather path wins below ~450 padded context tokens (one fused
# gather+einsum beats per-layer pallas_call launch overhead when the whole
# table is a few pages); the Pallas kernel wins from ~650 up and by 1.3x+ at
# 1400+. The threshold is on the STATIC padded table width, so dispatch is
# trace-time and costs nothing.
_PALLAS_MIN_PADDED_CTX = 512
# Measured row-count crossover of the BARE (non-fused) decode read kernel
# vs the XLA gather (r5 wedge table, v5e): the kernel wins 3.4x at batch 8
# mixed lengths, loses 2-4x by batch 32 — per-row page staging scales with
# rows while one gather amortizes. 16 is the conservative boundary between
# the measured points. Serving's decode path never sees this (it reads
# through the FUSED write+attention kernel, whose staging the write pass
# already pays); only bare paged_attention() reads — micro-benches, adopted
# pools — cross over. Since round 6 the crossover lives HERE (resolve_impl
# applies it automatically from the static row count) instead of as a
# duplicated constant in benchmarks/paged_attention_micro.py.
_MICRO_READ_XLA_MIN_BATCH = 16


def micro_read_xla_min_batch() -> int:
    """The bare-read row-count crossover — the measured default, with the
    ``MICRO_READ_XLA_MIN_BATCH`` env var kept as an OVERRIDE only (re-tuning
    on new chip generations without a code change)."""
    raw = os.environ.get("MICRO_READ_XLA_MIN_BATCH", "")
    try:
        return max(1, int(raw))
    except ValueError:
        return _MICRO_READ_XLA_MIN_BATCH


def resolve_impl(
    q_seq: int,
    head_dim: int,
    padded_ctx: int,
    backend_is_tpu: Optional[bool] = None,
    rows: Optional[int] = None,
    fused: bool = True,
) -> str:
    """The implementation ``impl="auto"`` will select, from static shape
    facts alone: q_seq (chunk length), head_dim, the padded context
    capacity ``block_tables.shape[1] * block_size``, and the batch row
    count. Exposed so callers (bench.py, engines) can ASSERT the Pallas
    kernel is in the measured path instead of discovering a silent
    fallback after the fact (VERDICT r1 weak #1).

    q_seq > 1 resolves to ``ragged`` — the ragged paged-attention kernel
    serving mixed prefill-chunk / spec-verify / decode rows in ONE
    invocation (it replaced the q_len <= 8 ``pallas_mq`` path in round 6;
    per-row bounds select each row's path inside the kernel, so there is
    no small-q cap anymore).

    ``fused``: the caller reads through the fused write+attention decode
    kernel (the serving path) — row count never flips it. ``fused=False``
    is the bare read (micro-benches, externally-written pools): there the
    measured row-count crossover applies and ``rows`` at or above
    :func:`micro_read_xla_min_batch` falls back to the one-gather XLA path.
    """
    if backend_is_tpu is None:
        backend_is_tpu = _use_pallas()
    if (
        backend_is_tpu
        and head_dim % 128 == 0
        and padded_ctx >= _PALLAS_MIN_PADDED_CTX
    ):
        if q_seq == 1:
            if (
                not fused
                and rows is not None
                and rows >= micro_read_xla_min_batch()
            ):
                return "xla"
            return "pallas"
        return "ragged"
    return "xla"


def paged_attention(
    q: jax.Array,             # [B, S, Nh, D]
    k_pool: jax.Array,        # [N, Hkv, Bk, D] (single layer)
    v_pool: jax.Array,        # [N, Hkv, Bk, D]
    block_tables: jax.Array,  # [B, M] int32
    positions: jax.Array,     # [B, S] int32, -1 = pad
    kv_lens: jax.Array,       # [B] int32
    block_size: int = 16,
    impl: str = "auto",
    window: Optional[int] = None,  # Mistral sliding window (None = full causal)
    k_scale: Optional[jax.Array] = None,   # [N, Bk, D] bf16 — int8 pools
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Attention of a chunk of queries against paged context. → [B, S, Nh, D].

    ``impl``: "auto" (pallas on TPU for decode, ragged for multi-token
    spans, else xla), "xla", "pallas", "ragged" ("pallas_mq" accepted as a
    legacy alias of "ragged").
    ``window``: query at position p sees context positions (p-window, p].
    ``k_scale``/``v_scale``: int8 pools' per-(page, token) scales — both
    impls dequantize context-sized (Pallas in VMEM, XLA at the gather).
    """
    if impl == "auto":
        # the Pallas decode kernel needs lane-aligned pages: XLA:TPU stores
        # HBM arrays padded to 128 lanes, so a head_dim that isn't a
        # multiple of 128 cannot be page-DMA'd without relayout. All the
        # production geometries (Llama-3 8B/70B, Qwen-7B, Mistral, Gemma)
        # have D ∈ {128, 256}; CI-scale minis fall back to XLA. Small padded
        # tables also stay on XLA (see resolve_impl / the measured
        # crossover note above). This is the BARE read path (the fused
        # write+attention kernel dispatches inside models/llama.py), so the
        # row-count crossover applies.
        impl = resolve_impl(
            q_seq=q.shape[1],
            head_dim=q.shape[3],
            padded_ctx=block_tables.shape[1] * block_size,
            rows=q.shape[0],
            fused=False,
        )
    if impl == "pallas":
        from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
            paged_attention_pallas,
        )

        return paged_attention_pallas(
            q, k_pool, v_pool, block_tables, positions, kv_lens, block_size,
            window=window, k_scale=k_scale, v_scale=v_scale,
        )
    if impl in ("ragged", "pallas_mq"):
        # "pallas_mq" is the pre-round-6 name of the small-q path, kept as
        # an alias: the ragged kernel serves those shapes (and every other
        # mixed-span batch) without the old q_len <= 8 cap
        from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
            ragged_paged_attention,
        )

        return ragged_paged_attention(
            q, k_pool, v_pool, block_tables, positions, kv_lens, block_size,
            window=window, k_scale=k_scale, v_scale=v_scale,
        )
    return paged_attention_xla(
        q, k_pool, v_pool, block_tables, positions, kv_lens, block_size,
        window=window, k_scale=k_scale, v_scale=v_scale,
    )


def dequantize_kv(codes: jax.Array, scale: jax.Array) -> jax.Array:
    """THE int8-KV dequant arithmetic: bf16 cast of BOTH operands, then
    multiply. Every reader of int8 pages — the XLA gather here, the
    seq-sharded shard_map locals (``parallel/ring_attention.py``), and the
    dense prefill roundtrip (``models/llama._layer_step``) — must produce
    bit-identical reals from the same (codes, scale), so the arithmetic
    lives in exactly one place. ``scale`` must already broadcast against
    ``codes``."""
    return codes.astype(jnp.bfloat16) * scale.astype(jnp.bfloat16)


def _gather_ctx(
    pool: jax.Array, block_tables: jax.Array, block_size: int,
    scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Materialize a batch's paged context: head-major pool [N, Hkv, Bk, D]
    gathered by [B, M] tables → [B, J, Hkv, D] token-major context.

    ``scale`` ([N, Bk, D] bf16, int8 pools): the per-(page, token) scales
    gather alongside and dequantize the CONTEXT-sized result — never the
    whole pool (a full-pool dequant copy would be GBs at serving sizes)."""
    b, m = block_tables.shape
    _, hkv, _, d = pool.shape
    ctx = jnp.take(pool, block_tables, axis=0).transpose(
        0, 1, 3, 2, 4
    ).reshape(b, m * block_size, hkv, d)
    if scale is None:
        return ctx
    s_ctx = jnp.take(scale, block_tables, axis=0).reshape(
        b, m * block_size, d
    )
    return dequantize_kv(ctx, s_ctx[:, :, None, :])


def paged_attention_xla(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    kv_lens: jax.Array,
    block_size: int = 16,
    window: Optional[int] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    b, s, nh, d = q.shape
    hkv = k_pool.shape[1]
    qpk = nh // hkv
    m = block_tables.shape[1]
    j = m * block_size

    k_ctx = _gather_ctx(k_pool, block_tables, block_size, k_scale)
    v_ctx = _gather_ctx(v_pool, block_tables, block_size, v_scale)

    qg = q.reshape(b, s, hkv, qpk, d).astype(jnp.float32)
    scores = jnp.einsum(
        "bsgqd,bjgd->bgqsj", qg, k_ctx.astype(jnp.float32)
    ) * (d**-0.5)

    key_pos = jnp.arange(j, dtype=jnp.int32)[None, :]           # [1, J]
    causal = positions[:, :, None] >= key_pos[:, None, :]       # [B, S, J]
    in_len = key_pos[:, None, :] < kv_lens[:, None, None]       # [B, 1→S, J]
    visible = causal & in_len
    if window is not None:  # Mistral SWA: key must be within (p-window, p]
        visible &= key_pos[:, None, :] > positions[:, :, None] - window
    mask = visible[:, None, None, :, :]                         # [B,1,1,S,J]
    scores = jnp.where(mask, scores, _NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    # fully-masked rows (padded queries) → softmax of -inf row ≈ uniform junk;
    # zero them so padded outputs are exactly 0.
    any_valid = jnp.any(mask, axis=-1, keepdims=True)
    probs = jnp.where(any_valid, probs, 0.0)

    out = jnp.einsum("bgqsj,bjgd->bsgqd", probs, v_ctx.astype(jnp.float32))
    return out.reshape(b, s, nh, d).astype(q.dtype)


def paged_tree_attention(
    q: jax.Array,             # [B, N, Nh, D] — one query per tree node
    k_pool: jax.Array,        # [Nb, Hkv, Bk, D]
    v_pool: jax.Array,
    block_tables: jax.Array,  # [B, M]
    prefix_lens: jax.Array,   # [B] committed context BEFORE the tree chunk
    tree_mask: jax.Array,     # [N, N] bool — node i may attend node j (ancestors)
    block_size: int = 16,
    node_positions: Optional[jax.Array] = None,  # [B, N] semantic positions
    window: Optional[int] = None,                # Mistral SWA over the prefix
    k_scale: Optional[jax.Array] = None,         # [Nb, Bk, D] — int8 pools
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Attention for speculative tree verification.

    The N tree-node KVs are written at *cache positions* ``prefix_len + i``
    (node index, NOT semantic depth — siblings share a depth but need distinct
    slots). Masking: every node sees the committed prefix; within the chunk,
    node i sees node j iff ``tree_mask[i, j]`` (ancestor chain, reference
    ``worker/engines/speculative.py:184-213`` get_tree_attention_mask).
    """
    b, n, nh, d = q.shape
    hkv = k_pool.shape[1]
    qpk = nh // hkv
    m = block_tables.shape[1]
    j = m * block_size

    k_ctx = _gather_ctx(k_pool, block_tables, block_size, k_scale)
    v_ctx = _gather_ctx(v_pool, block_tables, block_size, v_scale)

    qg = q.reshape(b, n, hkv, qpk, d).astype(jnp.float32)
    scores = jnp.einsum("bsgqd,bjgd->bgqsj", qg, k_ctx.astype(jnp.float32)) * (
        d**-0.5
    )

    key_pos = jnp.arange(j, dtype=jnp.int32)[None, :]                # [1, J]
    is_prefix = key_pos[:, None, :] < prefix_lens[:, None, None]     # [B, 1, J]
    chunk_idx = key_pos[:, None, :] - prefix_lens[:, None, None]     # [B, 1, J]
    in_chunk = (chunk_idx >= 0) & (chunk_idx < n)
    safe_idx = jnp.clip(chunk_idx, 0, n - 1)                         # [B, 1, J]
    # tree_mask lookup per (query node, chunk key)
    tm = jnp.take_along_axis(
        jnp.broadcast_to(tree_mask[None, :, :], (b, n, n)),
        jnp.broadcast_to(safe_idx, (b, n, j)).astype(jnp.int32),
        axis=2,
    )                                                                # [B, N, J]
    if window is not None and node_positions is not None:
        # prefix keys beyond the node's window drop out by ABSOLUTE
        # position; within-chunk keys window by SEMANTIC node position
        # (prefix + depth — cache slots are node-indexed, so the raw
        # key_pos of a chunk key says nothing about its distance). Deep
        # trees on tiny windows (Mistral-class SWA, VERDICT r5 #5) thus
        # mask exactly like the sequential engine would: an ancestor more
        # than ``window`` semantic steps up is invisible.
        is_prefix &= (
            key_pos[:, None, :] > node_positions[:, :, None] - window
        )
        key_node_pos = jnp.take_along_axis(
            jnp.broadcast_to(node_positions[:, None, :], (b, n, n)),
            jnp.broadcast_to(safe_idx, (b, n, j)).astype(jnp.int32),
            axis=2,
        )                                                            # [B, N, J]
        tm &= key_node_pos > node_positions[:, :, None] - window
    mask = is_prefix | (in_chunk & tm)
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    any_valid = jnp.any(mask[:, None, None, :, :], axis=-1, keepdims=True)
    probs = jnp.where(any_valid, probs, 0.0)
    out = jnp.einsum("bgqsj,bjgd->bsgqd", probs, v_ctx.astype(jnp.float32))
    return out.reshape(b, n, nh, d).astype(q.dtype)


def dense_causal_attention(
    q: jax.Array,   # [B, S, Nh, D]
    k: jax.Array,   # [B, S, Hkv, D]
    v: jax.Array,   # [B, S, Hkv, D]
    lengths: Optional[jax.Array] = None,  # [B] valid lengths
    window: Optional[int] = None,
) -> jax.Array:
    """Plain causal GQA attention over contiguous KV — the test oracle."""
    b, s, nh, d = q.shape
    hkv = k.shape[2]
    qpk = nh // hkv
    qg = q.reshape(b, s, hkv, qpk, d).astype(jnp.float32)
    scores = jnp.einsum("bsgqd,bjgd->bgqsj", qg, k.astype(jnp.float32)) * (
        d**-0.5
    )
    idx = jnp.arange(s, dtype=jnp.int32)
    mask = idx[None, :, None] >= idx[None, None, :]             # [1, S, J]
    if window is not None:
        mask = mask & (idx[None, None, :] > idx[None, :, None] - window)
    if lengths is not None:
        mask = mask & (idx[None, None, :] < lengths[:, None, None])
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bgqsj,bjgd->bsgqd", probs, v.astype(jnp.float32))
    return out.reshape(b, s, nh, d).astype(q.dtype)
