"""TPU-native weight-only quantization: int8 / fp8 with per-channel scales.

The reference exposes quantization purely as engine passthrough flags —
AWQ/GPTQ/FP8/INT8 strings handed to vLLM (``worker/engines/llm_vllm.py:83-87``)
and SGLang; the actual kernels live in those CUDA deps. Here quantization is
first-party and TPU-shaped:

- **Storage**: matmul weights live in HBM as int8 (or float8_e4m3) with a
  float32 per-output-channel scale — half the bytes, so a chip fits ~2x
  the model (or correspondingly more KV pages). That capacity win is the
  primary benefit today.
- **Compute**: the MXU consumes bf16. On the decode path (small activation
  row counts) the contraction runs through the Pallas kernel in
  ``ops/qmm_pallas.py``: int8 tiles are DMA'd HBM→VMEM and converted
  in-kernel, so HBM sees half the bytes. Everywhere else (prefill,
  CPU/tests) the convert is expressed inline in the XLA matmul — XLA can
  materialize the converted operand there, but those paths are
  compute-bound, not weight-bandwidth-bound.
- **Pytree shape**: a quantized weight is a sub-dict ``{"qw", "scale"}`` whose
  leaves both carry the stacked leading L axis, so ``lax.scan`` over layers,
  GSPMD sharding, and pipeline stage slicing all keep working unchanged.

``matmul(x, w)`` is the single dispatch point: models call it for every
projection and it transparently handles plain or quantized leaves.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

QUANT_MODES = ("int8", "fp8")

# weight leaves eligible for quantization (matmul weights only: norms, biases,
# and the embedding table stay high-precision)
QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
     # MoE expert weights (stacked [L, E, in, out]) share the same scheme;
     # the router projection stays high-precision — quantizing it perturbs
     # top-k expert selection far more than it saves in bytes
     "we_gate", "we_up", "we_down"}
)

_FP8_MAX = 448.0  # float8_e4m3 largest finite value


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "qw" in w and "scale" in w


def quantize_weight(w: jax.Array, mode: str) -> Dict[str, jax.Array]:
    """Symmetric per-output-channel quantization of ``w [..., in, out]``.

    Scale reduces the contraction axis (-2) only: shape ``[..., 1, out]`` —
    per layer (leading axes) and per output channel, the granularity that
    keeps GQA/MLP projections accurate without zero points.
    """
    if mode not in QUANT_MODES:
        raise ValueError(f"unknown quantization mode {mode!r}; use {QUANT_MODES}")
    wf = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    if mode == "int8":
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        qw = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    else:  # fp8
        scale = jnp.where(amax > 0, amax / _FP8_MAX, 1.0)
        qw = (wf / scale).astype(jnp.float8_e4m3fn)
    return {"qw": qw, "scale": scale.astype(jnp.float32)}


def dequantize(w: Dict[str, jax.Array], dtype: Any = jnp.float32) -> jax.Array:
    return (w["qw"].astype(jnp.float32) * w["scale"]).astype(dtype)


def _pallas_qmm_ok(m: int, k_dim: int, n: int, qdtype) -> bool:
    """Trace-time gate for the in-kernel-dequant Pallas matmul: TPU backend,
    int8 storage, a bandwidth-bound row count, and tileable K/N."""
    if os.environ.get("DGI_DISABLE_PALLAS"):
        return False
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:  # pragma: no cover
        return False
    from distributed_gpu_inference_tpu.ops import qmm_pallas

    return (
        qdtype in (jnp.int8, jnp.float8_e4m3fn)
        and qmm_pallas.qmm_rows_ok(m)
        and qmm_pallas.pick_tiles(k_dim, n) is not None
    )


def matmul(x: jax.Array, w: Any) -> jax.Array:
    """``x @ w`` where ``w`` is a plain array or a quantized sub-dict.

    Quantized decode-shaped calls go through the Pallas VMEM-dequant kernel
    (int8 on the HBM wire); otherwise convert-on-read matmul in x.dtype
    (bf16 on the MXU), then scale the output channels. The scale broadcast
    ``[..., 1, out]`` collapses against ``x @ qw``'s trailing [..., out].
    """
    if not is_quantized(w):
        return x @ w
    qw = w["qw"]
    if qw.ndim == 2:
        lead = x.shape[:-1]
        m = 1
        for d in lead:
            m *= d
        if _pallas_qmm_ok(m, qw.shape[0], qw.shape[1], qw.dtype):
            # single dispatch point: lift to a 1-layer stack
            return matmul_stacked(
                x, {"qw": qw[None], "scale": w["scale"][None]}, jnp.int32(0)
            )
    out = x @ qw.astype(x.dtype)
    # scale shape [..., 1, out] → drop the kept contraction axis for broadcast
    scale = jnp.squeeze(w["scale"], axis=-2).astype(jnp.float32)
    return (out.astype(jnp.float32) * scale).astype(x.dtype)


# weight keys large enough to be worth the stacked-scan treatment (the MoE
# expert weights route through the einsum combine instead)
STACKED_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}
)


def split_stacked_quant(layers: Dict[str, Any]):
    """Partition a stacked layer tree for the scan in ``models/llama.py``:
    quantized matmul weights are pulled OUT of the scan xs (so the Pallas
    kernel can take the whole stacked array + a layer index instead of a
    materialized per-layer slice) and everything else stays scanned.

    → (scanned_layers, stacked_or_None)
    """
    stacked = {
        k: v for k, v in layers.items()
        if k in STACKED_KEYS and is_quantized(v)
    }
    if not stacked:
        return layers, None
    scanned = {k: v for k, v in layers.items() if k not in stacked}
    return scanned, stacked


def matmul_stacked(x: jax.Array, w: Dict[str, jax.Array], layer_idx) -> jax.Array:
    """``x @ dequant(w[layer_idx])`` for a stacked quantized weight
    ``{"qw": [L, K, N], "scale": [L, 1, N]}`` — the scan-body entry point.

    Decode-shaped calls hit the Pallas kernel with the STACKED operand (no
    per-layer slice ever materializes); other shapes slice the layer and
    take the XLA convert-on-read path (equivalent HLO to scanning the
    weight as an xs leaf, so nothing regresses).
    """
    qw = w["qw"]
    _, k_dim, n = qw.shape
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    if _pallas_qmm_ok(m, k_dim, n, qw.dtype):
        from distributed_gpu_inference_tpu.ops.qmm_pallas import (
            qmm_stacked_pallas,
        )

        out = qmm_stacked_pallas(
            x.reshape(m, k_dim), qw, w["scale"], layer_idx
        )
        return out.reshape(*lead, n)
    sliced = {
        "qw": lax.dynamic_index_in_dim(qw, layer_idx, 0, keepdims=False),
        "scale": lax.dynamic_index_in_dim(
            w["scale"], layer_idx, 0, keepdims=False
        ),
    }
    return matmul(x, sliced)


def quantize_params(
    params: Dict[str, Any], mode: Optional[str], consume: bool = False
) -> Dict[str, Any]:
    """Quantize every eligible matmul weight in a model params pytree.

    Structure-preserving everywhere else; returns a new pytree. ``mode=None``
    is the identity.

    ``consume=True`` drops each source leaf's reference as soon as its
    quantized replacement exists (the input ``params['layers']`` dict is
    emptied). Peak HBM is then full-precision + ONE quantized leaf instead
    of full-precision + the whole quantized tree — the difference between
    fitting and OOM when cold-starting an int8 model near chip capacity.
    """
    if mode is None:
        return params
    out = dict(params)
    if consume:
        src = params["layers"]
        new_layers: Dict[str, Any] = {}
        for k in list(src.keys()):
            v = src.pop(k)
            if k in QUANT_KEYS and not is_quantized(v):
                new_layers[k] = quantize_weight(v, mode)
                # block so the source buffer is actually dead before the
                # next leaf allocates (lazy tunnel-side reclaim)
                jax.block_until_ready(
                    jax.tree.leaves(new_layers[k])[0]
                )
                del v
            else:
                new_layers[k] = v
        out["layers"] = new_layers
        return out
    out["layers"] = {
        k: (quantize_weight(v, mode)
            if (k in QUANT_KEYS and not is_quantized(v)) else v)
        for k, v in params["layers"].items()
    }
    return out


def param_bytes(params: Dict[str, Any]) -> int:
    """Total HBM bytes of a params pytree (quantized or not)."""
    return sum(
        leaf.dtype.itemsize * leaf.size for leaf in jax.tree.leaves(params)
    )
