"""TPU-native weight-only quantization: int8 / fp8 with per-channel scales.

The reference exposes quantization purely as engine passthrough flags —
AWQ/GPTQ/FP8/INT8 strings handed to vLLM (``worker/engines/llm_vllm.py:83-87``)
and SGLang; the actual kernels live in those CUDA deps. Here quantization is
first-party and TPU-shaped:

- **Storage**: matmul weights live in HBM as int8 (or float8_e4m3) with a
  float32 per-output-channel scale — half the bytes, so a chip fits ~2x
  the model (or correspondingly more KV pages). That capacity win is the
  primary benefit today.
- **Compute**: the MXU consumes bf16; the int8→bf16 convert is expressed
  inline in the matmul so XLA *can* fuse it into the operand read.
  Measured on v5e (2026-07), decode throughput is ≈ parity with bf16 —
  XLA materializes the converted operand rather than streaming it, so the
  bandwidth saving is not yet realized; a Pallas matmul kernel that
  converts in VMEM after the int8 HBM read is the designated upgrade path
  if decode speed (not capacity) is the goal.
- **Pytree shape**: a quantized weight is a sub-dict ``{"qw", "scale"}`` whose
  leaves both carry the stacked leading L axis, so ``lax.scan`` over layers,
  GSPMD sharding, and pipeline stage slicing all keep working unchanged.

``matmul(x, w)`` is the single dispatch point: models call it for every
projection and it transparently handles plain or quantized leaves.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

QUANT_MODES = ("int8", "fp8")

# weight leaves eligible for quantization (matmul weights only: norms, biases,
# and the embedding table stay high-precision)
QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
     # MoE expert weights (stacked [L, E, in, out]) share the same scheme;
     # the router projection stays high-precision — quantizing it perturbs
     # top-k expert selection far more than it saves in bytes
     "we_gate", "we_up", "we_down"}
)

_FP8_MAX = 448.0  # float8_e4m3 largest finite value


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "qw" in w and "scale" in w


def quantize_weight(w: jax.Array, mode: str) -> Dict[str, jax.Array]:
    """Symmetric per-output-channel quantization of ``w [..., in, out]``.

    Scale reduces the contraction axis (-2) only: shape ``[..., 1, out]`` —
    per layer (leading axes) and per output channel, the granularity that
    keeps GQA/MLP projections accurate without zero points.
    """
    if mode not in QUANT_MODES:
        raise ValueError(f"unknown quantization mode {mode!r}; use {QUANT_MODES}")
    wf = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    if mode == "int8":
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        qw = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    else:  # fp8
        scale = jnp.where(amax > 0, amax / _FP8_MAX, 1.0)
        qw = (wf / scale).astype(jnp.float8_e4m3fn)
    return {"qw": qw, "scale": scale.astype(jnp.float32)}


def dequantize(w: Dict[str, jax.Array], dtype: Any = jnp.float32) -> jax.Array:
    return (w["qw"].astype(jnp.float32) * w["scale"]).astype(dtype)


def matmul(x: jax.Array, w: Any) -> jax.Array:
    """``x @ w`` where ``w`` is a plain array or a quantized sub-dict.

    Quantized path: convert-on-read matmul in x.dtype (bf16 on the MXU),
    then scale the output channels. The scale broadcast ``[..., 1, out]``
    collapses against ``x @ qw``'s trailing [..., out].
    """
    if not is_quantized(w):
        return x @ w
    out = x @ w["qw"].astype(x.dtype)
    # scale shape [..., 1, out] → drop the kept contraction axis for broadcast
    scale = jnp.squeeze(w["scale"], axis=-2).astype(jnp.float32)
    return (out.astype(jnp.float32) * scale).astype(x.dtype)


def quantize_params(
    params: Dict[str, Any], mode: Optional[str], consume: bool = False
) -> Dict[str, Any]:
    """Quantize every eligible matmul weight in a model params pytree.

    Structure-preserving everywhere else; returns a new pytree. ``mode=None``
    is the identity.

    ``consume=True`` drops each source leaf's reference as soon as its
    quantized replacement exists (the input ``params['layers']`` dict is
    emptied). Peak HBM is then full-precision + ONE quantized leaf instead
    of full-precision + the whole quantized tree — the difference between
    fitting and OOM when cold-starting an int8 model near chip capacity.
    """
    if mode is None:
        return params
    out = dict(params)
    if consume:
        src = params["layers"]
        new_layers: Dict[str, Any] = {}
        for k in list(src.keys()):
            v = src.pop(k)
            if k in QUANT_KEYS and not is_quantized(v):
                new_layers[k] = quantize_weight(v, mode)
                # block so the source buffer is actually dead before the
                # next leaf allocates (lazy tunnel-side reclaim)
                jax.block_until_ready(
                    jax.tree.leaves(new_layers[k])[0]
                )
                del v
            else:
                new_layers[k] = v
        out["layers"] = new_layers
        return out
    out["layers"] = {
        k: (quantize_weight(v, mode)
            if (k in QUANT_KEYS and not is_quantized(v)) else v)
        for k, v in params["layers"].items()
    }
    return out


def param_bytes(params: Dict[str, Any]) -> int:
    """Total HBM bytes of a params pytree (quantized or not)."""
    return sum(
        leaf.dtype.itemsize * leaf.size for leaf in jax.tree.leaves(params)
    )
