"""Durable-IO hardening primitives (round 19): per-tier circuit breakers
and crash-atomic file writes.

The serving contract this module exists to enforce: an OPTIONAL durable
surface (spill tier, checkpoint sink, persisted config) can NEVER fail a
request. Failures are counted and fenced, never propagated:

- :class:`IOBreaker` is the classic closed → open → half-open machine,
  sized for a cache tier on the admission path: after ``threshold``
  consecutive failures the tier trips OPEN and is skipped entirely (no
  per-request timeout tax while the device browns out); after a jittered
  ``open_s`` window exactly ONE probe is let through (half-open) — success
  closes the breaker, failure re-opens it with fresh jitter. The jitter is
  seeded per-breaker so a fleet of workers doesn't hammer a recovering
  device in lockstep, and so tests can assert the exact probe instants.
- :func:`atomic_write_text` / :func:`atomic_write_bytes` implement the
  temp + fsync + rename discipline for every file this codebase persists
  (worker config, machine fingerprint, checkpoint files): a crash or a
  torn write mid-save leaves the OLD file intact, never a half-written
  one. Both consult the ``io.file.write`` chaos seam so seeded
  ``disk_full`` storms exercise the cleanup path.

Env knobs (read at breaker construction — docs/ENV_CONFIG.md):

=============================  =============================================
``DGI_IO_BREAKER_THRESHOLD``   consecutive failures before tripping (3)
``DGI_IO_BREAKER_OPEN_S``      base open window seconds before a probe (10)
``DGI_IO_BREAKER_JITTER``      max fractional jitter on the window (0.5)
``DGI_IO_BREAKER_DISABLE``     "1" disables breakers (every op attempted —
                               the pre-round-19 behavior, and the bench
                               A/B's "breakers off" leg)
=============================  =============================================
"""

from __future__ import annotations

import os
import random
import time
from pathlib import Path
from typing import Callable, Union

from distributed_gpu_inference_tpu.testing import faults as _faults

# gauge state codes (io_breaker_state{tier}): closed is the healthy zero
BREAKER_CLOSED = 0
BREAKER_HALF_OPEN = 1
BREAKER_OPEN = 2

_STATE_NAMES = {BREAKER_CLOSED: "closed", BREAKER_HALF_OPEN: "half_open",
                BREAKER_OPEN: "open"}


def breaker_env_config() -> dict:
    """The env-tunable breaker geometry (one read site, shared by every
    tier). Malformed values fall back to defaults — a bad env var must not
    take down the worker it configures."""
    def _f(name: str, default: float) -> float:
        try:
            return float(os.environ.get(name, "") or default)
        except ValueError:
            return default

    return {
        "threshold": max(1, int(_f("DGI_IO_BREAKER_THRESHOLD", 3))),
        "open_s": max(0.0, _f("DGI_IO_BREAKER_OPEN_S", 10.0)),
        "jitter": max(0.0, _f("DGI_IO_BREAKER_JITTER", 0.5)),
        "disabled": os.environ.get("DGI_IO_BREAKER_DISABLE", "") == "1",
    }


class IOBreaker:
    """Per-tier circuit breaker: closed → open → half-open → closed.

    Not thread-safe by itself — callers (the KV manager) already serialize
    tier access under their own locks/loop. ``clock`` is injectable so the
    state machine is testable with virtual time.
    """

    def __init__(self, name: str, threshold: int = 3, open_s: float = 10.0,
                 jitter: float = 0.5, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.name = name
        self.threshold = threshold
        self.open_s = open_s
        self.jitter = jitter
        self._clock = clock
        # seeded per-breaker: deterministic probe instants in tests, and
        # distinct workers de-synchronize their probes against a shared
        # recovering backend
        self._rng = random.Random(0x10C4E5 ^ seed ^ hash(name) & 0xFFFF)
        self._failures = 0
        self._state = BREAKER_CLOSED
        self._probe_at = 0.0
        self.trips = 0          # cumulative: rides heartbeat wire stats

    # -- state machine -------------------------------------------------------

    def allow(self) -> bool:
        """May the caller attempt the tier now? OPEN answers False until
        the jittered probe instant, then transitions to HALF-OPEN and
        admits exactly one probe; HALF-OPEN answers False while that probe
        is in flight (its record_* call resolves the state)."""
        if self._state == BREAKER_CLOSED:
            return True
        if self._state == BREAKER_OPEN:
            if self._clock() >= self._probe_at:
                self._state = BREAKER_HALF_OPEN
                return True
            return False
        return False               # half-open: probe already in flight

    def record_success(self) -> None:
        self._failures = 0
        self._state = BREAKER_CLOSED

    def record_failure(self) -> None:
        self._failures += 1
        if self._state == BREAKER_HALF_OPEN \
                or self._failures >= self.threshold:
            self._trip()

    def _trip(self) -> None:
        self._state = BREAKER_OPEN
        self.trips += 1
        self._probe_at = self._clock() + self.open_s * (
            1.0 + self.jitter * self._rng.random()
        )

    # -- introspection -------------------------------------------------------

    @property
    def state_code(self) -> int:
        return self._state

    @property
    def state(self) -> str:
        return _STATE_NAMES[self._state]

    @property
    def closed(self) -> bool:
        return self._state == BREAKER_CLOSED

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"IOBreaker({self.name!r}, state={self.state}, "
                f"failures={self._failures}, trips={self.trips})")


# ---------------------------------------------------------------------------
# crash-atomic file writes: temp + fsync + rename
# ---------------------------------------------------------------------------


def atomic_write_bytes(path: Union[str, Path], data: bytes) -> None:
    """Write ``data`` to ``path`` atomically: a sibling temp file is
    written and fsynced FIRST, then renamed over the target (os.replace is
    atomic on POSIX within one filesystem). A crash or injected IO fault
    at any point leaves the previous file intact; the temp is cleaned up
    on failure. Raises OSError on failure — callers decide whether the
    write was optional (fingerprint cache) or not (issued credentials)."""
    path = Path(path)
    _faults.io_fault("io.file.write", path=str(path))
    tmp = path.with_name(f".{path.name}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            tmp.unlink()
        except OSError:
            pass
        raise


def atomic_write_text(path: Union[str, Path], text: str,
                      encoding: str = "utf-8") -> None:
    atomic_write_bytes(path, text.encode(encoding))


__all__ = [
    "BREAKER_CLOSED", "BREAKER_HALF_OPEN", "BREAKER_OPEN", "IOBreaker",
    "atomic_write_bytes", "atomic_write_text", "breaker_env_config",
]
