"""Serving runtime: paged KV management, jitted engines, continuous batching,
speculative decoding, worker loop.

TPU-native re-design of the reference's worker runtime + engine layer
(``worker/main.py``, ``worker/batch_processor.py``, ``worker/engines/``,
``worker/distributed/kv_cache.py``).
"""
