"""Single-chip (and later mesh-sharded) serving engine: jitted prefill +
decode over paged KV, slot-based batch state, on-device sampling.

TPU-native replacement for the reference's engine layer (``worker/engines/
llm.py`` HF generate, ``llm_vllm.py`` vLLM wrapper): instead of wrapping a
serving framework, the engine owns

- device KV pools (``models.llama.init_kv_pools``) mutated in-place via
  donated jitted calls,
- a :class:`PagedKVCacheManager` for block accounting / prefix reuse / CoW,
- fixed-shape **slot** state (block tables, lengths, sampling params) so one
  compiled decode graph serves any mix of active requests — the static-shape
  answer to the reference's dynamic Python batches (SURVEY §7 "hard parts"),
- two decode drivers: per-step (host samples stop conditions every token —
  feeds the continuous batcher) and **multi-step** (``lax.scan`` of T decode
  steps with on-device stop masking — amortizes host round-trips; no
  reference analogue, TPU-first).

Prompt lengths are bucketed to powers of two so prefill compiles once per
bucket; decode compiles once per engine.
"""

from __future__ import annotations

import functools
import itertools
import json
import time
import uuid
import zlib
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_gpu_inference_tpu.models.configs import ModelConfig, get_model_config
from distributed_gpu_inference_tpu.models import llama
from distributed_gpu_inference_tpu.ops.quantization import quantize_params
from distributed_gpu_inference_tpu.ops.sampling import (
    sample_tokens_per_slot,
)
from distributed_gpu_inference_tpu.runtime.kv_cache import (
    HostKVStore,
    OutOfBlocksError,
    PagedKVCacheManager,
    PendingDeviceOps,
)
from distributed_gpu_inference_tpu.runtime.speculative import (
    SpecDecodeConfig,
    draft_apply,
    init_draft_params,
)
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    InferenceResponse,
    SamplingParams,
)

MAX_STOP_IDS = 4
_COPY_BUCKETS = (1, 2, 4, 8, 16, 32)
# core pack layout (int32 columns): last_token, kv_len, slot_key x2,
# stop_ids x MAX_STOP_IDS, top_k
_CORE_I_COLS = 5 + MAX_STOP_IDS
_BIG_BUDGET = 1 << 30
# quantized loads: full-precision trees up to this size init on-device
# (fast) before consume-quantization; larger ones stream/build so they
# never stage full-size in HBM. 8 GB, not "just fits 16": the tunnel frees
# consume-quantized bf16 leaves LAZILY, so an 11 GB device build passed
# this gate and then OOMed the follow-on prefill (observed round 4 on a
# 4-layer 70B-width slice) — leave real headroom for the reclaim lag.
_QUANT_DEVICE_BUILD_LIMIT = 8 * 1024**3


def _resolve_kv_dtype(kv_cache_dtype: Optional[str], activation_dtype) -> Any:
    """KV pool storage dtype. ``fp8`` = float8_e4m3 (scale-free: post-RoPE
    K and V magnitudes sit well inside e4m3's ±448 range, the same rationale
    as vLLM's unscaled fp8 KV default)."""
    if kv_cache_dtype is None:
        return jnp.dtype(activation_dtype)
    alias = {
        "fp8": jnp.float8_e4m3fn,
        "float8_e4m3fn": jnp.float8_e4m3fn,
        "bf16": jnp.bfloat16,
        "bfloat16": jnp.bfloat16,
        # int8 pools carry per-(page, token) scale pools alongside — the
        # quantized-KV mode that WINS on v5e (int8→bf16 converts are
        # HW-native; fp8's are software-emulated — BENCH_NOTES_r04)
        "int8": jnp.int8,
    }
    if kv_cache_dtype not in alias:
        raise ValueError(
            f"unknown kv_cache_dtype {kv_cache_dtype!r}; use {sorted(alias)}"
        )
    return jnp.dtype(alias[kv_cache_dtype])


@dataclass
class EngineConfig:
    max_batch_size: int = 8
    max_seq_len: int = 2048
    block_size: int = 16
    num_blocks: Optional[int] = None      # default: 1.5x worst-case + pad block
    prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128, 256, 512, 1024, 2048)
    enable_prefix_cache: bool = True
    multi_step: int = 16                  # scan horizon for decode_multi
    dtype: str = "bfloat16"
    # weight-only quantization (ops/quantization.py): int8 | fp8 | None —
    # first-party TPU replacement for the reference's vLLM passthrough flags
    # (worker/engines/llm_vllm.py:83-87 AWQ/GPTQ/FP8/INT8)
    quantization: Optional[str] = None
    # KV-cache storage dtype: None = activation dtype; "fp8" stores pools as
    # float8_e4m3 — half the decode KV read bytes AND double the page
    # capacity (decode streams the whole live context every step, so at
    # serving batch sizes KV reads rival the weight stream; the TPU
    # counterpart of vLLM's --kv-cache-dtype fp8 the reference passes
    # through). Dequant to bf16 happens in VMEM inside the Pallas decode
    # kernel / at the XLA gather.
    kv_cache_dtype: Optional[str] = None
    # spill tiers (reference HBM→CPU→Redis chain): 0 disables the host tier
    spill_host_blocks: int = 0
    spill_remote_store: Optional[Any] = None   # RemoteKVStore-like (L3)
    # persist the quantized weight tree to this dir after first build (orbax),
    # so later cold starts skip quantization entirely — VERDICT r2 #1's
    # startup fix for serving near-HBM-capacity models (8B int8 on 16 GB)
    quant_cache_dir: Optional[str] = None
    # sub-wave admission (VERDICT r2 #3): split a submit_batch wave into
    # chunks of this many sequences, each prefilled by a narrower compiled
    # graph, so sequence #1 samples its first token after ONE sub-wave
    # instead of after the whole wave's prefill. 0 = whole-wave (one call).
    admission_subwave: int = 0
    # bounded decode rounds between sub-waves: slots already generating
    # (earlier sub-waves, previously admitted requests) advance this many
    # tokens between chunks instead of stalling for the whole admission.
    # 0 = no interleave (pure TTFT staggering).
    admission_interleave_steps: int = 0
    # long-context prefill strategy on a mesh with a ``seq`` axis: a fresh
    # prompt longer than the largest prefill bucket runs ONE seq-sharded
    # pass (ring or ulysses attention over the seq axis,
    # parallel/ring_attention.py) instead of single-chip chunking; KV pages
    # land in the same paged pools decode reads (SURVEY §5.7)
    seq_parallel_impl: str = "ring"   # ring | ulysses
    # storage-side sequence parallelism: shard the KV pools' BLOCK axis
    # over ``seq`` so per-device pool memory scales 1/seq (servable context
    # scales with the mesh). Decode reads route through the shard_map
    # partial-softmax op (pages never move). Composes with the prefix
    # cache and chunked/continuation admission since round 4: chunks with
    # prior context read it through the sharded-pool CHUNK op; fresh first
    # chunks keep the cheaper dense path. Sliding-window models fenced.
    kv_seq_sharded: bool = False
    # engine-integrated speculative decoding: chain drafts (EAGLE-style
    # head) amortize the per-step weight stream over several accepted
    # tokens per slot. decode_multi then runs fused draft→verify→accept
    # steps; each slot commits 1..K+1 tokens per step and slots join/leave
    # mid-flight exactly as in plain continuous batching. Greedy outputs
    # are byte-identical to the non-speculative engine (the verify pass is
    # the target's own argmax); sampled slots ride the same graph at one
    # token per step. Single-chip only (no mesh).
    speculative: Optional[SpecDecodeConfig] = None
    # RAGGED rounds (round 6): max prefill-chunk width co-dispatched with
    # decode rows in one ragged_round() invocation. Bounds the dense
    # (non-attention) compute padding of the [B, S] round graph — decode
    # rows carry 1 live token out of S, so a wider chunk trades fewer
    # admission rounds against more masked matmul work per round. Clamped
    # to the largest prefill bucket; widths bucket through prefill_buckets
    # so the compiled round-graph count stays logarithmic.
    ragged_chunk: int = 256

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.max_seq_len // self.block_size)

    def resolved_num_blocks(self) -> int:
        if self.num_blocks is not None:
            return self.num_blocks
        worst = self.max_batch_size * self.max_blocks_per_seq
        return int(worst * 1.5) + 1  # +1: reserved pad block 0


@dataclass
class _Slot:
    request: InferenceRequest
    seq_id: str
    prompt_len: int
    generated: List[int] = field(default_factory=list)
    cached_tokens: int = 0
    # TTFT clock origin: the REQUEST's arrival time, not slot-bind time —
    # queue wait is part of time-to-first-token or an SLO claim is a lie
    # (reference single_worker.py:38-73 measures from submission too).
    # Migration paths override with the donor's original start_time.
    start_time: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_reason: Optional[str] = None
    # True while a chunk-interleaved admission is mid-prefill: the slot's KV
    # is incomplete and its last_token is garbage, so decode rounds MUST
    # skip it until the final chunk samples the first token
    prefilling: bool = False

    def __post_init__(self) -> None:
        if self.start_time is None:
            self.start_time = self.request.arrival_time


@dataclass
class ChunkedAdmission:
    """In-flight chunk-interleaved admission (``submit_chunked_start``).

    The scheduler runs one prefill chunk at a time via
    ``submit_chunked_step`` and interleaves bounded decode rounds for the
    other slots between chunks, so a long prompt never stalls active
    decodes longer than one chunk (vLLM-style chunked-prefill scheduling;
    VERDICT r1 next-step #4 — the repo's own benchmarks/pd_separation.py
    quantifies the interference this removes)."""

    request: InferenceRequest
    slot: int
    seq_id: str
    fresh: List[int]
    off: int
    mode: str
    done: bool = False


class RequestOverLength(ValueError):
    """Prompt + max_new_tokens exceeds the engine's ``max_seq_len`` — a
    per-request input error, not a capacity condition: no amount of
    waiting, preemption, or retry makes it fit THIS engine geometry.
    Carries the machine-readable ``error_code`` the serving layers thread
    through job results and SSE (like ``shed_overload`` /
    ``request_timeout``), so a client can route the request to a
    longer-context deployment instead of string-matching the message."""

    error_code = "over_length"


@dataclass
class KVPressure:
    """KV-block exhaustion observed at a step boundary — a SCHEDULING event,
    not an error. The engine leaves every sequence in a consistent frozen
    state (nothing decoded for the pressured slots, nothing half-allocated)
    and hands this signal to whoever drives it (``ContinuousBatcher``,
    ``generate``) to pick a preemption victim / requeue admissions.

    ``source``: "decode" means active slots could not reserve their next
    step's blocks (progress REQUIRES freeing blocks — preempt someone);
    "admission" means new work could not allocate (it can simply wait for
    running sequences to finish unless it outranks them).
    """

    source: str
    slots: List[int] = field(default_factory=list)   # slots that froze
    requests: int = 0                                # admissions deferred


#: wire version of the portable checkpoint format. Bump when a field's
#: meaning changes; ``from_wire`` refuses unknown versions so a newer
#: worker's checkpoint can never be silently mis-resumed by an older one.
CHECKPOINT_WIRE_VERSION = 1


@dataclass
class PreemptedSequence:
    """A running sequence frozen by :meth:`TPUEngine.preempt_slot` (or
    snapshotted live by :meth:`TPUEngine.snapshot_slot`).

    Carries everything needed for a byte-identical greedy (and seed-stable
    sampled) continuation through :meth:`TPUEngine.resume`: the original
    request, every token generated so far, and the slot's PRNG key
    material. Device blocks are RELEASED at preempt time — full blocks park
    in the prefix cache (and spill to the host tier under further
    pressure), so resume restores them via the radix index / ``_probe_spill``
    instead of recomputing the whole context.

    The state is also PORTABLE: :meth:`to_wire` / :meth:`from_wire` give a
    versioned JSON-safe encoding workers piggyback on heartbeats to the
    control plane, so a sequence can resume on a DIFFERENT engine after its
    worker dies (KV restored through the prefix cache / spill tiers when
    reachable, deterministic uncached-suffix recompute otherwise).
    """

    request: InferenceRequest
    prompt_len: int
    generated: List[int]
    slot_key: Tuple[int, int]             # threefry key words (hi, lo)
    start_time: Optional[float]
    first_token_time: Optional[float]
    cached_tokens: int
    preempt_count: int = 0                # maintained by the scheduler layer

    @staticmethod
    def _wire_crc(data: Dict[str, Any]) -> int:
        """CRC32 over the canonical JSON of the checkpoint WITHOUT its
        ``crc`` field — the integrity check for a record that crosses HTTP
        and sits in a TEXT column through a store brownout (round 19)."""
        body = {k: v for k, v in data.items() if k != "crc"}
        return zlib.crc32(
            json.dumps(body, sort_keys=True, separators=(",", ":")).encode()
        )

    def to_wire(self) -> Dict[str, Any]:
        """Versioned JSON-safe checkpoint (numbers, strings, lists only —
        it crosses HTTP and lands in a TEXT column). Carries a ``crc``
        field over the canonical JSON body so a torn/corrupted store row is
        DETECTED at resume (caller degrades to recompute) rather than
        resuming a half-written sequence."""
        r = self.request
        data = {
            "v": CHECKPOINT_WIRE_VERSION,
            "request": {
                "request_id": r.request_id,
                "model": r.model,
                "prompt_token_ids": list(r.prompt_token_ids or []),
                "sampling": r.sampling.to_dict(),
                "priority": r.priority,
                "session_id": r.session_id,
                # optional EDF deadline (absolute): resumes are already
                # head-of-line, but the victim policy still reads it
                **({"deadline_at": r.deadline_at}
                   if r.deadline_s is not None else {}),
            },
            "prompt_len": self.prompt_len,
            "generated": list(self.generated),
            "slot_key": [int(self.slot_key[0]), int(self.slot_key[1])],
            "start_time": self.start_time,
            "first_token_time": self.first_token_time,
            "cached_tokens": self.cached_tokens,
            "preempt_count": self.preempt_count,
        }
        data["crc"] = self._wire_crc(data)
        return data

    @classmethod
    def from_wire(cls, data: Dict[str, Any]) -> "PreemptedSequence":
        if not isinstance(data, dict):
            raise ValueError("checkpoint must be a dict")
        ver = data.get("v")
        if ver != CHECKPOINT_WIRE_VERSION:
            raise ValueError(
                f"unsupported checkpoint version {ver!r} (this build "
                f"speaks v{CHECKPOINT_WIRE_VERSION})"
            )
        # verify-when-present: pre-round-19 rows carry no crc and parse as
        # before (mixed-version fleets); a present-but-wrong crc means the
        # row was torn or bit-flipped in the store — refuse to resume it
        if "crc" in data and int(data["crc"]) != cls._wire_crc(data):
            raise ValueError("checkpoint integrity check failed (bad crc)")
        r = data["request"]
        request = InferenceRequest(
            request_id=r["request_id"],
            model=r.get("model"),
            prompt_token_ids=[int(t) for t in (r.get("prompt_token_ids")
                                               or [])],
            sampling=SamplingParams.from_dict(r["sampling"]),
            priority=int(r.get("priority") or 0),
            session_id=r.get("session_id"),
        )
        if r.get("deadline_at") is not None:
            # arrival_time was re-minted by the ctor above: re-derive the
            # relative deadline so deadline_at round-trips the wire
            request.deadline_s = max(
                0.0, float(r["deadline_at"]) - request.arrival_time
            )
        key = data.get("slot_key") or [0, 0]
        return cls(
            request=request,
            prompt_len=int(data["prompt_len"]),
            generated=[int(t) for t in (data.get("generated") or [])],
            slot_key=(int(key[0]), int(key[1])),
            start_time=data.get("start_time"),
            first_token_time=data.get("first_token_time"),
            cached_tokens=int(data.get("cached_tokens") or 0),
            preempt_count=int(data.get("preempt_count") or 0),
        )


class TPUEngine:
    """Paged-KV serving engine for one model on one chip/mesh."""

    def __init__(
        self,
        model_cfg: ModelConfig | str,
        engine_cfg: Optional[EngineConfig] = None,
        params: Optional[llama.Params] = None,
        seed: int = 0,
        eos_token_id: Optional[int] = None,
        mesh: Optional[Any] = None,
        checkpoint_path: Optional[str] = None,
    ) -> None:
        """``mesh``: first-class tensor parallelism — params and KV pools are
        GSPMD-sharded over the mesh's ``model`` axis and XLA inserts the TP
        collectives (the reference only passes tensor_parallel_size through
        to vLLM, SURVEY §2.2). Data parallelism stays request-level at the
        fleet scheduler, so an engine mesh must not carry a data axis.

        ``checkpoint_path``: orbax dir / HF safetensors dir; random init
        when absent (hermetic tests, benchmarks)."""
        self.model_cfg = (
            get_model_config(model_cfg) if isinstance(model_cfg, str) else model_cfg
        )
        self.cfg = engine_cfg or EngineConfig()
        self.dtype = jnp.dtype(self.cfg.dtype)
        self.kv_dtype = _resolve_kv_dtype(self.cfg.kv_cache_dtype, self.dtype)
        if (
            self.kv_dtype.itemsize == 1
            and self.cfg.block_size % 32 != 0
            and jax.default_backend() == "tpu"
        ):
            # byte-dtype pool pages tile (32, 128) on TPU: a narrower block
            # would make page slices non-DMA-able in the Pallas kernel
            raise ValueError(
                f"kv_cache_dtype={self.cfg.kv_cache_dtype!r} needs "
                f"block_size % 32 == 0 on TPU, got {self.cfg.block_size}"
            )
        # int8 KV composes with meshes AND spill tiers since round 5:
        # scale pools shard with their data pools (replicated under TP —
        # no head axis to shard; block-axis-sharded under seq —
        # parallel/sharding.py kv_scale_sharding*), the shard_map seq ops
        # dequantize their local page shards, the quantize amax reduce
        # over sharded heads lowers to an all-reduce-max (scales stay
        # bit-identical to a single-chip engine), and evicted pages spill
        # int8 codes + scale pages as an atomic pair through L2/L3
        # (runtime/kv_cache.py store_spilled/_probe_spill).
        self.mesh = mesh
        self._seq_axis = 1
        if mesh is not None:
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            tp = sizes.get("model", 1)
            self._seq_axis = sizes.get("seq", 1)
        if mesh is not None:
            # general mesh validations (ANY mesh, not just seq-sharded)
            if sizes.get("data", 1) > 1:
                raise ValueError(
                    "engine mesh must not carry a data axis (DP is "
                    "request-level at the scheduler); got "
                    f"data={sizes['data']}"
                )
            if self.model_cfg.num_kv_heads % max(tp, 1):
                raise ValueError(
                    f"num_kv_heads {self.model_cfg.num_kv_heads} not "
                    f"divisible by model axis {tp}"
                )
            if self.model_cfg.num_experts and \
                    self.model_cfg.num_experts % max(tp, 1):
                raise ValueError(
                    f"num_experts {self.model_cfg.num_experts} not "
                    f"divisible by model axis {tp} (EP shards experts)"
                )
        if self.cfg.speculative is not None:
            self.cfg.speculative.validate(self.cfg)
            if mesh is not None:
                # the draft head would need its own sharding rules and the
                # verify chunk its own partitioning; keep the mode
                # single-chip until that exists
                raise ValueError(
                    "speculative decode mode is single-chip: drop the mesh "
                    "or EngineConfig.speculative"
                )
        if self.cfg.kv_seq_sharded:
            if self._seq_axis <= 1:
                raise ValueError(
                    "kv_seq_sharded needs a mesh with a seq axis > 1"
                )
            # prefix caching and chunked/continuation admission compose with
            # sharded pools since round 4: continuation chunks attend prior
            # context through the shard_map partial-softmax chunk op
            # (parallel/ring_attention.seq_parallel_paged_chunk_attention);
            # only sliding-window models stay fenced (below).
            if self.cfg.resolved_num_blocks() % self._seq_axis:
                # round the pool UP so the block axis shards evenly
                blocks = self.cfg.resolved_num_blocks()
                self.cfg.num_blocks = (
                    -(-blocks // self._seq_axis) * self._seq_axis
                )
        if params is not None:
            self.params = quantize_params(params, self.cfg.quantization)
            if mesh is not None:
                from distributed_gpu_inference_tpu.parallel import sharding as _sh

                self.params = _sh.shard_params(self.params, mesh)
        else:
            self.params = self._load_params(checkpoint_path, seed)
        self.num_blocks = self.cfg.resolved_num_blocks()
        self.kv = self._init_kv()
        host_store = (
            HostKVStore(self.cfg.spill_host_blocks)
            if self.cfg.spill_host_blocks > 0 else None
        )
        spill = host_store is not None or self.cfg.spill_remote_store is not None
        self.manager = PagedKVCacheManager(
            self.num_blocks,
            self.cfg.block_size,
            enable_prefix_cache=self.cfg.enable_prefix_cache,
            host_store=host_store,
            remote_store=self.cfg.spill_remote_store,
            spill_on_evict=spill,
            kv_dtype=np.dtype(self.kv_dtype),
        )
        self.eos_token_id = eos_token_id

        b, m = self.cfg.max_batch_size, self.cfg.max_blocks_per_seq
        self.slots: List[Optional[_Slot]] = [None] * b
        self._block_tables = np.zeros((b, m), dtype=np.int32)
        self._kv_lens = np.zeros((b,), dtype=np.int32)
        self._last_tokens = np.zeros((b,), dtype=np.int32)
        self._temps = np.zeros((b,), dtype=np.float32)
        self._top_ks = np.zeros((b,), dtype=np.int32)
        self._top_ps = np.ones((b,), dtype=np.float32)
        self._stop_ids = np.full((b, MAX_STOP_IDS), -1, dtype=np.int32)
        # One PRNG key per slot: a seeded request's random stream is
        # independent of which other requests share the batch. Exact token
        # reproduction additionally requires identical logits — i.e. the
        # same dtype and the same prefill split (prefix-cache hits change
        # the suffix bucket, and bf16 reduction order can flip low bits);
        # greedy requests are robust to those effects, sampled ones are
        # reproducible given equal numerics.
        self._slot_keys = np.zeros((b, 2), dtype=np.uint32)
        self._host_rng = np.random.default_rng(seed + 0x5EED)

        # Device-resident core slot state (sampling params, PRNG keys, stop
        # ids, last token, committed length). The host numpy mirrors above
        # stay authoritative for scheduling; their device copies are uploaded
        # ONLY when a host-initiated change lands (admission, adopt, error
        # recovery) — never per decode round. Each host→device transfer costs
        # a full control round-trip on a remote-tunnel TPU (~10 ms measured),
        # so per-call re-upload of slot arrays was the round-1 TTFT/latency
        # sink (VERDICT round 1, weak #3).
        self._dev_core: Optional[Dict[str, jax.Array]] = None
        self._core_dirty = True

        # integrated speculative decoding: EAGLE-style draft head weights +
        # per-slot last-verified hidden state (device-resident between
        # rounds, like _dev_core). The hidden starts at zeros for a fresh
        # slot — the first step then drafts garbage and accepts ~nothing,
        # which is CORRECT (emission is target-verified regardless of draft
        # quality) and seeds the real hidden from that verify pass.
        self._draft_params: Optional[Dict[str, jax.Array]] = None
        self._dev_spec_h: Optional[jax.Array] = None
        self._spec_h_zero: set = set()
        if self.cfg.speculative is not None:
            sp = self.cfg.speculative
            self._draft_params = (
                sp.draft_params if sp.draft_params is not None
                else init_draft_params(
                    self.model_cfg, jax.random.PRNGKey(sp.draft_seed),
                    dtype=self.dtype,
                )
            )
            # acceptance-adaptive draft depth: per-slot EMA of the
            # ACCEPTED length (host-side — deterministic float arithmetic
            # over integer accept counts, so same seed → same K
            # schedule). Fresh slots start optimistic at K and converge.
            self._spec_k_ema = np.full((b,), float(sp.num_draft_tokens))
            # oracle-draft fractional-rate accumulator (per slot): a rate
            # whose K-scaled target is fractional dithers deterministically
            # (e.g. 2.4 → 2,3,2,3,2 accepted per round)
            self._spec_oracle_acc = np.zeros((b,))
            # test hook: set to a list and every dispatch appends its
            # [(slot, selected_k), ...] — None (default) records nothing
            self.spec_k_trace: Optional[List[Any]] = None

        self._build_jit_fns()
        # pending KV-pressure signal (set at step boundaries, consumed by
        # the scheduler layer via take_pressure)
        self._pressure: Optional[KVPressure] = None
        self.stats: Dict[str, Any] = {
            "requests": 0, "completed": 0, "generated_tokens": 0,
            "prefill_tokens": 0, "prefill_calls": 0, "decode_calls": 0,
            "preemptions": 0, "resumes": 0, "kv_pressure_events": 0,
            "ragged_rounds": 0,
        }
        if self.cfg.speculative is not None:
            self.stats.update({
                "spec_steps": 0, "spec_slot_steps": 0, "spec_drafted": 0,
                "spec_accepted": 0, "spec_emitted": 0,
            })

    # -------------------------------------------------- sharded weight init

    def _load_params(self, checkpoint_path: Optional[str], seed: int):
        """Weights land SHARDED when a mesh is set: never materialize the
        full model on one chip (a TP engine must serve models bigger than a
        single chip's HBM — full-size init then reshard would OOM first)."""
        from distributed_gpu_inference_tpu.models.loader import (
            load_or_init_params,
        )

        if self.mesh is None:
            if self.cfg.quantization is None:
                return load_or_init_params(
                    self.model_cfg, checkpoint_path=checkpoint_path,
                    dtype=self.cfg.dtype, seed=seed,
                )
            cached = self._load_quant_cache(checkpoint_path, seed)
            if cached is not None:
                return cached
            # quantized single-chip cold build. Three regimes:
            # - full-precision tree fits HBM transiently → init on device
            #   (fast) and quantize with consume=True, freeing each source
            #   leaf as its replacement lands (peak = full tree + 1 leaf);
            # - it does NOT fit and there is no checkpoint (benchmarks) →
            #   streamed on-device init: each leaf generated + quantized one
            #   layer slice at a time (no host init, no multi-GB upload);
            # - real checkpoint that doesn't fit (llama3-8b bf16 = 16.1 GB
            #   on 16 GB) → build + quantize on host CPU, upload only
            #   quantized bytes.
            fp_bytes = self.model_cfg.param_bytes(jnp.dtype(self.cfg.dtype).itemsize)
            if fp_bytes <= _QUANT_DEVICE_BUILD_LIMIT:
                params = quantize_params(
                    load_or_init_params(
                        self.model_cfg, checkpoint_path=checkpoint_path,
                        dtype=self.cfg.dtype, seed=seed,
                    ),
                    self.cfg.quantization,
                    consume=True,
                )
                # persisting would download the tree from the accelerator —
                # measured 14 MB/s on a tunneled chip, minutes for GBs — so
                # only host-resident trees are cached
                if jax.default_backend() == "cpu":
                    self._save_quant_cache(params, checkpoint_path, seed)
            elif checkpoint_path is None:
                from distributed_gpu_inference_tpu.models.loader import (
                    init_quantized_streamed,
                )

                # streamed on-device init is itself the fast path (~30 s for
                # 8B incl. cached compiles); no persistence needed or wanted
                params = init_quantized_streamed(
                    self.model_cfg, self.cfg.quantization,
                    dtype=self.cfg.dtype, seed=seed,
                )
            else:
                cpu = jax.local_devices(backend="cpu")[0]
                with jax.default_device(cpu):
                    host_params = quantize_params(
                        load_or_init_params(
                            self.model_cfg, checkpoint_path=checkpoint_path,
                            dtype=self.cfg.dtype, seed=seed,
                        ),
                        self.cfg.quantization,
                        consume=True,
                    )
                # save BEFORE upload while the tree is host-resident: the
                # next cold start then restores int8 from disk (~1 GB/s
                # upload) instead of re-quantizing the fp checkpoint
                self._save_quant_cache(host_params, checkpoint_path, seed)
                dev = jax.devices()[0]
                params = jax.tree.map(
                    lambda a: jax.device_put(a, dev), host_params
                )
            return params
        # build (and quantize) on the host CPU backend, then device_put
        # host→shards direct — int8/fp8 leaves ship half the bytes
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            host_params = quantize_params(
                load_or_init_params(
                    self.model_cfg, checkpoint_path=checkpoint_path,
                    dtype=self.cfg.dtype, seed=seed,
                ),
                self.cfg.quantization,
            )
        from distributed_gpu_inference_tpu.parallel import sharding as _sh

        return _sh.shard_params(host_params, self.mesh)

    def _quant_cache_path(self, checkpoint_path: Optional[str], seed: int):
        import hashlib
        from pathlib import Path

        if not self.cfg.quant_cache_dir or self.mesh is not None:
            return None
        if checkpoint_path is None:
            src = "rand"
        else:
            # content signature, not just the path: an in-place checkpoint
            # update (same dir, new weights) must invalidate the cache or
            # the engine silently serves the previous model
            p = Path(checkpoint_path)
            sig = hashlib.sha1()
            # recursive: orbax trees keep weights in nested files whose
            # in-place rewrite must invalidate the cache
            for f in sorted(p.rglob("*")):
                try:
                    st = f.stat()
                except OSError:
                    continue
                sig.update(f"{f.name}:{st.st_size}:{st.st_mtime_ns};".encode())
            src = f"{p.name or 'ckpt'}-{sig.hexdigest()[:10]}"
        tag = (
            f"{self.model_cfg.name}-{self.cfg.quantization}-"
            f"{self.cfg.dtype}-{src}-seed{seed}"
        )
        return Path(self.cfg.quant_cache_dir) / tag

    def _load_quant_cache(self, checkpoint_path: Optional[str], seed: int):
        """Restore a previously persisted quantized tree (orbax) straight to
        the device — skips init + quantization on every cold start after the
        first. Corrupt/incompatible caches fall back to a fresh build."""
        p = self._quant_cache_path(checkpoint_path, seed)
        if p is None or not (p / "params").exists():
            return None
        from distributed_gpu_inference_tpu.models.loader import load_checkpoint

        try:
            return load_checkpoint(p)
        except Exception:
            return None

    def _save_quant_cache(self, params, checkpoint_path: Optional[str],
                          seed: int) -> None:
        p = self._quant_cache_path(checkpoint_path, seed)
        if p is None or (p / "params").exists():
            return
        from distributed_gpu_inference_tpu.models.loader import save_checkpoint

        try:
            save_checkpoint(p, params)
        except Exception:
            pass  # cache is best-effort; serving proceeds with live params

    def _init_kv(self) -> llama.KVPools:
        if self.mesh is None:
            return llama.init_kv_pools(
                self.model_cfg, self.num_blocks, self.cfg.block_size,
                self.kv_dtype,
            )
        # zeros created directly with the sharded layout (no single-device
        # staging allocation)
        from distributed_gpu_inference_tpu.parallel import sharding as _sh

        s = (
            _sh.kv_sharding_seq(self.mesh)
            if self.cfg.kv_seq_sharded else _sh.kv_sharding(self.mesh)
        )
        out_s = {"k": s, "v": s}
        if self.kv_dtype == jnp.int8:
            ss = (
                _sh.kv_scale_sharding_seq(self.mesh)
                if self.cfg.kv_seq_sharded
                else _sh.kv_scale_sharding(self.mesh)
            )
            out_s["k_scale"] = out_s["v_scale"] = ss
        make = jax.jit(
            lambda: llama.init_kv_pools(
                self.model_cfg, self.num_blocks, self.cfg.block_size,
                self.kv_dtype,
            ),
            out_shardings=out_s,
        )
        return make()

    # ------------------------------------------------------------------ jit

    def _build_jit_fns(self) -> None:
        cfg, bs = self.model_cfg, self.cfg.block_size
        m = self.cfg.max_blocks_per_seq

        # seq-sharded pools: decode reads go through the shard_map
        # partial-softmax op (a GSPMD gather from an N-sharded pool would
        # all-gather it); prefill attends DENSE over the chunk (fresh
        # prompts: chunk == whole context), so pool pages are never read
        # during admission
        decode_attn_override = None
        prefill_dense_fn = None
        chunk_attn_override = None
        if self.cfg.kv_seq_sharded:
            if cfg.sliding_window is not None:
                raise ValueError(
                    "kv_seq_sharded does not support sliding-window models"
                )
            from distributed_gpu_inference_tpu.ops.attention import (
                dense_causal_attention,
            )
            from distributed_gpu_inference_tpu.parallel.ring_attention import (
                seq_parallel_paged_chunk_attention,
                seq_parallel_paged_decode_attention,
            )

            mesh = self.mesh

            def decode_attn_override(q, layer_k, layer_v, tables, positions,
                                     kv_lens, layer_ks=None, layer_vs=None):
                return seq_parallel_paged_decode_attention(
                    q, layer_k, layer_v, tables, positions, kv_lens, mesh,
                    block_size=bs, k_scale=layer_ks, v_scale=layer_vs,
                )

            def prefill_dense_fn(q, k, v, kv_lens):
                return dense_causal_attention(q, k, v, lengths=kv_lens)

            # continuation/cached chunks: the chunk's KV is in the sharded
            # pool by the time attention runs, so one partial-softmax read
            # covers cached prefix + prior chunks + in-chunk causal keys
            def chunk_attn_override(q, layer_k, layer_v, tables, positions,
                                    kv_lens, layer_ks=None, layer_vs=None):
                return seq_parallel_paged_chunk_attention(
                    q, layer_k, layer_v, tables, positions, kv_lens, mesh,
                    block_size=bs, k_scale=layer_ks, v_scale=layer_vs,
                )

        # --- device-state pack/unpack (ONE upload per packed buffer: on a
        # remote-tunnel TPU every host→device transfer is a control RTT, so
        # slot state crosses in two packed arrays, not ten small ones)

        def unpack_core(ci, cf):
            return {
                "last": ci[:, 0],
                "lens": ci[:, 1],
                "keys": jax.lax.bitcast_convert_type(ci[:, 2:4], jnp.uint32),
                "stops": ci[:, 4:4 + MAX_STOP_IDS],
                "top_ks": ci[:, 4 + MAX_STOP_IDS],
                "temps": cf[:, 0],
                "top_ps": cf[:, 1],
            }

        self._unpack_core_fn = jax.jit(unpack_core)

        def unpack_sched(si):
            return si[:, :m], si[:, m] > 0, si[:, m + 1]

        self._unpack_sched_fn = jax.jit(unpack_sched)

        # --- sampling fused into the serving graphs. ``mode`` is static:
        # "greedy" compiles an argmax-only epilogue (no [B, V] sort in the
        # step — the whole batch is temperature 0, the serving common case),
        # "mixed" compiles the full per-slot nucleus sampler. The engine
        # picks the variant per call from the host mirrors.

        def sample_mode(logits, keys, positions, temps, top_ks, top_ps, mode):
            if mode == "greedy":
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return sample_tokens_per_slot(
                logits, keys, positions, temps, top_ks, top_ps
            )

        def prefill_batch(params, kv, toks_pos, tables, lens_after, core,
                          wave, mode):
            out = llama.forward_chunk(
                cfg, params, toks_pos[0], toks_pos[1], kv, tables, lens_after,
                block_size=bs, last_only=True,
                dense_attn_fn=(
                    (lambda q, k, v: prefill_dense_fn(q, k, v, lens_after))
                    if prefill_dense_fn else None
                ),
            )
            first = sample_mode(
                out.logits[:, 0, :], core["keys"], lens_after, core["temps"],
                core["top_ks"], core["top_ps"], mode,
            )
            core = dict(core)
            core["last"] = jnp.where(wave, first, core["last"])
            core["lens"] = jnp.where(wave, lens_after, core["lens"])
            return first, core, out.kv

        self._prefill_batch_fn = jax.jit(
            prefill_batch, static_argnames=("mode",), donate_argnums=(1, 5)
        )

        def prefill_chunk(params, kv, toks_pos, table, kv_len, keys, temps,
                          top_ks, top_ps, mode, sample):
            out = llama.forward_chunk(
                cfg, params, toks_pos[0], toks_pos[1], kv, table, kv_len,
                block_size=bs, last_only=True, with_logits=sample,
                dense_attn_fn=(
                    # fresh single-chunk prompts only in kv_seq_sharded mode
                    # (chunk == whole context; _prefill_one_chunk enforces)
                    (lambda q, k, v: prefill_dense_fn(q, k, v, kv_len))
                    if prefill_dense_fn else None
                ),
            )
            if not sample:
                # intermediate chunk: KV side effects only — no LM head read
                return None, out.kv
            first = sample_mode(
                out.logits[:, 0, :], keys, kv_len, temps, top_ks, top_ps, mode
            )
            return first, out.kv

        self._prefill_chunk_fn = jax.jit(
            prefill_chunk, static_argnames=("mode", "sample"),
            donate_argnums=(1,),
        )

        # continuation/cached chunk prefill over seq-sharded pools: same
        # shape contract as prefill_chunk, but attention reads the pool
        # through the shard_map partial-softmax chunk op (prior context +
        # in-chunk keys; the layer step wrote the chunk's KV first)
        self._prefill_chunk_paged_fn = None
        if chunk_attn_override is not None:
            def prefill_chunk_paged(params, kv, toks_pos, table, kv_len,
                                    keys, temps, top_ks, top_ps, mode,
                                    sample):
                out = llama.forward_chunk(
                    cfg, params, toks_pos[0], toks_pos[1], kv, table, kv_len,
                    block_size=bs, last_only=True, with_logits=sample,
                    attn_override=chunk_attn_override,
                )
                if not sample:
                    return None, out.kv
                first = sample_mode(
                    out.logits[:, 0, :], keys, kv_len, temps, top_ks,
                    top_ps, mode,
                )
                return first, out.kv

            self._prefill_chunk_paged_fn = jax.jit(
                prefill_chunk_paged, static_argnames=("mode", "sample"),
                donate_argnums=(1,),
            )

        def prefill_seq_parallel(params, kv, toks_pos, table, kv_len, keys,
                                 temps, top_ks, top_ps, mode):
            # one seq-sharded pass over the WHOLE long prompt: attention
            # runs ring/ulysses over the mesh's seq axis; KV pages are
            # written to the paged pools exactly as chunked prefill would
            from distributed_gpu_inference_tpu.parallel import ring_attention

            dense = (
                ring_attention.ring_self_attention
                if self.cfg.seq_parallel_impl == "ring"
                else ring_attention.ulysses_self_attention
            )

            def dense_attn(q, k_, v_):
                return dense(q, k_, v_, kv_len, self.mesh)

            out = llama.forward_chunk(
                cfg, params, toks_pos[0], toks_pos[1], kv, table, kv_len,
                block_size=bs, last_only=True, dense_attn_fn=dense_attn,
            )
            first = sample_mode(
                out.logits[:, 0, :], keys, kv_len, temps, top_ks, top_ps,
                mode,
            )
            return first, out.kv

        self._prefill_seq_fn = jax.jit(
            prefill_seq_parallel, static_argnames=("mode",),
            donate_argnums=(1,),
        )

        def decode_multi(params, kv, core, tables, active, budgets,
                         num_steps, mode):
            # One graph serves the per-step path (num_steps=1) and the
            # multi-step scan. Slot state lives in ``core`` (device-resident
            # between rounds); per-slot budgets mask slots out ON DEVICE once
            # they emit their allowance, so one compiled T=multi_step graph
            # serves every call. ``core["lens"]`` is the COMMITTED context
            # length; each non-done step feeds the pending token at position
            # lens (writing its KV) and advances lens by one — on exit the
            # device lens/last match the host mirrors exactly, which is what
            # lets the next round skip the state upload.
            stops = core["stops"]

            def step(carry, _):
                kv, last, lens, done, n_emit = carry
                cur = jnp.where(~done, lens + 1, 0).astype(jnp.int32)
                positions = jnp.where(
                    (~done)[:, None], lens[:, None], -1
                ).astype(jnp.int32)
                out = llama.forward_chunk(
                    cfg, params, last[:, None], positions, kv, tables, cur,
                    block_size=bs, last_only=True,
                    attn_override=decode_attn_override,
                    # the fused Pallas decode kernel has no GSPMD
                    # partitioning rules (and its in-kernel int8 quantize
                    # amax would be per-shard): mesh engines stay on the
                    # XLA paged path, which partitions + all-reduces
                    allow_fused=self.mesh is None,
                )
                toks = sample_mode(
                    out.logits[:, 0, :], core["keys"], cur, core["temps"],
                    core["top_ks"], core["top_ps"], mode,
                )
                hit_stop = jnp.any(toks[:, None] == stops, axis=1)
                emitted = jnp.where(done, -1, toks)
                new_emit = n_emit + (~done).astype(jnp.int32)
                new_done = done | hit_stop | (new_emit >= budgets)
                new_lens = jnp.where(done, lens, lens + 1)
                new_last = jnp.where(done, last, toks)
                return (out.kv, new_last, new_lens, new_done, new_emit), emitted

            done0 = ~active
            n0 = jnp.zeros_like(core["lens"])
            (kv, last, lens, _done, _), emitted = jax.lax.scan(
                step, (kv, core["last"], core["lens"], done0, n0), None,
                length=num_steps,
            )
            core = dict(core)
            core["last"], core["lens"] = last, lens
            return kv, core, emitted.T  # emitted [B, T]

        self._decode_multi_fn = jax.jit(
            decode_multi, static_argnames=("num_steps", "mode"),
            donate_argnums=(1, 2),
        )

        # --- RAGGED round (round 6): ONE dispatch in which decode rows
        # (1 live token each, at position lens) and admission prefill-chunk
        # rows (up to S live tokens) coexist — per-row positions/-1 padding
        # select each row's path, and on TPU the attention inside
        # forward_chunk dispatches to the ragged paged-attention kernel
        # (ops.attention.resolve_impl → "ragged" for S > 1). Admission
        # stops being a competing dispatch: appending a chunk row to the
        # next round IS the admission. Per-row token math is identical to
        # the split paths (decode rows ≡ decode_multi's step, chunk rows ≡
        # _prefill_chunk_fn), so greedy outputs are byte-identical and
        # seeded sampling is stable (the sampler folds the absolute
        # position, which is per-row here exactly as there).
        def ragged_round(params, kv, toks_pos, tables, lens_after, core,
                         sample_flag, mode):
            out = llama.forward_chunk(
                cfg, params, toks_pos[0], toks_pos[1], kv, tables,
                lens_after, block_size=bs, last_only=True,
                attn_override=chunk_attn_override,
                # the fused write+attention kernel is S=1-shaped; ragged
                # rounds always carry at least one multi-token-capable row
                allow_fused=False,
            )
            toks = sample_mode(
                out.logits[:, 0, :], core["keys"], lens_after,
                core["temps"], core["top_ks"], core["top_ps"], mode,
            )
            # rows that sampled (decode rows + FINAL admission chunks)
            # advance the device core exactly as decode_multi / the
            # batched prefill would; intermediate chunk rows only write KV
            sampled = sample_flag > 0
            core = dict(core)
            core["last"] = jnp.where(sampled, toks, core["last"])
            core["lens"] = jnp.where(sampled, lens_after, core["lens"])
            return out.kv, core, toks

        self._ragged_round_fn = jax.jit(
            ragged_round, static_argnames=("mode",), donate_argnums=(1, 5),
        )

        # --- integrated speculative decoding: R fused draft→verify→accept
        # rounds per dispatch (lax.scan — the spec analogue of decode_multi's
        # scan, same per-dispatch RTT amortization; the round-2 lesson from
        # the standalone decoder was that one host round per tree round
        # loses to vanilla outright). Per round, the draft head chains K
        # greedy tokens from the last verified hidden; one multi-query
        # target pass (q_len = K+1 per slot — ops.attention's small-q path)
        # verifies them; each slot accepts its longest matching prefix plus
        # the target's bonus token. Chain positions are sequential, so
        # accepted KV is already at its final position and a rejected
        # suffix is dead weight the next round overwrites — no tree
        # compaction, no KV movement. Per-round records (emission order,
        # accept counts, active mask) return to the host, which replays
        # stop/budget bookkeeping EXACTLY as the per-step path would.
        self._spec_rounds_fn = None
        self._spec_ragged_round_fn = None
        if self.cfg.speculative is not None:
            spec_k = self.cfg.speculative.num_draft_tokens

            def draft_chain(params, dp, pending, h):
                # K-token greedy draft chain — shared by the fused scan
                # and the spec ragged round. Draft logits go through
                # project_logits (final norm + head) — the readout
                # distillation trains against (the round-3 tied-embedding
                # finding, runtime/speculative.py). Depth is always the
                # STATIC spec_k; per-slot adaptive depths mask the tail
                # (positions/acceptance), never re-trace.
                toks = [pending]
                hh = h
                for _ in range(spec_k):
                    hh = draft_apply(
                        cfg, dp, hh, llama.embed_tokens(params, toks[-1],
                                                        cfg)
                    )
                    dl = llama.project_logits(cfg, params, hh[:, None, :])
                    toks.append(
                        jnp.argmax(dl[:, 0, :], axis=-1).astype(jnp.int32)
                    )
                return jnp.stack(toks, axis=1)                   # [B, K+1]

            def accept_chain(chunk, target_pred, ks, forced, lens, caps,
                             offs):
                # longest matching prefix (greedy match) bounded by the
                # slot's selected depth; the oracle (forced >= 0)
                # overrides the match — cost stays real, only the
                # decision is forced. Clamped so committed + pending
                # stays inside block coverage.
                match = (chunk[:, 1:] == target_pred[:, :-1]).astype(
                    jnp.int32
                ) * (offs[:, 1:] <= ks[:, None]).astype(jnp.int32)
                n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                n_acc = jnp.where(
                    forced >= 0, jnp.minimum(forced, ks), n_acc
                )
                return jnp.minimum(n_acc, jnp.maximum(caps - lens - 2, 0))

            def spec_rounds(params, dp, kv, core, h_last, tables, active,
                            caps, budgets, ks, forced_rounds, rounds, mode):
                # caps[b] = token positions the slot's reserved blocks
                # cover for the WHOLE dispatch; writes beyond drop to the
                # pad block, acceptance is clamped, and a row freezes when
                # its next window no longer fits (host re-reserves next
                # dispatch). budgets[b] = remaining max_new_tokens.
                # ks[b] = the slot's selected draft depth (= spec_k unless
                # adaptive); forced_rounds[r, b] = oracle accepted length
                # per round (-1 = real acceptance).
                keys, temps = core["keys"], core["temps"]
                top_ks, top_ps, stops = (
                    core["top_ks"], core["top_ps"], core["stops"]
                )
                offs = jnp.arange(spec_k + 1, dtype=jnp.int32)[None, :]

                def body(carry, forced):
                    kv, lens, pending, h, done, n_emit = carry
                    act = ~done
                    b = lens.shape[0]
                    # ---- draft phase
                    chunk = draft_chain(params, dp, pending, h)  # [B, K+1]

                    # ---- verify phase: one target pass over the chain.
                    # t0 (the pending token) commits its KV exactly as a
                    # vanilla step would; drafts write ahead of
                    # verification into reserved blocks (only up to the
                    # slot's selected depth — deeper columns are masked).
                    pos = lens[:, None] + offs
                    pos = jnp.where(
                        act[:, None] & (offs <= ks[:, None])
                        & (pos < caps[:, None]), pos, -1
                    )
                    kv_lens_after = jnp.where(
                        act, lens + ks + 1, 0
                    ).astype(jnp.int32)
                    out = llama.forward_chunk(
                        cfg, params, chunk, pos, kv, tables, kv_lens_after,
                        block_size=bs, last_only=False, allow_fused=False,
                    )
                    target_pred = jnp.argmax(out.logits, axis=-1).astype(
                        jnp.int32
                    )                                            # [B, K+1]

                    # ---- acceptance
                    n_acc = accept_chain(
                        chunk, target_pred, ks, forced, lens, caps, offs
                    )
                    bonus = jnp.take_along_axis(
                        target_pred, n_acc[:, None], axis=1
                    )[:, 0]
                    if mode == "mixed":
                        # sampled slots ride the same graph at one token
                        # per round: sample from the pending token's logits
                        # exactly as a vanilla step would (same key fold
                        # position), never accept drafts
                        sampled0 = sample_tokens_per_slot(
                            out.logits[:, 0, :], keys, lens + 1, temps,
                            top_ks, top_ps,
                        )
                        is_sampled = temps > 0.0
                        n_acc = jnp.where(is_sampled, 0, n_acc)
                        bonus = jnp.where(is_sampled, sampled0, bonus)

                    # ---- ordered emission [B, K+1]: accepted drafts then
                    # the bonus; -1 pads the rejected tail. The host
                    # replays stop/budget truncation from this record; the
                    # device mirrors it below only to gate later rounds.
                    acc_pad = jnp.concatenate(
                        [chunk[:, 1:], jnp.zeros((b, 1), jnp.int32)],
                        axis=1,
                    )
                    emitted = jnp.where(
                        offs < n_acc[:, None], acc_pad,
                        jnp.where(offs == n_acc[:, None],
                                  bonus[:, None], -1),
                    )
                    emitted = jnp.where(act[:, None], emitted, -1)

                    # ---- device stop/budget masking (gates later rounds;
                    # same construction as the tree decoder's scan)
                    is_stop = (
                        (emitted[:, :, None] == stops[:, None, :]).any(-1)
                        & (emitted >= 0)
                    )
                    cum = jnp.cumsum(is_stop.astype(jnp.int32), axis=1)
                    pre_stop = (cum - is_stop.astype(jnp.int32)) == 0
                    emit_j = (emitted >= 0) & pre_stop & ~is_stop
                    rank = jnp.cumsum(emit_j.astype(jnp.int32), axis=1) \
                        - emit_j.astype(jnp.int32)
                    emit_mask = emit_j & (
                        n_emit[:, None] + rank < budgets[:, None]
                    )
                    n_emit2 = n_emit + emit_mask.sum(axis=1)
                    stop_hit = (is_stop & pre_stop).any(axis=1)

                    # ---- advance slot state; freeze rows whose next
                    # window no longer fits the reservation
                    new_h = jnp.take_along_axis(
                        out.hidden, n_acc[:, None, None].astype(jnp.int32),
                        axis=1,
                    )[:, 0, :]
                    lens2 = jnp.where(act, lens + n_acc + 1, lens)
                    pending2 = jnp.where(act, bonus, pending)
                    h2 = jnp.where(act[:, None], new_h, h)
                    done2 = done | (
                        act & (stop_hit | (n_emit2 >= budgets)
                               | (lens2 + 2 > caps))
                    )
                    rec = (emitted, n_acc, act)
                    return (out.kv, lens2, pending2, h2, done2, n_emit2), rec

                (kv, lens, pending, h_last, _done, _n), recs = jax.lax.scan(
                    body,
                    (kv, core["lens"], core["last"], h_last, ~active,
                     jnp.zeros_like(core["lens"])),
                    forced_rounds, length=rounds,
                )
                core = dict(core)
                core["lens"], core["last"] = lens, pending
                return kv, core, h_last, recs

            self._spec_rounds_fn = jax.jit(
                spec_rounds, static_argnames=("rounds", "mode"),
                donate_argnums=(2, 3, 4),
            )

            # --- spec RAGGED round (round 8): ONE dispatch whose row batch
            # mixes VERIFY rows (the draft chain + pending token,
            # q_len = 2..K+1, one per active decode slot) with admission
            # prefill-chunk rows — the spec engine's analogue of
            # ragged_round, so admission stops being a competing dispatch
            # for speculating engines too. One round per dispatch (the
            # host replays stop/budget bookkeeping from the emission
            # record, exactly like the fused scan's per-round replay);
            # pure-decode moments keep the deeper _spec_rounds_fn scan.
            # The LM head reads a GATHERED [B, K+1] hidden slice (chain
            # offsets for verify rows, the last valid chunk index for
            # admission rows) — never the full [B, S, V] chunk width.
            def spec_ragged_round(params, dp, kv, toks_pos, tables,
                                  lens_after, core, h_last, spec_row,
                                  sample_flag, ks, caps, forced, mode):
                keys, temps = core["keys"], core["temps"]
                top_ks, top_ps = core["top_ks"], core["top_ps"]
                offs = jnp.arange(spec_k + 1, dtype=jnp.int32)[None, :]
                lens = core["lens"]
                b, s_w = toks_pos[0].shape

                # ---- draft + row merge: verify rows overwrite their
                # chunk columns with the chain; chunk rows keep toks_pos
                chunk = draft_chain(params, dp, core["last"], h_last)
                pos_spec = lens[:, None] + offs
                pos_spec = jnp.where(
                    spec_row[:, None] & (offs <= ks[:, None])
                    & (pos_spec < caps[:, None]), pos_spec, -1
                )
                pad = ((0, 0), (0, s_w - (spec_k + 1)))
                chain_w = jnp.pad(chunk, pad)
                pos_spec_w = jnp.pad(pos_spec, pad, constant_values=-1)
                token_ids = jnp.where(
                    spec_row[:, None], chain_w, toks_pos[0]
                )
                positions = jnp.where(
                    spec_row[:, None], pos_spec_w, toks_pos[1]
                )
                kv_lens_row = jnp.where(
                    spec_row, lens + ks + 1, lens_after
                ).astype(jnp.int32)
                out = llama.forward_chunk(
                    cfg, params, token_ids, positions, kv, tables,
                    kv_lens_row, block_size=bs, last_only=False,
                    with_logits=False, allow_fused=False,
                )

                # ---- gathered logits: chain offsets for verify rows, the
                # last valid index (forward_chunk's last_only rule) for
                # chunk rows — identical arithmetic to the split paths,
                # so greedy chunk rows stay byte-identical to
                # _plain_ragged_round's in-graph sample
                n_valid = jnp.sum((positions >= 0).astype(jnp.int32),
                                  axis=1)
                last_idx = jnp.maximum(n_valid - 1, 0)
                gidx = jnp.where(
                    spec_row[:, None],
                    jnp.minimum(offs, s_w - 1),
                    last_idx[:, None],
                )                                              # [B, K+1]
                hsel = jnp.take_along_axis(
                    out.hidden, gidx[:, :, None].astype(jnp.int32), axis=1
                )                                              # [B, K+1, H]
                logits = llama.project_logits(cfg, params, hsel)
                target_pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)

                # ---- acceptance (verify rows) + sample (chunk-final and
                # sampled rows). Sample positions: lens + 1 for verify
                # rows, lens_after for chunk rows — the split paths' key
                # folds exactly.
                n_acc = accept_chain(
                    chunk, target_pred, ks, forced, lens, caps, offs
                )
                bonus = jnp.take_along_axis(
                    target_pred, n_acc[:, None], axis=1
                )[:, 0]
                samp_pos = jnp.where(spec_row, lens + 1, lens_after)
                tok0 = sample_mode(
                    logits[:, 0, :], keys, samp_pos, temps, top_ks,
                    top_ps, mode,
                )
                if mode == "mixed":
                    # sampled slots ride the round at one token: sample
                    # from the pending token's logits, never accept drafts
                    is_sampled = temps > 0.0
                    n_acc = jnp.where(is_sampled & spec_row, 0, n_acc)
                    bonus = jnp.where(is_sampled, tok0, bonus)

                # ---- ordered emission record [B, K+1] for the host
                # replay: accepted drafts then the bonus; -1 pads
                acc_pad = jnp.concatenate(
                    [chunk[:, 1:], jnp.zeros((b, 1), jnp.int32)], axis=1
                )
                emitted = jnp.where(
                    offs < n_acc[:, None], acc_pad,
                    jnp.where(offs == n_acc[:, None], bonus[:, None], -1),
                )
                emitted = jnp.where(spec_row[:, None], emitted, -1)

                # ---- advance device state: verify rows commit n_acc + 1
                # and carry the bonus pending; chunk-final rows commit
                # their sampled first token; intermediate chunks only
                # wrote KV
                new_h = jnp.take_along_axis(
                    hsel, n_acc[:, None, None].astype(jnp.int32), axis=1
                )[:, 0, :]
                sampled = sample_flag > 0
                core = dict(core)
                core["lens"] = jnp.where(
                    spec_row, lens + n_acc + 1,
                    jnp.where(sampled, lens_after, lens),
                )
                core["last"] = jnp.where(
                    spec_row, bonus,
                    jnp.where(sampled, tok0, core["last"]),
                )
                h2 = jnp.where(spec_row[:, None], new_h, h_last)
                return out.kv, core, h2, tok0, emitted, n_acc

            self._spec_ragged_round_fn = jax.jit(
                spec_ragged_round, static_argnames=("mode",),
                donate_argnums=(2, 6, 7),
            )

            def unpack_spec_sched(si):
                # one packed upload per spec ragged round: tables,
                # spec_row, sample_flag, ks, caps, forced
                return (si[:, :m], si[:, m] > 0, si[:, m + 1],
                        si[:, m + 2], si[:, m + 3], si[:, m + 4])

            self._unpack_spec_sched_fn = jax.jit(unpack_spec_sched)

        def apply_ops(kv, srcs, dsts):
            # page copies (CoW): dst = -1 entries are dropped. Scale pools
            # (int8 KV) copy with their pages — a page without its scale is
            # garbage
            out = {
                "k": kv["k"].at[:, dsts].set(kv["k"][:, srcs], mode="drop"),
                "v": kv["v"].at[:, dsts].set(kv["v"][:, srcs], mode="drop"),
            }
            for sk in ("k_scale", "v_scale"):
                if sk in kv:
                    out[sk] = kv[sk].at[:, dsts].set(
                        kv[sk][:, srcs], mode="drop"
                    )
            return out

        self._apply_ops_fn = jax.jit(apply_ops, donate_argnums=(0,))

    # ------------------------------------------------------- device helpers

    def _pack_core(self) -> Tuple[np.ndarray, np.ndarray]:
        b = len(self.slots)
        ci = np.zeros((b, _CORE_I_COLS), np.int32)
        ci[:, 0] = self._last_tokens
        ci[:, 1] = self._kv_lens
        ci[:, 2:4] = self._slot_keys.view(np.int32)
        ci[:, 4:4 + MAX_STOP_IDS] = self._stop_ids
        ci[:, 4 + MAX_STOP_IDS] = self._top_ks
        cf = np.stack([self._temps, self._top_ps], axis=1).astype(np.float32)
        return ci, cf

    def _sync_core(self) -> Dict[str, jax.Array]:
        """Upload host slot mirrors to device — only when a host-initiated
        change (admission / adopt / error recovery) made them stale. Decode
        rounds advance the device copy in-graph, so steady-state serving
        never re-uploads."""
        if self._core_dirty or self._dev_core is None:
            ci, cf = self._pack_core()
            self._dev_core = self._unpack_core_fn(ci, cf)
            self._core_dirty = False
        return self._dev_core

    def _sched_arrays(
        self, active_mask: np.ndarray, budgets: np.ndarray
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Per-round scheduling state (block tables, active mask, budgets)
        as ONE packed upload — tables grow most rounds, so these always ship."""
        mm = self.cfg.max_blocks_per_seq
        si = np.zeros((len(self.slots), mm + 2), np.int32)
        si[:, :mm] = self._block_tables
        si[:, mm] = active_mask
        si[:, mm + 1] = budgets
        return self._unpack_sched_fn(si)

    def _decode_mode(self) -> str:
        for i, s in enumerate(self.slots):
            if s is not None and s.finish_reason is None \
                    and not s.prefilling and self._temps[i] > 0:
                return "mixed"
        return "greedy"

    def _invalidate_device_state(self) -> None:
        """A failed donated call may have consumed the device core buffers —
        rebuild from host mirrors on next use. The speculative draft hidden
        rebuilds as zeros: that only lowers the next step's acceptance,
        never correctness (emission is always target-verified)."""
        self._dev_core = None
        self._core_dirty = True
        self._dev_spec_h = None

    def _spec_h_device(self) -> jax.Array:
        """Per-slot last-verified hidden for the draft head, device-resident
        between rounds; rebinds/invalidations zero the affected rows."""
        if self._dev_spec_h is None:
            self._dev_spec_h = jnp.zeros(
                (len(self.slots), self.model_cfg.hidden_size), self.dtype
            )
            self._spec_h_zero.clear()
        elif self._spec_h_zero:
            # fixed-shape mask multiply, NOT .at[rows].set — a dynamic row
            # list would compile one scatter per distinct stale-set size
            keep = np.ones((len(self.slots), 1), np.float32)
            keep[sorted(self._spec_h_zero)] = 0.0
            self._dev_spec_h = self._dev_spec_h * jnp.asarray(
                keep, self.dtype
            )
            self._spec_h_zero.clear()
        return self._dev_spec_h

    def _apply_pending(self) -> None:
        ops = self.manager.take_pending_ops()
        if ops.empty:
            return
        # downloads FIRST: an evicted block's id is about to be reused, so
        # its page must reach the host store before any copy/upload/prefill
        # can overwrite it
        for bid, key in ops.downloads:
            k = np.asarray(self.kv["k"][:, bid])
            v = np.asarray(self.kv["v"][:, bid])
            scale_page = None
            if "k_scale" in self.kv:
                # an int8 page without its scale is garbage: spill them as
                # a pair (manager stores the scale under the paired key)
                ks = np.asarray(self.kv["k_scale"][:, bid])
                vs = np.asarray(self.kv["v_scale"][:, bid])
                scale_page = np.stack([ks, vs], axis=1)
            self.manager.store_spilled(
                key, np.stack([k, v], axis=1), scale_page
            )
        if ops.copies:
            n = len(ops.copies)
            bucket = next(c for c in _COPY_BUCKETS if c >= n) if n <= _COPY_BUCKETS[-1] else n
            srcs = np.zeros((bucket,), np.int32)
            # pad with an OUT-OF-RANGE id (num_blocks): -1 would wrap to the
            # last block instead of being dropped
            dsts = np.full((bucket,), self.num_blocks, np.int32)
            for i, (s, d) in enumerate(ops.copies):
                srcs[i], dsts[i] = s, d
            self.kv = self._apply_ops_fn(self.kv, jnp.asarray(srcs), jnp.asarray(dsts))
        for dst, host_kv in ops.uploads:
            k = jnp.asarray(host_kv[:, 0], dtype=self.kv_dtype)
            v = jnp.asarray(host_kv[:, 1], dtype=self.kv_dtype)
            self.kv = {
                **self.kv,
                "k": self.kv["k"].at[:, dst].set(k),
                "v": self.kv["v"].at[:, dst].set(v),
            }
        for dst, host_sc in ops.scale_uploads:
            ks = jnp.asarray(host_sc[:, 0], jnp.bfloat16)
            vs = jnp.asarray(host_sc[:, 1], jnp.bfloat16)
            self.kv = {
                **self.kv,
                "k_scale": self.kv["k_scale"].at[:, dst].set(ks),
                "v_scale": self.kv["v_scale"].at[:, dst].set(vs),
            }

    def _bucket_len(self, n: int) -> int:
        for b in self.cfg.prefill_buckets:
            if b >= n:
                return b
        raise ValueError(
            f"prompt chunk of {n} tokens exceeds largest prefill bucket "
            f"{self.cfg.prefill_buckets[-1]}"
        )

    # -------------------------------------------------------- slot API

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    # ------------------------------------------- KV pressure + preemption

    def _signal_pressure(self, source: str, slots: Sequence[int] = (),
                         requests: int = 0) -> None:
        """Record a step-boundary KV-pressure event for the scheduler. One
        ``KVPressure`` accumulates per engine round; "decode" outranks
        "admission" (decode pressure blocks progress, admission can wait)."""
        if self._pressure is None:
            self._pressure = KVPressure(source=source)
            self.stats["kv_pressure_events"] += 1
        elif source == "decode":
            self._pressure.source = "decode"
        for sl in slots:
            if sl not in self._pressure.slots:
                self._pressure.slots.append(sl)
        self._pressure.requests += requests

    def request_fits_pool(self, request: InferenceRequest) -> bool:
        """Static admissibility check: can the request's PROMPT (plus its
        pending first token, and the speculative verify window when the
        engine speculates) fit an idle pool? A request failing this can
        never even be admitted — the one case a capacity error
        legitimately reaches the client immediately.

        Deliberately NOT a worst-case (prompt + max_new_tokens) test:
        max_new_tokens is a cap, not a promise — most generations stop at
        EOS far earlier, so pre-rejecting on the cap would break every
        generous-cap/short-output workload that served fine. Growth beyond
        the pool is a DYNAMIC condition the preemption machinery absorbs,
        bounded by the scheduler's preemption/resume caps."""
        return self._fits_empty_pool(len(request.prompt_token_ids or []) + 1)

    def _fits_empty_pool(self, tokens: int) -> bool:
        """One fit rule for admission AND resume: ``tokens`` context (+
        the speculative verify window) against the whole pool minus the
        reserved pad block — the two callers must never disagree about
        what fits."""
        if self.cfg.speculative is not None:
            tokens += self.cfg.speculative.num_draft_tokens + 1
        need = -(-tokens // self.cfg.block_size)
        return need <= self.num_blocks - 1   # block 0 is the reserved pad

    def resume_fits_pool(self, pre: "PreemptedSequence") -> bool:
        """Static admissibility of a RESUME: the preempted sequence's
        prompt + already-generated context + pending token (+ the spec
        verify window) against an EMPTY pool. Only a sequence failing
        this can never be re-admitted — an allocation failure on a
        statically-fitting resume is a dynamic condition (cache eviction
        in flight, a transient allocator fault injected by chaos, another
        admission racing) and must be retried, not aborted: the fleet
        chaos suite showed a 2-second injected pressure storm permanently
        killing requests the pool could trivially hold a moment later."""
        return self._fits_empty_pool(pre.prompt_len + len(pre.generated) + 1)

    def take_pressure(self) -> Optional[KVPressure]:
        """Consume the pending pressure signal (None when the last round
        ran unpressured). The scheduler calls this after every engine round
        / admission attempt and reacts per its preemption policy."""
        p, self._pressure = self._pressure, None
        return p

    def snapshot_slot(self, slot: int) -> PreemptedSequence:
        """Non-destructive checkpoint of a LIVE slot: the same portable state
        :meth:`preempt_slot` captures, but the slot keeps decoding. This is
        the worker-failover checkpoint source — the snapshot rides to the
        control plane and, should this worker die, :meth:`resume` on a
        replacement engine recomputes the uncached suffix and continues
        byte-identically (greedy) / seed-stably (sampled).

        ``generated`` may include the pending token (sampled, KV unwritten);
        resume treats the whole list as prompt suffix and recomputes, so the
        distinction never leaks. Mid-prefill and finished slots have nothing
        useful to checkpoint and are rejected."""
        s = self.slots[slot]
        if s is None:
            raise ValueError(f"slot {slot} is empty")
        if s.prefilling:
            raise ValueError(f"slot {slot} is mid-prefill")
        if s.finish_reason is not None:
            raise ValueError(f"slot {slot} already finished")
        return PreemptedSequence(
            request=s.request,
            prompt_len=s.prompt_len,
            generated=list(s.generated),
            slot_key=(int(self._slot_keys[slot, 0]),
                      int(self._slot_keys[slot, 1])),
            start_time=s.start_time,
            first_token_time=s.first_token_time,
            cached_tokens=s.cached_tokens,
        )

    def preempt_slot(self, slot: int) -> PreemptedSequence:
        """Freeze a RUNNING sequence and release its device blocks — the
        recovery half of KV-pressure handling. Full blocks are freed
        through ``free_sequence(cache=True)``: they park in the prefix
        cache and, when evicted under further pressure, spill to the
        host/remote tiers — so :meth:`resume` restores them via the radix
        index or ``_probe_spill`` instead of recomputing the whole context.

        The sequence's pending token (sampled but its KV not yet written)
        is dropped from the manager's token log first, so only fully
        written blocks can be cached/spilled; it stays in ``generated`` and
        is recomputed by the resume prefill."""
        s = self.slots[slot]
        if s is None:
            raise ValueError(f"slot {slot} is empty")
        if s.prefilling:
            raise ValueError(
                f"slot {slot} is mid-prefill (chunked admission) — abort it "
                "with abort_chunked instead of preempting"
            )
        if s.finish_reason is not None:
            raise ValueError(
                f"slot {slot} already finished ({s.finish_reason}) — use "
                "finish_slot"
            )
        seq = self.manager.seq_tokens[s.seq_id]
        committed = int(self._kv_lens[slot])
        while len(seq) > committed:
            seq.pop()
        # drop reserved tail blocks (spec windows, multi-step horizons) so
        # the freed footprint is exactly the committed context
        self.manager.trim_reserved(s.seq_id)
        pre = PreemptedSequence(
            request=s.request,
            prompt_len=s.prompt_len,
            generated=list(s.generated),
            slot_key=(int(self._slot_keys[slot, 0]),
                      int(self._slot_keys[slot, 1])),
            start_time=s.start_time,
            first_token_time=s.first_token_time,
            cached_tokens=s.cached_tokens,
        )
        self.manager.free_sequence(s.seq_id, cache=True)
        self.slots[slot] = None
        self._kv_lens[slot] = 0
        self._core_dirty = True
        if self.cfg.speculative is not None:
            self._spec_h_zero.add(slot)
        self.stats["preemptions"] += 1
        return pre

    def resume(self, pre: PreemptedSequence,
               slot: Optional[int] = None) -> int:
        """Re-admit a preempted sequence through the normal allocation +
        prefill path. The resume prompt is the original prompt plus every
        generated token: ``allocate_sequence`` restores whatever prefix the
        cache/spill tiers still hold and the prefill recomputes only the
        uncached suffix. Greedy continuations are byte-identical to a
        never-preempted run; sampled continuations are seed-stable because
        the slot's PRNG key is restored verbatim and the sampler folds in
        the absolute position.

        Raises OutOfBlocksError (state untouched) when the pool still
        cannot hold the sequence — the scheduler retries later."""
        sp = pre.request.sampling
        remaining = sp.max_new_tokens - len(pre.generated)
        if remaining <= 0:
            raise ValueError("preempted sequence has no remaining budget")
        token_ids = list(pre.request.prompt_token_ids or []) + \
            list(pre.generated)
        # the preserved key words round-trip through SamplingParams.seed:
        # _bind_slot unpacks PRNGKey-style [seed >> 32, seed & 0xffffffff]
        seed = (pre.slot_key[0] << 32) | pre.slot_key[1]
        derived = replace(
            pre.request,
            prompt_token_ids=token_ids,
            session_id=None,
            sampling=replace(sp, max_new_tokens=remaining, seed=seed),
        )
        slot = self.submit(derived, slot=slot)
        s = self.slots[slot]
        assert s is not None
        # restore the client-visible identity: the ORIGINAL request (decode
        # budgets are max_new_tokens minus the FULL generated list), prompt
        # accounting, and the TTFT clock origin
        s.request = pre.request
        s.prompt_len = pre.prompt_len
        s.generated = list(pre.generated) + s.generated
        s.cached_tokens = pre.cached_tokens
        s.start_time = pre.start_time
        if pre.first_token_time is not None:
            s.first_token_time = pre.first_token_time
        self.stats["requests"] -= 1          # not a new client request
        self.stats["resumes"] += 1
        return slot

    def _validate_request(self, request: InferenceRequest) -> List[int]:
        token_ids = request.prompt_token_ids
        if not token_ids:
            raise ValueError("request has no prompt_token_ids")
        if len(token_ids) + request.sampling.max_new_tokens > self.cfg.max_seq_len:
            raise RequestOverLength(
                f"prompt {len(token_ids)} + max_new {request.sampling.max_new_tokens}"
                f" exceeds max_seq_len {self.cfg.max_seq_len}"
            )
        return token_ids

    def submit(self, request: InferenceRequest, slot: Optional[int] = None) -> int:
        """Admit a request into a slot: allocate blocks (prefix-cache aware),
        run prefill, sample the first token. Returns the slot index."""
        if slot is None:
            free = self.free_slots()
            if not free:
                raise RuntimeError("no free slots")
            slot = free[0]
        if self.slots[slot] is not None:
            raise RuntimeError(f"slot {slot} busy")
        token_ids = self._validate_request(request)
        seq_id = request.session_id or uuid.uuid4().hex
        try:
            blocks, cached = self.manager.allocate_sequence(seq_id, token_ids)
        except OutOfBlocksError:
            # allocate_sequence scrubbed its own staging: state is clean,
            # the caller sees a pressure signal + typed error, never a
            # half-admitted sequence
            self._signal_pressure("admission", requests=1)
            raise
        try:
            return self._submit_allocated(request, slot, seq_id, token_ids, cached)
        except Exception as exc:
            self.slots[slot] = None
            self._kv_lens[slot] = 0
            self.manager.free_sequence(seq_id, cache=False)
            if isinstance(exc, OutOfBlocksError):
                self._signal_pressure("admission", requests=1)
            raise

    def submit_batch(self, requests: Sequence[InferenceRequest],
                     partial: bool = False) -> List[int]:
        """Admit several requests at once: same-bucket prefills run as ONE
        batched device call (full batch width, inactive rows masked with
        position -1). On a remote-tunnel TPU each device call costs a full
        control round-trip, so per-request prefill serializes admission —
        this path admits a whole wave for one RTT. Long prompts that need
        chunking fall back to the per-request chunked path.

        ``partial``: when KV blocks run out mid-wave, admit the prefix of
        the wave that DID allocate and return only its slots (a pressure
        signal marks the deferred tail) instead of rolling the whole wave
        back — the batcher requeues the tail with no client-visible error.
        With ``partial=False`` (default) exhaustion rolls back the whole
        wave and raises ``OutOfBlocksError`` after signalling pressure;
        state is clean either way."""
        if not requests:
            return []
        free = self.free_slots()
        if len(requests) > len(free):
            raise RuntimeError(
                f"{len(requests)} requests > {len(free)} free slots"
            )
        max_bucket = self.cfg.prefill_buckets[-1]
        slots_out: List[int] = []
        grouped: Dict[int, List[Tuple[InferenceRequest, int, str, List[int], int]]] = {}
        admitted: List[Tuple[int, str]] = []  # (slot, seq_id) for cleanup
        stats_snapshot = {
            k: self.stats[k]
            for k in ("requests", "prefill_tokens", "prefill_calls",
                      "generated_tokens")
        }
        mgr_stats_snapshot = dict(self.manager.stats.__dict__)
        downloads_before = len(self.manager.pending.downloads)
        interleaved_extra = 0   # decode tokens emitted to non-wave slots

        def _rollback() -> None:
            for slot, seq_id in admitted:
                self.slots[slot] = None
                self._kv_lens[slot] = 0
                if seq_id in self.manager.seq_blocks:
                    self.manager.free_sequence(seq_id, cache=False)
            # pending device ops staged for now-freed blocks must not apply
            # later: a freed id gets reallocated, and an orphaned upload or
            # CoW copy would clobber the new owner's pages (allocate_sequence
            # scrubs its own staging on OutOfBlocksError the same way).
            # Downloads are NOT filtered: a spill-on-evict download's source
            # block is popped from metas when staged, and dropping it would
            # lose the evicted page's only copy.
            alive = self.manager.metas
            p = self.manager.pending
            p.uploads = [u for u in p.uploads if u[0] in alive]
            p.scale_uploads = [u for u in p.scale_uploads if u[0] in alive]
            p.copies = [
                c for c in p.copies if c[0] in alive and c[1] in alive
            ]
            # stats must not double-count requests a retry will re-admit —
            # engine counters and the manager's cache stats alike. Spills
            # staged by this wave survive the rollback (their downloads are
            # kept above), so those stay counted.
            kept_wave_spills = len(p.downloads) - downloads_before
            self.stats.update(stats_snapshot)
            self.manager.stats.__dict__.update(mgr_stats_snapshot)
            self.manager.stats.spills += max(kept_wave_spills, 0)

        try:
            for request, slot in zip(requests, free):
                token_ids = self._validate_request(request)
                seq_id = request.session_id or uuid.uuid4().hex
                try:
                    _, cached = self.manager.allocate_sequence(
                        seq_id, token_ids
                    )
                except OutOfBlocksError:
                    # step-boundary pressure: allocate_sequence scrubbed its
                    # own staging, nothing of THIS request is admitted
                    deferred = len(requests) - len(slots_out)
                    self._signal_pressure("admission", requests=deferred)
                    if not partial:
                        raise
                    break   # admit the prefix that allocated; tail deferred
                admitted.append((slot, seq_id))
                slots_out.append(slot)
                n_fresh = len(token_ids) - cached
                if n_fresh > max_bucket or (
                    self.cfg.kv_seq_sharded and cached > 0
                ):
                    # chunked long-prompt path (per request). Sharded pools
                    # also route CACHED prompts here: the batched/sub-wave
                    # prefill graphs attend dense over the chunk only, which
                    # cannot see a cached prefix — the chunked path reads it
                    # through the sharded-pool chunk op.
                    self._submit_allocated(request, slot, seq_id, token_ids, cached)
                    continue
                bucket = self._bucket_len(max(n_fresh, 1))
                grouped.setdefault(bucket, []).append(
                    (request, slot, seq_id, token_ids, cached)
                )

            b = len(self.slots)
            sw = self.cfg.admission_subwave
            groups = sorted(grouped.items())
            if sw > 0:
                # SUB-WAVE admission (VERDICT r2 #3): chunks of ≤ sw
                # sequences prefill through a width-bucketed narrow graph;
                # each chunk samples its first tokens as soon as ITS prefill
                # lands, so p50 TTFT scales with the sub-wave, not the wave.
                # Optionally a bounded decode round runs between chunks so
                # already-generating slots never stall for a whole admission.
                wave_slots = {s_ for s_, _ in admitted}
                chunks: List[Tuple[int, list]] = []
                for bucket, items in groups:
                    for i0 in range(0, len(items), sw):
                        chunks.append((bucket, items[i0:i0 + sw]))
                k = self.cfg.admission_interleave_steps
                if k > 0:
                    for ci, (bucket, chunk) in enumerate(chunks):
                        self._commit_subwave(
                            chunk, self._prefill_subwave(bucket, chunk)
                        )
                        if ci < len(chunks) - 1:
                            out = self.decode_multi(k)
                            # count only tokens _record_token counted: an
                            # emitted stop token ends the slot WITHOUT
                            # incrementing generated_tokens
                            for sl, t in out.items():
                                if sl in wave_slots:
                                    continue
                                s_ = self.slots[sl]
                                stop = (
                                    1 if s_ is not None
                                    and s_.finish_reason == "stop" else 0
                                )
                                interleaved_extra += len(t) - stop
                else:
                    # pipelined staggering: dispatch every narrow prefill
                    # back-to-back (async dispatch — the device queue runs
                    # them in order), then read first tokens chunk by chunk.
                    # Chunk c's tokens reach the host as soon as ITS compute
                    # lands while later chunks are still running, so the
                    # TTFT stagger costs ~no wall-clock vs one wide call.
                    dispatched = [
                        (chunk, self._prefill_subwave(bucket, chunk))
                        for bucket, chunk in chunks
                    ]
                    for chunk, first in dispatched:
                        self._commit_subwave(chunk, first)
            else:
                for bucket, items in groups:
                    self._apply_pending()
                    toks_pos = np.zeros((2, b, bucket), np.int32)
                    toks_pos[1] = -1
                    lens = np.zeros((b,), np.int32)
                    wave = np.zeros((b,), bool)
                    for request, slot, seq_id, token_ids, cached in items:
                        s = _Slot(request=request, seq_id=seq_id,
                                  prompt_len=len(token_ids),
                                  cached_tokens=cached)
                        self._bind_slot(slot, s, kv_len=len(token_ids))
                        fresh = token_ids[cached:]
                        n = len(fresh)
                        toks_pos[0, slot, :n] = fresh
                        toks_pos[1, slot, :n] = np.arange(cached, cached + n)
                        lens[slot] = cached + n
                        wave[slot] = True
                        self.stats["prefill_tokens"] += n
                    mode = (
                        "greedy"
                        if all(it[0].sampling.temperature <= 0 for it in items)
                        else "mixed"
                    )
                    core = self._sync_core()
                    first, self._dev_core, self.kv = self._prefill_batch_fn(
                        self.params, self.kv, toks_pos, self._block_tables,
                        lens, core, wave, mode,
                    )
                    self.stats["prefill_calls"] += 1
                    first_np = np.asarray(first)
                    for request, slot, seq_id, token_ids, cached in items:
                        self._record_token(
                            slot, int(first_np[slot]), device_synced=True
                        )
        except Exception as exc:
            # a failed wave must not leak: every sequence this call admitted
            # (bound or not) is freed so a retry sees clean state
            self._invalidate_device_state()
            _rollback()
            # interleaved decode tokens that went to slots OUTSIDE this wave
            # really happened and survive the rollback
            self.stats["generated_tokens"] += interleaved_extra
            if isinstance(exc, OutOfBlocksError):
                self._signal_pressure(
                    "admission", requests=len(requests)
                )
            raise
        return slots_out

    def _prefill_subwave(self, bucket: int, chunk: list):
        """Prefill ≤ admission_subwave sequences through a width-bucketed
        narrow graph (the width-generic ``_prefill_chunk_fn``), sampling
        their first tokens in-graph. Pad rows carry position -1 everywhere
        (KV writes dropped) and their sampled garbage is never read."""
        self._apply_pending()
        w = 1
        while w < len(chunk):
            w *= 2
        w = min(w, len(self.slots))
        mm = self.cfg.max_blocks_per_seq
        toks_pos = np.zeros((2, w, bucket), np.int32)
        toks_pos[1] = -1
        tables = np.zeros((w, mm), np.int32)
        lens = np.zeros((w,), np.int32)
        keys = np.zeros((w, 2), np.uint32)
        temps = np.zeros((w,), np.float32)
        top_ks = np.zeros((w,), np.int32)
        top_ps = np.ones((w,), np.float32)
        for j, (request, slot, seq_id, token_ids, cached) in enumerate(chunk):
            s = _Slot(request=request, seq_id=seq_id,
                      prompt_len=len(token_ids), cached_tokens=cached)
            self._bind_slot(slot, s, kv_len=len(token_ids))
            fresh = token_ids[cached:]
            n = len(fresh)
            toks_pos[0, j, :n] = fresh
            toks_pos[1, j, :n] = np.arange(cached, cached + n)
            lens[j] = cached + n
            tables[j] = self._block_tables[slot]
            keys[j] = self._slot_keys[slot]
            temps[j] = self._temps[slot]
            top_ks[j] = self._top_ks[slot]
            top_ps[j] = self._top_ps[slot]
            self.stats["prefill_tokens"] += n
        mode = (
            "greedy"
            if all(it[0].sampling.temperature <= 0 for it in chunk)
            else "mixed"
        )
        first, self.kv = self._prefill_chunk_fn(
            self.params, self.kv, toks_pos, tables, lens, keys, temps,
            top_ks, top_ps, mode, True,
        )
        self.stats["prefill_calls"] += 1
        return first

    def _commit_subwave(self, chunk: list, first) -> None:
        """Read a sub-wave's first tokens (blocks until its prefill lands)
        and account them — the point each sequence's TTFT clock stops."""
        first_np = np.asarray(first)
        for j, (request, slot, seq_id, token_ids, cached) in enumerate(chunk):
            self._record_token(slot, int(first_np[j]))

    def _bind_slot(self, slot: int, s: "_Slot", kv_len: int) -> None:
        """Install slot state (block table, committed length, sampling, stop
        ids) for a sequence already allocated in the manager. Shared by the
        prefill submit path and the PD-handoff adopt path so the two can
        never drift."""
        self.slots[slot] = s
        self._block_tables[slot] = self.manager.block_table_for(
            s.seq_id, self.cfg.max_blocks_per_seq
        )
        self._kv_lens[slot] = kv_len
        sp = s.request.sampling
        self._temps[slot] = sp.temperature
        self._top_ks[slot] = sp.top_k
        self._top_ps[slot] = sp.top_p
        self._stop_ids[slot] = -1
        # ignore_eos (bench/oracle workloads): no stop ids at all — the
        # generation runs to its max_new_tokens budget
        stop = [] if sp.ignore_eos else list(sp.stop_token_ids)[:MAX_STOP_IDS]
        if self.eos_token_id is not None and self.eos_token_id not in stop \
                and len(stop) < MAX_STOP_IDS and not sp.ignore_eos:
            stop.append(self.eos_token_id)
        self._stop_ids[slot, : len(stop)] = stop
        # host-side key material (no device round-trip on the admission hot
        # path): threefry PRNGKey(seed) is [seed >> 32, seed & 0xffffffff]
        if sp.seed is not None:
            seed_val = int(sp.seed)
            self._slot_keys[slot] = (
                (seed_val >> 32) & 0xFFFFFFFF, seed_val & 0xFFFFFFFF
            )
        else:
            self._slot_keys[slot] = self._host_rng.integers(
                0, 2**32, size=2, dtype=np.uint32
            )
        self._core_dirty = True
        if self.cfg.speculative is not None:
            # fresh occupant: its draft feature starts at zeros (stale
            # hidden would only cost acceptance, never correctness — but
            # deterministic stats want a clean start), its adaptive-depth
            # EMA restarts optimistic at K, and its oracle dither resets
            self._spec_h_zero.add(slot)
            self._spec_k_ema[slot] = float(
                self.cfg.speculative.num_draft_tokens
            )
            self._spec_oracle_acc[slot] = 0.0
        self.stats["requests"] += 1

    def _submit_allocated(self, request: InferenceRequest, slot: int,
                          seq_id: str, token_ids: List[int], cached: int) -> int:
        self._apply_pending()
        s = _Slot(request=request, seq_id=seq_id, prompt_len=len(token_ids),
                  cached_tokens=cached)
        self._bind_slot(slot, s, kv_len=len(token_ids))

        # CHUNKED prefill of the uncached suffix: prompts longer than the
        # largest bucket split into full-bucket pieces + a bucketed tail, so
        # long contexts need no giant compile and no dynamic shapes
        # (reference delegates this to vLLM's chunked-prefill flag,
        # llm_vllm.py:61 — first-party here). Each chunk attends to all
        # prior context via kv_len_after; only the final chunk's logits
        # (the last prompt token) are consumed.
        fresh = token_ids[cached:]
        max_bucket = self.cfg.prefill_buckets[-1]
        off = cached
        mode = "greedy" if request.sampling.temperature <= 0 else "mixed"
        if (
            self._seq_axis > 1
            and cached == 0
            and len(fresh) > max_bucket
        ):
            # sequence-parallel long-context prefill (mesh seq axis)
            first = self._prefill_seq_parallel(slot, fresh, mode)
            tok = int(np.asarray(first)[0])
            self._record_token(slot, tok)
            return slot
        first = None
        while True:
            piece = fresh[: max_bucket]
            fresh = fresh[max_bucket:]
            is_last = not fresh
            first = self._prefill_one_chunk(slot, piece, off, is_last, mode)
            off += len(piece)
            if is_last:
                break

        tok = int(np.asarray(first)[0])
        self._record_token(slot, tok)
        return slot

    def _prefill_seq_parallel(self, slot: int, fresh: List[int], mode: str):
        """Whole-prompt seq-sharded prefill (mesh ``seq`` axis): ring/ulysses
        attention spreads the S² work over the axis; KV pages land in the
        same paged pools decode reads. Pad length buckets to multiples of
        (seq_axis x block_size) so long prompts compile per bucket, not per
        length."""
        n = len(fresh)
        step = self._seq_axis * max(self.cfg.block_size, 16)
        padded = -(-n // step) * step
        toks_pos = np.zeros((2, 1, padded), np.int32)
        toks_pos[1] = -1
        toks_pos[0, 0, :n] = fresh
        toks_pos[1, 0, :n] = np.arange(n)
        first, self.kv = self._prefill_seq_fn(
            self.params, self.kv, toks_pos,
            self._block_tables[slot : slot + 1],
            np.asarray([n], np.int32),
            self._slot_keys[slot : slot + 1],
            self._temps[slot : slot + 1],
            self._top_ks[slot : slot + 1],
            self._top_ps[slot : slot + 1],
            mode,
        )
        self.stats["prefill_tokens"] += n
        self.stats["prefill_calls"] += 1
        self.stats["seq_parallel_prefills"] = (
            self.stats.get("seq_parallel_prefills", 0) + 1
        )
        return first

    def _prefill_one_chunk(self, slot: int, piece: List[int], off: int,
                           is_last: bool, mode: str):
        """One single-sequence prefill chunk. The final chunk samples the
        first token IN-GRAPH (the eager sampler here used to cost ~15
        dispatch round-trips on a tunneled TPU); intermediate chunks skip
        the LM head entirely."""
        n = len(piece)
        bucket = (
            self._bucket_len(max(n, 1)) if is_last
            else self.cfg.prefill_buckets[-1]
        )
        toks_pos = np.zeros((2, 1, bucket), np.int32)
        toks_pos[1] = -1
        toks_pos[0, 0, :n] = piece
        toks_pos[1, 0, :n] = np.arange(off, off + n)
        # seq-sharded pools: a chunk with PRIOR context (cached prefix or an
        # earlier chunk) must read it through the sharded-pool chunk op; a
        # fresh first chunk keeps the cheaper dense path (off == 0 means
        # nothing precedes it)
        prefill_fn = self._prefill_chunk_fn
        if self.cfg.kv_seq_sharded and off > 0:
            prefill_fn = self._prefill_chunk_paged_fn
        first, self.kv = prefill_fn(
            self.params, self.kv, toks_pos,
            self._block_tables[slot : slot + 1],
            np.asarray([off + n], np.int32),
            self._slot_keys[slot : slot + 1],
            self._temps[slot : slot + 1],
            self._top_ks[slot : slot + 1],
            self._top_ps[slot : slot + 1],
            mode, is_last,
        )
        self.stats["prefill_tokens"] += n
        self.stats["prefill_calls"] += 1
        return first

    # ------------------------------------------- chunk-interleaved admission

    def submit_chunked_start(
        self, request: InferenceRequest, slot: Optional[int] = None
    ) -> ChunkedAdmission:
        """Begin a chunk-interleaved admission: allocate + bind the slot but
        run NO prefill yet. The slot is marked ``prefilling`` so decode
        rounds skip it until ``submit_chunked_step`` finishes the prompt."""
        if slot is None:
            free = self.free_slots()
            if not free:
                raise RuntimeError("no free slots")
            slot = free[0]
        if self.slots[slot] is not None:
            raise RuntimeError(f"slot {slot} busy")
        token_ids = self._validate_request(request)
        seq_id = request.session_id or uuid.uuid4().hex
        try:
            _, cached = self.manager.allocate_sequence(seq_id, token_ids)
        except OutOfBlocksError:
            self._signal_pressure("admission", requests=1)
            raise
        try:
            self._apply_pending()
            s = _Slot(request=request, seq_id=seq_id,
                      prompt_len=len(token_ids), cached_tokens=cached,
                      prefilling=True)
            self._bind_slot(slot, s, kv_len=len(token_ids))
        except Exception:
            self.slots[slot] = None
            self._kv_lens[slot] = 0
            self.manager.free_sequence(seq_id, cache=False)
            raise
        return ChunkedAdmission(
            request=request, slot=slot, seq_id=seq_id,
            fresh=list(token_ids[cached:]), off=cached,
            mode="greedy" if request.sampling.temperature <= 0 else "mixed",
        )

    def submit_chunked_step(self, adm: ChunkedAdmission) -> bool:
        """Run ONE prefill chunk of an in-flight admission; True once the
        admission completed (first token sampled). Work per call is bounded
        by the largest bucket, so a scheduler can interleave decode rounds
        between calls and no active slot stalls longer than one chunk."""
        if adm.done:
            return True
        s = self.slots[adm.slot]
        if s is None or s.seq_id != adm.seq_id:
            raise RuntimeError("chunked admission slot was freed")
        max_bucket = self.cfg.prefill_buckets[-1]
        if len(adm.fresh) <= max_bucket:
            # the upcoming chunk is the LAST one: it samples the first
            # token, whose pending KV block must exist. Pre-reserve it NOW
            # so exhaustion is a step-boundary retry (pressure signal,
            # chunk not consumed, caller steps again once blocks free)
            # instead of OutOfBlocksError aborting a fully-prefilled
            # admission from inside _record_token.
            try:
                if self.manager.reserve_tokens(s.seq_id, 1):
                    self._block_tables[adm.slot] = \
                        self.manager.block_table_for(
                            s.seq_id, self.cfg.max_blocks_per_seq
                        )
            except OutOfBlocksError:
                self.manager.trim_reserved(s.seq_id)
                self._signal_pressure("admission", requests=1)
                return False
        self._apply_pending()
        piece = adm.fresh[: max_bucket]
        adm.fresh = adm.fresh[max_bucket:]
        is_last = not adm.fresh
        try:
            first = self._prefill_one_chunk(
                adm.slot, piece, adm.off, is_last, adm.mode
            )
        except Exception:
            self.abort_chunked(adm)
            raise
        adm.off += len(piece)
        if is_last:
            s.prefilling = False
            tok = int(np.asarray(first)[0])
            self._record_token(adm.slot, tok)
            adm.done = True
        else:
            self._release_prefill_window(adm)
        return adm.done

    def abort_chunked(self, adm: ChunkedAdmission) -> None:
        """Release a failed/cancelled chunked admission's slot and blocks."""
        s = self.slots[adm.slot]
        adm.done = True
        if s is None or s.seq_id != adm.seq_id:
            return
        self.slots[adm.slot] = None
        self._kv_lens[adm.slot] = 0
        self.manager.free_sequence(adm.seq_id, cache=False)
        self._core_dirty = True

    # ------------------------------------------------------- ragged rounds

    @property
    def supports_ragged(self) -> bool:
        """Ragged rounds serve every paged engine except seq-sharded
        pools (whose decode rows read through a dedicated shard_map op —
        the one remaining split path). Spec-integrated engines serve
        ragged since round 8: their rounds carry VERIFY rows
        (q_len = 2..K+1 — the draft chain plus the pending token) in
        place of plain decode rows, co-dispatched with admission
        prefill-chunk rows in the same invocation, committing 1..K+1
        accepted tokens per slot at the same step boundary."""
        return not self.cfg.kv_seq_sharded

    def ragged_round(
        self, admissions: Sequence[ChunkedAdmission] = (),
        chunk_caps: Optional[Dict[int, int]] = None,
    ) -> Dict[int, List[int]]:
        """``chunk_caps``: optional per-admission prefill-token caps for
        THIS round, keyed by slot (the scheduler's per-round prefill
        budget — PR 17). A missing slot gets the full ``ragged_chunk``
        cap; a cap <= 0 skips the admission this round entirely (no row,
        no reservation — it retries next round). Chunked prefill is
        chunk-width-invariant, so any cap schedule yields byte-identical
        outputs; caps only shape WHEN prefill work lands."""
        if self.cfg.speculative is not None:
            return self._spec_ragged_round(admissions, chunk_caps)
        return self._plain_ragged_round(admissions, chunk_caps)

    def _ragged_admission_rows(
        self, admissions: Sequence[ChunkedAdmission], chunk_cap: int,
        chunk_caps: Optional[Dict[int, int]] = None,
    ) -> Tuple[List[Tuple[ChunkedAdmission, List[int], bool]], int]:
        """Slice each in-flight admission's next chunk row for a ragged
        round, pre-reserving the sampled first token's block for FINAL
        chunks (``submit_chunked_step``'s step-boundary rule); a
        pressured final chunk skips this round and retries. Shared by
        the plain and spec ragged rounds so the retry contract cannot
        drift. ``chunk_caps`` tightens (never widens) the per-admission
        slice — the scheduler's per-round prefill budget; a cap <= 0
        drops the admission from this round. Returns (ready rows, max
        chunk width)."""
        ready: List[Tuple[ChunkedAdmission, List[int], bool]] = []
        width = 1
        for adm in admissions:
            s = self.slots[adm.slot]
            assert s is not None
            cap = chunk_cap
            if chunk_caps is not None:
                cap = min(cap, int(chunk_caps.get(adm.slot, cap)))
                if cap <= 0:
                    continue
            piece = adm.fresh[:cap]
            is_last = len(adm.fresh) <= cap
            if is_last:
                try:
                    if self.manager.reserve_tokens(s.seq_id, 1):
                        self._block_tables[adm.slot] = \
                            self.manager.block_table_for(
                                s.seq_id, self.cfg.max_blocks_per_seq
                            )
                except OutOfBlocksError:
                    self.manager.trim_reserved(s.seq_id)
                    self._signal_pressure("admission", requests=1)
                    continue
            ready.append((adm, piece, is_last))
            width = max(width, len(piece))
        return ready, width

    def _fill_ragged_admission_rows(
        self, ready, toks_pos: np.ndarray, lens_after: np.ndarray,
        sample_flag: np.ndarray, row_mask: Optional[np.ndarray] = None,
    ) -> bool:
        """Write the admission chunk rows into a ragged round's host
        batch arrays; True when any admission samples non-greedily."""
        mixed = False
        for adm, piece, is_last in ready:
            sl, n = adm.slot, len(piece)
            toks_pos[0, sl, :n] = piece
            toks_pos[1, sl, :n] = np.arange(adm.off, adm.off + n)
            lens_after[sl] = adm.off + n
            sample_flag[sl] = 1 if is_last else 0
            if row_mask is not None:
                row_mask[sl] = True
            if adm.mode != "greedy":
                mixed = True
        return mixed

    def _commit_ragged_admissions(
        self, ready, toks: np.ndarray, out: Dict[int, List[int]],
    ) -> None:
        """Post-dispatch admission bookkeeping shared by the plain and
        spec ragged rounds: advance chunk offsets, account prefill
        tokens, and record each completed admission's in-graph-sampled
        first token (flipping ``adm.done``)."""
        for adm, piece, is_last in ready:
            s = self.slots[adm.slot]
            assert s is not None
            adm.fresh = adm.fresh[len(piece):]
            adm.off += len(piece)
            self.stats["prefill_tokens"] += len(piece)
            if is_last:
                s.prefilling = False
                tok = int(toks[adm.slot])
                out[adm.slot] = [tok]
                self._record_token(adm.slot, tok, device_synced=True)
                adm.done = True
            else:
                self._release_prefill_window(adm)

    def _plain_ragged_round(
        self, admissions: Sequence[ChunkedAdmission] = (),
        chunk_caps: Optional[Dict[int, int]] = None,
    ) -> Dict[int, List[int]]:
        """ONE device dispatch serving a ragged row batch: every active
        decode slot advances one token AND every in-flight admission
        advances one prefill chunk — the round-6 unification that replaced
        scheduling competing prefill/decode dispatches (subwave/interleave)
        with "append rows to the next round".

        Per-row semantics are exactly the split paths': decode rows feed
        their pending token at position ``_kv_lens`` (block pre-reserved,
        pressure freezes the row at the step boundary — ``decode_step``'s
        contract), admission rows run their next chunk with the final
        chunk sampling the first token in-graph (``submit_chunked_step``'s
        contract, including the pending-block pre-reservation; a pressured
        final chunk is NOT consumed and retries next round). Returns
        {slot: [token]} for every row that sampled. Admissions are mutated
        in place; ``adm.done`` flips when the first token lands."""
        admissions = [a for a in admissions if not a.done]
        for adm in admissions:
            s = self.slots[adm.slot]
            if s is None or s.seq_id != adm.seq_id:
                raise RuntimeError("ragged admission slot was freed")
        b = len(self.slots)
        max_bucket = self.cfg.prefill_buckets[-1]
        chunk_cap = min(max(int(self.cfg.ragged_chunk), 1), max_bucket)

        # --- decode rows: pre-reserve each pending token's block exactly
        # as decode_step does; exhaustion freezes the row (nothing decoded,
        # pending still pending) and signals step-boundary pressure
        kept: List[int] = []
        pressured: List[int] = []
        for i, s in enumerate(self.slots):
            if s is None or s.finish_reason is not None or s.prefilling:
                continue
            if len(self.manager.seq_tokens[s.seq_id]) >= self.cfg.max_seq_len:
                kept.append(i)      # length-finish triggers in _record_token
                continue
            try:
                added = self.manager.reserve_tokens(s.seq_id, 1)
            except OutOfBlocksError:
                self.manager.trim_reserved(s.seq_id)
                self._block_tables[i] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
                pressured.append(i)
                continue
            if added:
                self._block_tables[i] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
            kept.append(i)
        if pressured:
            self._signal_pressure("decode", slots=pressured)

        # --- admission chunk rows: shared slicing + final-chunk
        # pending-block pre-reservation (``_ragged_admission_rows``)
        ready, width = self._ragged_admission_rows(admissions, chunk_cap,
                                                   chunk_caps)
        if not kept and not ready:
            return {}

        self._apply_pending()
        s_w = self._bucket_len(width)
        toks_pos = np.zeros((2, b, s_w), np.int32)
        toks_pos[1] = -1
        lens_after = np.zeros((b,), np.int32)
        row_mask = np.zeros((b,), dtype=bool)
        sample_flag = np.zeros((b,), np.int32)
        mode = "greedy"
        for i in kept:
            toks_pos[0, i, 0] = self._last_tokens[i]
            toks_pos[1, i, 0] = self._kv_lens[i]
            lens_after[i] = self._kv_lens[i] + 1
            row_mask[i] = True
            sample_flag[i] = 1
            if self._temps[i] > 0:
                mode = "mixed"
        if self._fill_ragged_admission_rows(ready, toks_pos, lens_after,
                                            sample_flag, row_mask):
            mode = "mixed"
        core = self._sync_core()
        tables, _act, flag_d = self._sched_arrays(row_mask, sample_flag)
        try:
            self.kv, self._dev_core, toks = self._ragged_round_fn(
                self.params, self.kv, toks_pos, tables,
                jnp.asarray(lens_after), core, flag_d, mode,
            )
        except Exception:
            self._invalidate_device_state()
            raise
        toks = np.asarray(toks)
        self.stats["ragged_rounds"] += 1
        if kept:
            self.stats["decode_calls"] += 1
        if ready:
            # ONE device dispatch served every admission row — the counter
            # means device calls everywhere else (wave admission asserts
            # one per bucket), so it must not scale with the row count
            self.stats["prefill_calls"] += 1
        out: Dict[int, List[int]] = {}
        for i in kept:
            self._kv_lens[i] += 1   # the fed token's KV is now committed
            tok = int(toks[i])
            out[i] = [tok]
            self._record_token(i, tok, device_synced=True)
        self._commit_ragged_admissions(ready, toks, out)
        return out

    def _spec_ragged_round(
        self, admissions: Sequence[ChunkedAdmission] = (),
        chunk_caps: Optional[Dict[int, int]] = None,
    ) -> Dict[int, List[int]]:
        """Spec-integrated ragged round: ONE dispatch serving VERIFY rows
        (per active decode slot: the draft chain + pending token,
        q_len = 2..K+1) alongside admission prefill-chunk rows — the
        round-8 unification that gives a speculating engine PR 6's
        one-dispatch prefill+decode path. Per-row contracts match the
        split paths exactly: verify rows pre-reserve their worst-case
        window and commit 1..K+1 accepted tokens with precise
        ``trim_reserved`` rollback at this same step boundary
        (``_spec_decode_rounds``'s per-round contract — greedy outputs
        stay byte-identical spec on/off and ragged on/off); admission
        rows run their next chunk with the final chunk sampling in-graph
        (``submit_chunked_step``'s contract, pending-block pre-reservation
        included; a pressured final chunk retries next round). Returns
        {slot: [tokens]}; admissions mutate in place."""
        spec = self.cfg.speculative
        assert spec is not None and self._spec_ragged_round_fn is not None
        k = spec.num_draft_tokens
        admissions = [a for a in admissions if not a.done]
        for adm in admissions:
            s = self.slots[adm.slot]
            if s is None or s.seq_id != adm.seq_id:
                raise RuntimeError("ragged admission slot was freed")
        b = len(self.slots)
        max_bucket = self.cfg.prefill_buckets[-1]
        chunk_cap = min(max(int(self.cfg.ragged_chunk), 1), max_bucket)

        # --- verify rows: per-slot depth selection + worst-case
        # reservation (one round: up to K+1 fed tokens plus the
        # post-round pending token), exactly _spec_decode_rounds at
        # rounds=1; exhaustion freezes the row at the step boundary
        budgets = np.zeros((b,), np.int32)
        cand: List[int] = []
        for i, s in enumerate(self.slots):
            if s is None or s.finish_reason is not None or s.prefilling:
                continue
            rem = s.request.sampling.max_new_tokens - len(s.generated)
            if rem <= 0:
                continue
            budgets[i] = rem
            cand.append(i)
        ks_sel = self._select_spec_ks(cand)
        caps = np.zeros((b,), np.int32)
        spec_rows = np.zeros((b,), bool)
        pressured: List[int] = []
        for i in cand:
            s = self.slots[i]
            assert s is not None
            cur = len(self.manager.seq_tokens[s.seq_id])
            ki = int(ks_sel[i])
            want = min(ki + 1, int(budgets[i])) + ki + 1
            n_res = max(min(want, self.cfg.max_seq_len - cur), 0)
            try:
                if n_res > 0 and self.manager.reserve_tokens(s.seq_id,
                                                             n_res):
                    self._block_tables[i] = self.manager.block_table_for(
                        s.seq_id, self.cfg.max_blocks_per_seq
                    )
            except OutOfBlocksError:
                self.manager.trim_reserved(s.seq_id)
                self._block_tables[i] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
                pressured.append(i)
                continue
            spec_rows[i] = True
            caps[i] = cur + n_res
        if pressured:
            self._signal_pressure("decode", slots=pressured)
        if not spec_rows.any():
            # no verify row this round — admission-only (cold-start
            # ramp-up) or every candidate pressured out of its verify
            # window. The PLAIN ragged graph serves chunk rows with
            # byte-identical arithmetic and skips the draft chain + the
            # [B, K+1, V] head projections entirely; pressured slots it
            # can re-admit advance one VANILLA token (a 1-token
            # reservation can fit where K+2 did not — graceful
            # degradation, still target-greedy so outputs are unchanged;
            # only the stale draft hidden costs next-round acceptance).
            return self._plain_ragged_round(admissions, chunk_caps)

        # --- admission chunk rows: identical contract to the plain path
        # (shared helper — the retry/reservation rules cannot drift)
        ready, width = self._ragged_admission_rows(admissions, chunk_cap,
                                                   chunk_caps)

        self._apply_pending()
        # row width: a dedicated K+1 shape serves pure-verify rounds (the
        # steady state) without padding up to the smallest prefill
        # bucket; wider chunk rows bucket as usual — the compiled width
        # set stays {K+1} ∪ buckets
        s_w = k + 1 if width <= k + 1 else self._bucket_len(width)
        toks_pos = np.zeros((2, b, s_w), np.int32)
        toks_pos[1] = -1
        lens_after = np.zeros((b,), np.int32)
        sample_flag = np.zeros((b,), np.int32)
        mode = "greedy"
        for i in np.nonzero(spec_rows)[0]:
            if self._temps[i] > 0:
                mode = "mixed"
        if self._fill_ragged_admission_rows(ready, toks_pos, lens_after,
                                            sample_flag):
            mode = "mixed"
        forced = self._spec_forced(
            [int(i) for i in np.nonzero(spec_rows)[0]], 1, ks_sel
        )[0]
        core = self._sync_core()
        h_last = self._spec_h_device()
        mm = self.cfg.max_blocks_per_seq
        si = np.zeros((b, mm + 5), np.int32)
        si[:, :mm] = self._block_tables
        si[:, mm] = spec_rows
        si[:, mm + 1] = sample_flag
        si[:, mm + 2] = ks_sel
        si[:, mm + 3] = caps
        si[:, mm + 4] = forced
        (tables, spec_d, flag_d, ks_d, caps_d,
         forced_d) = self._unpack_spec_sched_fn(si)
        try:
            (self.kv, self._dev_core, self._dev_spec_h, tok0, emitted,
             n_acc) = self._spec_ragged_round_fn(
                self.params, self._draft_params, self.kv, toks_pos,
                tables, jnp.asarray(lens_after), core, h_last, spec_d,
                flag_d, ks_d, caps_d, forced_d, mode,
            )
        except Exception:
            self._invalidate_device_state()
            raise
        tok0 = np.asarray(tok0)
        emitted = np.asarray(emitted)
        n_acc = np.asarray(n_acc)
        self.stats["ragged_rounds"] += 1
        if spec_rows.any():
            self.stats["decode_calls"] += 1
            self.stats["spec_steps"] += 1
        if ready:
            self.stats["prefill_calls"] += 1
        out: Dict[int, List[int]] = {}
        for i in np.nonzero(spec_rows)[0]:
            i = int(i)
            if spec.adaptive:
                self._spec_ema_update(i, int(n_acc[i]))
            s = self.slots[i]
            assert s is not None
            a = int(n_acc[i])
            # the device committed t0..t_a (fed in the verify pass)
            self._kv_lens[i] += a + 1
            if self._temps[i] <= 0.0:
                self.stats["spec_slot_steps"] += 1
                self.stats["spec_drafted"] += int(ks_sel[i])
                self.stats["spec_accepted"] += a
                self.stats["spec_emitted"] += a + 1
            commit: List[int] = []
            for t in emitted[i]:
                if t < 0 or s.finish_reason is not None:
                    break
                out.setdefault(i, []).append(int(t))
                self._record_token(i, int(t), already_committed=True,
                                   device_synced=True)
                if s.finish_reason is None:
                    commit.append(int(t))
            self.manager.commit_tokens(s.seq_id, commit)
            # precise rollback of the rejected window at the same step
            # boundary (footprint matches a never-speculated engine)
            if self.manager.trim_reserved(s.seq_id):
                self._block_tables[i] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
            self._maybe_release_window(i)
        self._commit_ragged_admissions(ready, tok0, out)
        return out

    def _record_token(self, slot: int, tok: int, already_committed: bool = False,
                      device_synced: bool = False) -> None:
        """Account a freshly *sampled* token.

        ``self._kv_lens[slot]`` is the **committed** context length — tokens
        whose KV has been written on device. A sampled token is *pending*: its
        KV is written only when it is fed in the next decode step, at position
        ``_kv_lens``. This method records the sample, checks stop/length, and
        (unless ``already_committed`` — the multi-step scan pre-reserves)
        allocates the block its KV will land in.

        ``device_synced``: the token came from a graph that already advanced
        the device core state identically (decode rounds, batched prefill) —
        the host-mirror update below then does NOT dirty the device copy.
        """
        s = self.slots[slot]
        assert s is not None
        now = time.time()
        if s.first_token_time is None:
            s.first_token_time = now
        if tok in self._stop_ids[slot]:
            s.finish_reason = "stop"
            return
        s.generated.append(tok)
        self.stats["generated_tokens"] += 1
        self._last_tokens[slot] = tok
        if not device_synced:
            self._core_dirty = True
        if len(s.generated) >= s.request.sampling.max_new_tokens:
            s.finish_reason = s.finish_reason or "length"
            return
        if int(self._kv_lens[slot]) >= self.cfg.max_seq_len:
            s.finish_reason = "length"
            return
        if not already_committed:
            new_block = self.manager.append_token(s.seq_id, tok)
            if new_block is not None:
                self._block_tables[slot] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
            self._apply_pending()
            self._maybe_release_window(slot)

    def _release_prefill_window(self, adm: ChunkedAdmission) -> None:
        """Sliding-window models, MID-prefill: hand back blocks that every
        REMAINING chunk query is already past, between chunks. Without
        this a 32k prompt on a windowed model holds its entire prompt KV
        until the first decode step (``_maybe_release_window`` only runs
        on token commits) — worst-case pool pressure exactly when a long
        admission is streaming in. The earliest remaining query sits at
        position ``adm.off``, not ``cur - 1`` (``seq_tokens`` already
        holds the WHOLE prompt during prefill), so the window passed to
        the manager widens by the not-yet-queried tail: only keys
        <= adm.off - window release. The attention window mask already
        excludes those positions for every remaining chunk row, so
        pad-block reads are never visible — byte-identical outputs."""
        w = self.model_cfg.sliding_window
        if w is None:
            return
        s = self.slots[adm.slot]
        if s is None:
            return
        cur = len(self.manager.seq_tokens[s.seq_id])
        released = self.manager.release_out_of_window(
            s.seq_id, w + max(cur - adm.off, 0)
        )
        for lb in released:
            self._block_tables[adm.slot, lb] = 0

    def _maybe_release_window(self, slot: int) -> None:
        """Sliding-window models: hand blocks every future query is past back
        to the pool (window-bounded KV memory — SWA's serving payoff). The
        released logical slots point at pad block 0; the attention window
        mask already excludes those positions, so reads stay correct."""
        w = self.model_cfg.sliding_window
        if w is None:
            return
        s = self.slots[slot]
        assert s is not None
        released = self.manager.release_out_of_window(s.seq_id, w)
        for lb in released:
            self._block_tables[slot, lb] = 0

    def decode_step(self) -> Dict[int, int]:
        """One decode step for all active unfinished slots: feeds each slot's
        pending token (writing its KV at position ``_kv_lens``), samples the
        next. Returns {slot: sampled_token} (stop tokens included, then the
        slot finishes)."""
        active = [
            i for i, s in enumerate(self.slots)
            if s is not None and s.finish_reason is None and not s.prefilling
        ]
        if not active:
            return {}
        # pre-reserve the block this step's SAMPLED token will occupy (and
        # CoW a shared tail) BEFORE the device call: exhaustion then freezes
        # the slot at the step boundary — nothing decoded, pending token
        # still pending, host/device state untouched — and signals the
        # scheduler, instead of OutOfBlocksError unwinding mid-record with a
        # sampled-but-unplaced token
        kept: List[int] = []
        pressured: List[int] = []
        for i in active:
            s = self.slots[i]
            assert s is not None
            if len(self.manager.seq_tokens[s.seq_id]) >= self.cfg.max_seq_len:
                # context full: this step's sample triggers the length
                # finish and is never appended — reserving past the table
                # width would overflow it
                kept.append(i)
                continue
            try:
                added = self.manager.reserve_tokens(s.seq_id, 1)
            except OutOfBlocksError:
                self.manager.trim_reserved(s.seq_id)
                self._block_tables[i] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
                pressured.append(i)
                continue
            if added:
                self._block_tables[i] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
            kept.append(i)
        if pressured:
            self._signal_pressure("decode", slots=pressured)
        if not kept:
            return {}
        active = kept
        self._apply_pending()
        active_mask = np.zeros(len(self.slots), dtype=bool)
        active_mask[active] = True
        # budgets stay out of the per-step graph's way: stop/length decisions
        # are host-side in _record_token, exactly as before
        budgets = np.where(active_mask, _BIG_BUDGET, 0).astype(np.int32)
        core = self._sync_core()
        tables, act_d, bud_d = self._sched_arrays(active_mask, budgets)
        mode = self._decode_mode()
        try:
            self.kv, self._dev_core, emitted = self._decode_multi_fn(
                self.params, self.kv, core, tables, act_d, bud_d, 1, mode,
            )
        except Exception:
            self._invalidate_device_state()
            raise
        self.stats["decode_calls"] += 1
        toks = np.asarray(emitted)[:, 0]
        out: Dict[int, int] = {}
        for i in active:
            self._kv_lens[i] += 1  # the fed token's KV is now committed
            tok = int(toks[i])
            out[i] = tok
            self._record_token(i, tok, device_synced=True)
        return out

    # ------------------------------------------- spec depth / oracle helpers

    def _select_spec_ks(self, active: Sequence[int]) -> np.ndarray:
        """Per-slot draft depth for the next dispatch. Non-adaptive: the
        configured K everywhere. Adaptive: the smallest choice from the
        static ``k_choices`` set strictly above the slot's accepted-length
        EMA (always draft a little deeper than the recent accept), capped
        at the largest choice. Depths select masks inside ONE compiled
        graph — never a new trace."""
        sp = self.cfg.speculative
        assert sp is not None
        ks = np.full((len(self.slots),), sp.num_draft_tokens, np.int32)
        if sp.adaptive:
            choices = sp.k_choices()
            for i in active:
                ema = float(self._spec_k_ema[i])
                sel = choices[-1]
                for c in choices:
                    if ema < c:
                        sel = c
                        break
                ks[i] = sel
        if self.spec_k_trace is not None:
            self.spec_k_trace.append([(int(i), int(ks[i])) for i in active])
        return ks

    def _spec_ema_update(self, slot: int, accepted: int) -> None:
        sp = self.cfg.speculative
        assert sp is not None
        a = float(sp.adaptive_ema)
        self._spec_k_ema[slot] = (
            a * float(self._spec_k_ema[slot]) + (1.0 - a) * float(accepted)
        )

    def _spec_forced(self, active: Sequence[int], rounds: int,
                     ks: np.ndarray) -> np.ndarray:
        """Oracle-draft forced accepted lengths, [rounds, B] int32; -1 =
        real acceptance (the production value — also every inactive row).
        Fractional per-round targets (rate × K) dither through a per-slot
        accumulator, so the mean over rounds hits the rate exactly and the
        schedule is deterministic."""
        sp = self.cfg.speculative
        assert sp is not None
        out = np.full((rounds, len(self.slots)), -1, np.int32)
        rate = sp.oracle_accept_rate
        if rate is None:
            return out
        for i in active:
            target = float(rate) * float(ks[i])
            for r in range(rounds):
                self._spec_oracle_acc[i] += target
                f = int(np.floor(self._spec_oracle_acc[i] + 1e-9))
                f = max(0, min(f, int(ks[i])))
                self._spec_oracle_acc[i] -= f
                out[r, i] = f
        return out

    def set_spec_oracle(self, rate: Optional[float]) -> None:
        """Flip the oracle draft's forced acceptance rate on a LIVE engine
        (the bench A/B lever — the oracle is a traced input, so no
        recompile). ``None`` restores real acceptance."""
        sp = self.cfg.speculative
        if sp is None:
            raise ValueError("engine has no speculative config")
        if rate is not None and not (0.0 <= float(rate) <= 1.0):
            raise ValueError(f"oracle rate {rate} must be in [0, 1]")
        sp.oracle_accept_rate = None if rate is None else float(rate)
        self._spec_oracle_acc[:] = 0.0

    def spec_decode_step(self) -> Dict[int, List[int]]:
        """One speculative round for all active slots: draft K tokens per
        slot, verify the chain in one multi-query target pass, commit each
        slot's accepted prefix + bonus (1..K+1 tokens). Returns
        {slot: emitted_tokens} with the same contract as ``decode_multi``
        (a stop token appears in the list, then the slot finishes)."""
        return self._spec_decode_rounds(1)

    def _spec_decode_rounds(self, num_steps: int) -> Dict[int, List[int]]:
        """ONE fused dispatch of up to ``num_steps`` draft→verify→accept
        rounds (a lax.scan with device-resident done/budget/stop state —
        the same per-dispatch amortization decode_multi's scan buys vanilla
        decode). Rounds bucket to powers of two so at most log2 variants
        compile; per-round records replay on the host so cache-manager
        commits and emission bookkeeping exactly match the per-step path."""
        spec = self.cfg.speculative
        assert spec is not None and self._spec_rounds_fn is not None
        active = [
            i for i, s in enumerate(self.slots)
            if s is not None and s.finish_reason is None and not s.prefilling
        ]
        if not active:
            return {}
        b = len(self.slots)
        active_mask = np.zeros(b, dtype=bool)
        caps = np.zeros(b, dtype=np.int32)
        budgets = np.zeros(b, dtype=np.int32)
        for i in active:
            s = self.slots[i]
            budgets[i] = max(
                s.request.sampling.max_new_tokens - len(s.generated), 0
            )
        active = [i for i in active if budgets[i] > 0]
        if not active:
            return {}
        # every active round commits >= 1 token per slot, so rounds beyond
        # the largest remaining budget are dead weight; bucket to a power
        # of two so the compiled scan-length set stays logarithmic
        rounds = max(1, min(int(num_steps),
                            int(max(budgets[i] for i in active))))
        rounds = 1 << (rounds.bit_length() - 1)
        ks_sel = self._select_spec_ks(active)
        pressured: List[int] = []
        for i in active:
            s = self.slots[i]
            # reserve the dispatch's worst case up front — the device
            # cannot allocate mid-scan: commits are bounded by
            # min(rounds*(K+1), budget), plus K+1 so the final round's full
            # window and the post-dispatch pending token stay covered
            # (K = the slot's SELECTED depth — adaptive shallow slots
            # pre-book proportionally less). Near max_seq_len the window
            # shrinks and the in-graph clamp + freeze honor the smaller
            # cap.
            cur = len(self.manager.seq_tokens[s.seq_id])
            ki = int(ks_sel[i])
            want = min(rounds * (ki + 1), int(budgets[i])) + ki + 1
            n_res = max(min(want, self.cfg.max_seq_len - cur), 0)
            try:
                if n_res > 0 and self.manager.reserve_tokens(s.seq_id, n_res):
                    # table rebuild only when the reservation actually added
                    # blocks (or CoW'd a shared tail)
                    self._block_tables[i] = self.manager.block_table_for(
                        s.seq_id, self.cfg.max_blocks_per_seq
                    )
            except OutOfBlocksError:
                # pool can't hold this slot's verify window: freeze it for
                # this dispatch (step-boundary pressure, scheduler decides
                # who yields) rather than unwind half-reserved
                self.manager.trim_reserved(s.seq_id)
                self._block_tables[i] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
                pressured.append(i)
                continue
            active_mask[i] = True
            caps[i] = cur + n_res
        if pressured:
            self._signal_pressure("decode", slots=pressured)
        if not active_mask.any():
            return {}
        self._apply_pending()
        forced = self._spec_forced(
            [i for i in active if active_mask[i]], rounds, ks_sel
        )
        core = self._sync_core()
        h_last = self._spec_h_device()
        tables, act_d, caps_d = self._sched_arrays(active_mask, caps)
        mode = self._decode_mode()
        try:
            (self.kv, self._dev_core, self._dev_spec_h,
             recs) = self._spec_rounds_fn(
                self.params, self._draft_params, self.kv, core, h_last,
                tables, act_d, caps_d, jnp.asarray(budgets),
                jnp.asarray(ks_sel), jnp.asarray(forced), rounds, mode,
            )
        except Exception:
            self._invalidate_device_state()
            raise
        rec_emit, rec_nacc, rec_act = (np.asarray(r) for r in recs)
        self.stats["decode_calls"] += rounds
        adaptive = spec.adaptive
        out: Dict[int, List[int]] = {}
        for r in range(rounds):
            act = rec_act[r]
            if not act.any():
                break
            self.stats["spec_steps"] += 1
            for i in active:
                if not act[i]:
                    continue
                if adaptive:
                    # EMA sees every round the row was live (sampled rows
                    # contribute their structural zeros and converge to
                    # the shallowest depth — less dead verify weight)
                    self._spec_ema_update(i, int(rec_nacc[r, i]))
                s = self.slots[i]
                if s is None or s.finish_reason is not None:
                    continue
                a = int(rec_nacc[r, i])
                # the device committed t0..t_a (fed in the verify pass)
                self._kv_lens[i] += a + 1
                if self._temps[i] <= 0.0:
                    # efficiency counters describe SPECULATING slots only:
                    # sampled slots never accept drafts by design, and
                    # counting their forced zeros would dilute the exported
                    # accept-rate/tokens-per-step gauges under mixed traffic
                    self.stats["spec_slot_steps"] += 1
                    self.stats["spec_drafted"] += int(ks_sel[i])
                    self.stats["spec_accepted"] += a
                    self.stats["spec_emitted"] += a + 1
                commit: List[int] = []
                for t in rec_emit[r, i]:
                    if t < 0 or s.finish_reason is not None:
                        break
                    out.setdefault(i, []).append(int(t))
                    self._record_token(i, int(t), already_committed=True,
                                       device_synced=True)
                    if s.finish_reason is None:
                        # committed-or-pending-with-reserved-block, exactly
                        # as decode_multi's bookkeeping (stop/length
                        # trigger excluded)
                        commit.append(int(t))
                self.manager.commit_tokens(s.seq_id, commit)
        for i in active:
            s = self.slots[i]
            if s is None:
                continue
            # precise rollback of the rejected windows: drop reserved
            # blocks acceptance never reached, so the footprint matches a
            # never-speculated per-step engine
            if self.manager.trim_reserved(s.seq_id):
                self._block_tables[i] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
            self._maybe_release_window(i)
        return out

    def distill_draft(self, steps: int = 400, **kw: Any) -> None:
        """Distill the integrated draft head against this engine's own
        target weights (runtime.speculative.distill_draft_params) —
        acceptance goes from ~0 (random head) to task-dependent useful."""
        if self.cfg.speculative is None:
            raise ValueError("engine has no speculative config to distill")
        from distributed_gpu_inference_tpu.runtime.speculative import (
            distill_draft_params,
        )

        self._draft_params = distill_draft_params(
            self.model_cfg, self.params,
            jax.random.PRNGKey(self.cfg.speculative.draft_seed),
            steps=steps, **kw,
        )

    def decode_multi(self, num_steps: Optional[int] = None) -> Dict[int, List[int]]:
        """Run T decode steps in one device call (lax.scan) with on-device
        stop masking; host sees tokens only at the end. TPU-first throughput
        path — amortizes per-token host round-trips.

        With ``EngineConfig.speculative`` set, the T steps are fused
        draft→verify→accept rounds instead — each commits 1..K+1 tokens per
        slot, amortizing the weight stream over the accepted tokens."""
        num_steps = num_steps or self.cfg.multi_step
        if self.cfg.speculative is not None:
            return self._spec_decode_rounds(int(num_steps))
        active_mask = np.array(
            [s is not None and s.finish_reason is None and not s.prefilling
             for s in self.slots]
        )
        if not active_mask.any():
            return {}
        # per-slot token budgets enforced ON DEVICE (scan masks a slot once
        # it emits its allowance) — num_steps stays the compiled constant
        # instead of shrinking to the shortest slot and recompiling per
        # distinct tail length
        budgets = np.array(
            [
                min(
                    s.request.sampling.max_new_tokens - len(s.generated),
                    self.cfg.max_seq_len - int(self._kv_lens[i]),
                ) if active_mask[i] and s is not None else 0
                for i, s in enumerate(self.slots)
            ],
            dtype=np.int32,
        )
        budgets = np.maximum(budgets, 0)
        active_mask &= budgets > 0
        if not active_mask.any():
            return {}
        # pre-reserve KV blocks for each slot's actual horizon (no host
        # alloc mid-scan). A slot whose reservation exhausts the pool is
        # FROZEN for this round (masked out, partial reservation trimmed
        # back, pending token still pending) and reported as a pressure
        # signal — the step boundary stays consistent instead of the round
        # unwinding with half the batch reserved.
        pressured: List[int] = []
        for i, s in enumerate(self.slots):
            if active_mask[i] and s is not None:
                # clamp the horizon to the context limit: the length-finish
                # trigger token is never appended, so reserving past
                # max_seq_len would only overflow the block-table width
                cur = len(self.manager.seq_tokens[s.seq_id])
                n_res = min(int(min(num_steps, budgets[i])),
                            self.cfg.max_seq_len - cur)
                if n_res <= 0:
                    continue
                try:
                    self.manager.reserve_tokens(s.seq_id, n_res)
                except OutOfBlocksError:
                    self.manager.trim_reserved(s.seq_id)
                    active_mask[i] = False
                    pressured.append(i)
                self._block_tables[i] = self.manager.block_table_for(
                    s.seq_id, self.cfg.max_blocks_per_seq
                )
        if pressured:
            self._signal_pressure("decode", slots=pressured)
        if not active_mask.any():
            return {}
        self._apply_pending()
        core = self._sync_core()
        tables, act_d, bud_d = self._sched_arrays(
            active_mask, budgets.astype(np.int32)
        )
        mode = self._decode_mode()
        try:
            self.kv, self._dev_core, emitted = self._decode_multi_fn(
                self.params, self.kv, core, tables, act_d, bud_d,
                int(num_steps), mode,
            )
        except Exception:
            self._invalidate_device_state()
            raise
        self.stats["decode_calls"] += num_steps
        emitted = np.asarray(emitted)  # [B, T], -1 = masked-out step
        out: Dict[int, List[int]] = {}
        for i, s in enumerate(self.slots):
            if not active_mask[i] or s is None:
                continue
            toks = [int(t) for t in emitted[i] if t >= 0]
            out[i] = toks
            # each emitted token corresponds to one scan step that fed (and
            # thus committed) the previous pending token
            self._kv_lens[i] += len(toks)
            for t in toks:
                if s.finish_reason is not None:
                    break
                self._record_token(i, t, already_committed=True,
                                   device_synced=True)
            # manager bookkeeping: seq_tokens ← tokens that are committed or
            # pending-with-reserved-block (stop/length-trigger excluded, as in
            # the per-step path)
            commit = toks if s.finish_reason is None else toks[:-1]
            self.manager.commit_tokens(s.seq_id, commit)
            self._maybe_release_window(i)
        return out

    def finish_slot(self, slot: int, cache: bool = True) -> InferenceResponse:
        s = self.slots[slot]
        if s is None:
            raise ValueError(f"slot {slot} empty")
        self.manager.free_sequence(s.seq_id, cache=cache)
        self.slots[slot] = None
        self._kv_lens[slot] = 0
        self.stats["completed"] += 1
        now = time.time()
        resp = InferenceResponse(
            request_id=s.request.request_id,
            token_ids=list(s.generated),
            finish_reason=s.finish_reason or "abort",
            prompt_tokens=s.prompt_len,
            completion_tokens=len(s.generated),
            cached_tokens=s.cached_tokens,
            ttft_ms=(s.first_token_time - s.start_time) * 1000.0
            if s.first_token_time
            else None,
            e2e_ms=(now - s.start_time) * 1000.0,
        )
        # flight recorder: the engine's own wall-clock boundaries ride the
        # response so timeline events can be anchored at the instant the
        # engine observed them (first token sampled, sequence admitted)
        # rather than when a driver loop got around to noticing
        if s.start_time is not None:
            resp.extra["t_start"] = s.start_time
        if s.first_token_time is not None:
            resp.extra["t_first_token"] = s.first_token_time
        return resp

    # ---------------------------------------------------------- generate

    def generate(
        self,
        requests: Sequence[InferenceRequest],
        use_multi_step: bool = False,
        max_preemptions: int = 8,
    ) -> List[InferenceResponse]:
        """Batch-generate to completion (waves of ≤ max_batch_size).

        KV-pressure safe: admissions the pool cannot hold simply wait,
        decode pressure preempts the most-recently-admitted sequence
        (spill → resume, byte-identical continuation), and a request
        preempted more than ``max_preemptions`` times finishes with a
        ``preempted_too_often`` error instead of livelocking the wave.
        Clients never see an OutOfBlocksError."""
        pending = []
        responses: Dict[str, InferenceResponse] = {}
        for r in requests:
            if self.request_fits_pool(r):
                pending.append(r)
            else:
                # a prompt that cannot fit an idle pool would head-of-line
                # block the whole wave forever — reject it immediately and
                # keep serving the rest
                responses[r.request_id] = InferenceResponse(
                    request_id=r.request_id,
                    error="request exceeds KV pool capacity (prompt cannot "
                          "fit an idle pool)",
                )
        preempted: List[PreemptedSequence] = []
        stamp = itertools.count()
        admitted_at: Dict[int, int] = {}        # slot → admission stamp
        preempt_counts: Dict[str, int] = {}     # request_id → preemptions
        stalled = 0
        # after a preemption, resumes pause for one unpressured round so
        # the FROZEN slots reserve first — an immediate resume would take
        # back exactly the blocks the preemption freed and the pressure
        # would recur every round until the victim dies preempted_too_often
        hold_resume = False
        while pending or preempted or self.num_active:
            progressed = False
            n_free = len(self.free_slots())
            # resumes outrank fresh admissions: preempted work re-enters
            # at the head of the line
            while preempted and n_free > 0 and not hold_resume:
                try:
                    slot = self.resume(preempted[0])
                except OutOfBlocksError:
                    break               # still pressured; decode frees blocks
                preempted.pop(0)
                admitted_at[slot] = next(stamp)
                n_free -= 1
                progressed = True
            if pending and n_free > 0:
                wave, pending = pending[:n_free], pending[n_free:]
                try:
                    slots = self.submit_batch(wave, partial=True)
                except OutOfBlocksError:
                    # exhaustion in the PREFILL phase (first sampled token's
                    # block): the wave rolled back cleanly — defer it all
                    slots = []
                pending = wave[len(slots):] + pending   # deferred tail waits
                for sl in slots:
                    admitted_at[sl] = next(stamp)
                progressed = progressed or bool(slots)
            if self.num_active:
                out = (
                    self.decode_multi() if use_multi_step
                    else self.decode_step()
                )
                progressed = progressed or bool(out)
            pressure = self.take_pressure()
            if pressure is None:
                hold_resume = False     # unpressured round: resumes may flow
            elif pressure.source == "decode":
                victims = [
                    i for i, s in enumerate(self.slots)
                    if s is not None and s.finish_reason is None
                    and not s.prefilling
                ]
                if victims:
                    victim = max(
                        victims, key=lambda sl: admitted_at.get(sl, -1)
                    )
                    pre = self.preempt_slot(victim)
                    rid = pre.request.request_id
                    count = preempt_counts.get(rid, 0) + 1
                    preempt_counts[rid] = count
                    pre.preempt_count = count
                    if count > max_preemptions:
                        responses[rid] = InferenceResponse(
                            request_id=rid,
                            token_ids=list(pre.generated),
                            finish_reason="abort",
                            prompt_tokens=pre.prompt_len,
                            completion_tokens=len(pre.generated),
                            error="preempted_too_often: KV pool cannot "
                                  f"sustain this sequence ({count} "
                                  "preemptions)",
                        )
                    else:
                        preempted.append(pre)
                        hold_resume = True
                    progressed = True
            if not progressed:
                stalled += 1
                if stalled > 8 and preempted and self.num_active == 0 \
                        and not pending:
                    # an IDLE engine repeatedly failing a resume means the
                    # sequence's generated context alone no longer fits the
                    # pool — nothing will ever free more blocks. Deliver
                    # what it produced instead of wedging forever.
                    pre = preempted.pop(0)
                    rid = pre.request.request_id
                    responses[rid] = InferenceResponse(
                        request_id=rid,
                        token_ids=list(pre.generated),
                        finish_reason="abort",
                        prompt_tokens=pre.prompt_len,
                        completion_tokens=len(pre.generated),
                        error="request exceeds KV pool capacity: generated "
                              f"context ({len(pre.generated)} tokens) can "
                              "no longer be resumed",
                    )
                    stalled = 0
                elif stalled > 32:
                    raise OutOfBlocksError(
                        "generate wedged under KV pressure: "
                        f"{len(pending)} pending, {len(preempted)} "
                        f"preempted, {self.num_active} active — the pool "
                        "cannot hold even one waiting sequence"
                    )
            else:
                stalled = 0
            for i, s in enumerate(list(self.slots)):
                if s is not None and s.finish_reason is not None:
                    resp = self.finish_slot(i)
                    responses[resp.request_id] = resp
        return [responses[r.request_id] for r in requests]

    def get_stats(self) -> Dict[str, Any]:
        out = dict(self.stats)
        out["kv_cache"] = self.manager.get_stats()
        out["active_slots"] = self.num_active
        if self.cfg.speculative is not None:
            drafted = out.get("spec_drafted", 0)
            slot_steps = out.get("spec_slot_steps", 0)
            out["spec_accept_rate"] = (
                out.get("spec_accepted", 0) / drafted if drafted else 0.0
            )
            # tokens emitted per slot per verify step (1..K+1): the weight-
            # stream amortization factor the mode exists for
            out["spec_tokens_per_step"] = (
                out.get("spec_emitted", 0) / slot_steps if slot_steps
                else 0.0
            )
        return out
