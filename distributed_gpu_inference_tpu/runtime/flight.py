"""Request flight recorder — per-request phase timelines, dependency-free.

Every job/stream that carries a ``trace_id`` accumulates monotonic phase
events across its whole path: server admission/route/claim, worker poll
pickup, batcher queue wait and admission-chunk rounds, first token,
preempt/resume, PD prefill → handoff begin/commit → decode adopt, and
completion. The recorder is ADVISORY end to end:

- the hot path is one ``time.monotonic()`` read + one list append
  (:class:`Timeline.note`); serialization happens only at result/heartbeat
  boundaries (:meth:`Timeline.wire`);
- a request without a trace id (or with ``DGI_FLIGHT=0``) gets the
  shared :data:`NULL_TIMELINE`, whose ``note`` is a no-op ``pass`` — the
  recorder-off path allocates nothing per request;
- the recorder can NEVER fail or reorder a request: events are bounded by
  :data:`FLIGHT_EVENT_CAP` (excess is counted, not raised), attrs are
  sanitized at wire time, and every consumer treats a malformed payload as
  a skipped sample.

Worker-side events ship to the control plane through the existing result
payload (``result["timeline"]``) and heartbeat (``engine_stats["flight"]``)
channels; ``server/flight_recorder.py`` merges the per-source lists into
one causally-ordered timeline per trace.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

# per-request event cap: a runaway event source (e.g. one chunk-round event
# per ragged round on a 100k-token prompt) saturates at the cap and counts
# the overflow instead of growing without bound
FLIGHT_EVENT_CAP = 256

# the last slice of the cap is reserved for phase-boundary events: a
# saturating repeater (chunk rounds) must not crowd out the terminal
# events every phase derivation hangs off — without the reserve, a
# capped timeline would END mid-prefill and e2e/ttft/decode would be
# silently wrong instead of merely truncated
FLIGHT_BOUNDARY_RESERVE = 16
BOUNDARY_EVENTS = frozenset((
    "batcher.first_token", "batcher.completed",
    "worker.done", "worker.stream.done",
    "pd.prefill.done", "pd.decode.done",
    "handoff.commit", "handoff.rx_commit", "handoff.failed",
    "server.completed",
))

# canonical phase names — the /metrics histogram label set and the bench
# attribution columns. Order is the documentation/reading order.
PHASES = ("queue_wait", "prefill", "ttft", "handoff", "decode", "e2e")


def flight_enabled() -> bool:
    """Process-wide recorder switch (default ON — the recorder is cheap
    enough to be always-on; per-request opt-in is the ``trace_id``)."""
    return os.environ.get("DGI_FLIGHT", "").strip().lower() not in (
        "0", "false", "off", "no",
    )


class _NullTimeline:
    """The recorder-off stand-in: every hook is a no-op, so hot paths call
    ``tl.note(...)`` unconditionally without branching on a flag."""

    __slots__ = ()
    enabled = False
    trace_id = ""
    events: List[Any] = []
    dropped = 0

    def note(self, name: str, **attrs: Any) -> None:
        pass

    def note_at(self, name: str, ts: float, **attrs: Any) -> None:
        pass

    def extend_at(self, events: Any) -> None:
        pass

    def wire(self, done: bool = False) -> Optional[Dict[str, Any]]:
        return None


NULL_TIMELINE = _NullTimeline()


def _safe_attrs(attrs: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """JSON-safe scalar attrs only — the wire rides job results and
    heartbeats, and one exotic value must not poison either channel."""
    if not attrs:
        return None
    out: Dict[str, Any] = {}
    for k, v in attrs.items():
        if v is None or isinstance(v, (bool, int, float)):
            out[str(k)] = v
        else:
            out[str(k)] = str(v)[:128]
    return out or None


class Timeline:
    """Per-request event accumulator (one per traced job/stream).

    Events are recorded as monotonic offsets from a wall-clock anchor
    captured at construction: intra-process ordering can never go
    backwards under a wall-clock step, while the wire format converts to
    wall-clock timestamps so timelines from different hosts merge on a
    shared (skew-tolerant, see ``merge_events``) axis.
    """

    __slots__ = ("trace_id", "source", "cap", "dropped",
                 "_wall0", "_mono0", "events")
    enabled = True

    def __init__(self, trace_id: str, source: str = "",
                 cap: int = FLIGHT_EVENT_CAP) -> None:
        self.trace_id = str(trace_id)
        self.source = str(source)
        self.cap = int(cap)
        self.dropped = 0
        self._wall0 = time.time()
        self._mono0 = time.monotonic()
        # [(name, wall_ts, attrs-or-None), ...]
        self.events: List[Any] = []

    # -- hot path ----------------------------------------------------------

    def _room_for(self, name: str) -> bool:
        n = len(self.events)
        if n >= self.cap:
            return False
        reserve = min(FLIGHT_BOUNDARY_RESERVE, self.cap // 2)
        if n >= self.cap - reserve and name not in BOUNDARY_EVENTS:
            return False
        return True

    def note(self, name: str, **attrs: Any) -> None:
        """Record one event NOW. List append + monotonic read; never
        raises (the recorder must never fail a request)."""
        if not self._room_for(name):
            self.dropped += 1
            return
        self.events.append(
            (name, self._wall0 + (time.monotonic() - self._mono0),
             attrs or None)
        )

    # -- boundary helpers --------------------------------------------------

    def note_at(self, name: str, ts: float, **attrs: Any) -> None:
        """Record one event at an explicit wall-clock timestamp (a
        boundary observed elsewhere — the poll pickup stamp, an engine
        slot's first-token time, a handoff receiver's commit)."""
        if not self._room_for(name):
            self.dropped += 1
            return
        try:
            self.events.append((name, float(ts), attrs or None))
        except (TypeError, ValueError):
            pass

    def extend_at(self, events: Any) -> None:
        """Adopt ``[(name, wall_ts), ...]`` pairs recorded by a component
        that has no timeline of its own (e.g. the HandoffReceiver, which
        knows only the session key). Malformed entries are skipped."""
        if not events:
            return
        for ev in events:
            try:
                self.note_at(str(ev[0]), float(ev[1]))
            except (TypeError, ValueError, IndexError):
                continue

    def wire(self, done: bool = False) -> Optional[Dict[str, Any]]:
        """Serialize for the result/heartbeat channel. Events are shipped
        as the FULL list each time — the server-side merge unions events
        per source keyed by (name, timestamp), so duplicate delivery (a
        heartbeat retried, a result replayed) is idempotent by
        construction."""
        if not self.events:
            return None
        out: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "events": [
                [name, round(ts, 6), _safe_attrs(attrs) if attrs else None]
                for name, ts, attrs in self.events
            ],
        }
        if self.source:
            out["source"] = self.source
        if self.dropped:
            out["dropped"] = int(self.dropped)
        if done:
            out["done"] = True
        return out


def timeline_for(params: Any, source: str = "") -> Any:
    """A :class:`Timeline` for the request iff its params carry a
    ``trace_id`` and the process-wide recorder is enabled; the shared
    no-op :data:`NULL_TIMELINE` otherwise (zero per-request cost)."""
    if not isinstance(params, dict):
        return NULL_TIMELINE
    tid = params.get("trace_id")
    if not tid or not isinstance(tid, str) or not flight_enabled():
        return NULL_TIMELINE
    return Timeline(tid, source=source)


# ---------------------------------------------------------------------------
# merge + phase derivation (server-side, and the bench's client-side reader)
# ---------------------------------------------------------------------------


def merge_events(sources: Dict[str, List[Any]]) -> List[Dict[str, Any]]:
    """Merge per-source event lists into ONE causally-ordered timeline.

    Sort by wall timestamp (source name, then within-source order break
    ties deterministically), then clamp each timestamp to be >= its
    predecessor: the merged view is monotonically ordered even when the
    sources' clocks are skewed. Clamping is display-side only — the
    per-source lists keep their raw timestamps."""
    rows: List[Any] = []
    for src in sorted(sources):
        for i, ev in enumerate(sources[src] or []):
            try:
                name = str(ev[0])
                ts = float(ev[1])
            except (TypeError, ValueError, IndexError):
                continue
            attrs = ev[2] if len(ev) > 2 else None
            rows.append((ts, str(src), i, name, attrs))
    rows.sort(key=lambda r: (r[0], r[1], r[2]))
    out: List[Dict[str, Any]] = []
    prev = None
    for ts, src, _i, name, attrs in rows:
        if prev is not None and ts < prev:
            ts = prev
        prev = ts
        row: Dict[str, Any] = {"event": name, "ts": round(ts, 6),
                               "source": src}
        if isinstance(attrs, dict) and attrs:
            row["attrs"] = attrs
        out.append(row)
    return out


def _first(times: Dict[str, float], *names: str) -> Optional[float]:
    for n in names:
        if n in times:
            return times[n]
    return None


def phase_durations(merged: List[Dict[str, Any]]) -> Dict[str, float]:
    """Derive the canonical phase durations (seconds) from a merged
    timeline. Every phase is optional — only boundaries actually present
    yield a duration, and a nonsensical (negative) span is dropped rather
    than reported. The event names consumed here are the canonical table
    in docs/observability.md."""
    if not merged:
        return {}
    first: Dict[str, float] = {}
    last: Dict[str, float] = {}
    for ev in merged:
        name, ts = ev["event"], float(ev["ts"])
        first.setdefault(name, ts)
        last[name] = ts
    start = float(merged[0]["ts"])
    end = float(merged[-1]["ts"])
    out: Dict[str, float] = {}

    def put(phase: str, t0: Optional[float], t1: Optional[float]) -> None:
        if t0 is not None and t1 is not None and t1 >= t0:
            out[phase] = t1 - t0

    # queue wait: worker-side batcher wait preferred (the contended
    # resource), server-side submit→claim wait otherwise (queued path)
    put("queue_wait",
        _first(first, "batcher.enqueued", "server.submitted"),
        _first(first, "batcher.admitted", "server.claimed"))
    put("prefill",
        _first(first, "pd.prefill.start", "batcher.admitted"),
        _first(first, "pd.prefill.done", "batcher.first_token"))
    put("ttft", start,
        _first(first, "batcher.first_token", "pd.prefill.done"))
    # sender notes handoff.begin/commit, the receiving worker's data
    # plane notes handoff.rx_begin/rx_commit: the phase opens at the
    # FIRST begin either side observed and closes at the LAST commit
    h0 = _first(first, "handoff.begin", "handoff.rx_begin")
    h1 = _first(last, "handoff.commit", "handoff.rx_commit") \
        if ("handoff.commit" in last or "handoff.rx_commit" in last) \
        else None
    if h1 is not None and "handoff.commit" in last \
            and "handoff.rx_commit" in last:
        h1 = max(last["handoff.commit"], last["handoff.rx_commit"])
    put("handoff", h0, h1)
    put("decode",
        _first(first, "pd.decode.start", "batcher.first_token"),
        _first(last, "pd.decode.done", "batcher.completed"))
    put("e2e", start, end)
    return out
