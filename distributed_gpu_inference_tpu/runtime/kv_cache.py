"""Host-side paged KV-cache management: block allocator, radix prefix index,
copy-on-write, LRU eviction, and multi-tier spill (HBM → host RAM → KV store).

Capability parity with the reference's ``worker/distributed/kv_cache.py``
(CacheBlock:34, PagedKVCache:79, KVCachePool:250, DistributedKVCacheManager:326
with L1 GPU / L2 CPU / L3 Redis tiers and get_or_compute:389-445) plus the
RadixAttention-style prefix sharing the reference rents from SGLang
(SURVEY §2.3) — re-designed for TPU:

- The *device* side is a pair of pool arrays ``[L, N, Hkv, block, D]`` owned by
  the engine and mutated **inside jitted graphs** (scatter writes, block
  copies). This module never holds device tensors for blocks; it owns the
  *metadata*: free lists, refcounts, the radix tree, LRU order, and tier maps.
- Device-side effects the metadata layer decides on (CoW copies, spill-in
  uploads) are returned to the engine as explicit op lists
  (:class:`PendingDeviceOps`) so the engine can apply them as one fused jitted
  update — the TPU analogue of the reference's eager ``torch.Tensor`` block
  copies.
- Block 0 is reserved as the pad/garbage block (padded-token writes land
  there) and is never allocated.
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributed_gpu_inference_tpu.testing import faults as _faults
from distributed_gpu_inference_tpu.utils.data_structures import (
    KV_BLOCK_TOKENS,
    KVBlockMeta,
    compute_prefix_hash,
)


class OutOfBlocksError(RuntimeError):
    pass


class SpillIntegrityError(ValueError):
    """A spilled entry failed its CRC — bit rot or a torn write. The probe
    path QUARANTINES the entry (best-effort delete + counter) and degrades
    to the next tier or recompute; this error never crosses a request."""


#: checksummed spill-entry framing (round 19): magic + CRC32 of the body.
#: Entries without the magic are the pre-round-19 legacy form and are
#: accepted unchecked — a mixed-version fleet sharing one remote tier must
#: keep hitting, and legacy entries age out under TTL anyway.
_SPILL_MAGIC = b"SPL2"


def _pack_spill(page: np.ndarray,
                scale_page: Optional[np.ndarray]) -> bytes:
    """L3 wire form of a spilled block: magic + CRC32, then the
    length-prefixed page blob and the optional scale blob. One entry per
    block — (page, scale) are atomic by construction, so there is no
    orphaned-scale state to defend against. The CRC covers the whole body,
    so both bit rot (corrupt read) and torn writes surface as
    :class:`SpillIntegrityError` at unpack time."""
    import zlib

    from distributed_gpu_inference_tpu.utils.serialization import (
        TensorSerializer,
    )

    ser = TensorSerializer()
    pb = ser.serialize(page)
    body = len(pb).to_bytes(8, "little") + pb
    if scale_page is not None:
        body += ser.serialize(scale_page)
    return _SPILL_MAGIC + zlib.crc32(body).to_bytes(4, "little") + body


def _unpack_spill(raw: bytes) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    import zlib

    from distributed_gpu_inference_tpu.utils.serialization import (
        TensorSerializer,
    )

    if raw[:4] == _SPILL_MAGIC:
        if len(raw) < 8:
            raise SpillIntegrityError(
                f"torn spill entry: {len(raw)} bytes is shorter than the "
                "checksummed header"
            )
        want = int.from_bytes(raw[4:8], "little")
        raw = raw[8:]
        got = zlib.crc32(raw)
        if got != want:
            raise SpillIntegrityError(
                f"spill entry checksum mismatch: stored {want:#010x}, "
                f"computed {got:#010x} over {len(raw)} bytes"
            )
    n = int.from_bytes(raw[:8], "little")
    if 8 + n > len(raw):
        raise ValueError(
            f"malformed spill entry: {n}-byte page blob overruns the "
            f"{len(raw)}-byte entry"
        )
    ser = TensorSerializer()
    page = ser.deserialize(raw[8:8 + n])
    scale = ser.deserialize(raw[8 + n:]) if len(raw) > 8 + n else None
    return page, scale


@dataclass
class PendingDeviceOps:
    """Device-side effects for the engine to apply in its next jitted update.

    downloads: (src_block, spill_key) pages to pull to host BEFORE any write
               (spill-on-evict: the block id is about to be reused)
    copies:    (src_block, dst_block) page copies (CoW / defrag)
    uploads:   (dst_block, host_kv) spill-tier promotions; host_kv is
               ``np.ndarray [L, 2, Hkv, block, D]`` (k and v stacked on axis 1)
    scale_uploads: (dst_block, host_scales) int8-KV scale pages riding with
               an adopted handoff; host_scales is ``np.ndarray
               [L, 2, block, D]`` (k and v scales stacked on axis 1). A
               separate channel (not a wider uploads tuple) so the many
               (bid, page) destructure sites stay valid.
    """

    downloads: List[Tuple[int, str]] = field(default_factory=list)
    copies: List[Tuple[int, int]] = field(default_factory=list)
    uploads: List[Tuple[int, np.ndarray]] = field(default_factory=list)
    scale_uploads: List[Tuple[int, np.ndarray]] = field(default_factory=list)

    def merge(self, other: "PendingDeviceOps") -> None:
        self.downloads.extend(other.downloads)
        self.copies.extend(other.copies)
        self.uploads.extend(other.uploads)
        self.scale_uploads.extend(other.scale_uploads)

    @property
    def empty(self) -> bool:
        return not (
            self.downloads or self.copies or self.uploads
            or self.scale_uploads
        )


class _RadixNode:
    __slots__ = ("children", "block_id", "parent", "edge", "last_access")

    def __init__(self, parent: Optional["_RadixNode"], edge: Optional[Tuple[int, ...]],
                 block_id: Optional[int]) -> None:
        self.children: Dict[Tuple[int, ...], _RadixNode] = {}
        self.block_id = block_id
        self.parent = parent
        self.edge = edge
        self.last_access = time.monotonic()


class RadixPrefixIndex:
    """Radix tree over full token blocks for prefix-cache lookup.

    Each edge is one *full* block of tokens (KV_BLOCK_TOKENS); a node holds the
    physical block id caching that prefix block. Partial blocks are never
    shared (matches vLLM semantics; the reference's SGLang engine exposes the
    same behavior through RadixAttention).
    """

    def __init__(self, block_size: int = KV_BLOCK_TOKENS) -> None:
        self.block_size = block_size
        self.root = _RadixNode(None, None, None)
        self._nodes_by_block: Dict[int, _RadixNode] = {}

    def _chunks(self, token_ids: Sequence[int]) -> List[Tuple[int, ...]]:
        bs = self.block_size
        n_full = len(token_ids) // bs
        return [tuple(token_ids[i * bs : (i + 1) * bs]) for i in range(n_full)]

    def match_prefix(self, token_ids: Sequence[int]) -> List[int]:
        """Longest cached full-block prefix → list of physical block ids."""
        node = self.root
        out: List[int] = []
        now = time.monotonic()
        for chunk in self._chunks(token_ids):
            child = node.children.get(chunk)
            if child is None:
                break
            child.last_access = now
            out.append(child.block_id)  # type: ignore[arg-type]
            node = child
        return out

    def insert(self, token_ids: Sequence[int], block_ids: Sequence[int]) -> int:
        """Index ``block_ids`` as the cache of the full blocks of ``token_ids``.

        Returns the number of *newly indexed* blocks (already-present prefix
        nodes are left untouched — caller dedups against match_prefix).
        """
        node = self.root
        added = 0
        for chunk, bid in zip(self._chunks(token_ids), block_ids):
            child = node.children.get(chunk)
            if child is None:
                child = _RadixNode(node, chunk, bid)
                node.children[chunk] = child
                self._nodes_by_block[bid] = child
                added += 1
            node = child
        return added

    def contains_block(self, block_id: int) -> bool:
        return block_id in self._nodes_by_block

    def is_leaf(self, block_id: int) -> bool:
        node = self._nodes_by_block.get(block_id)
        return node is not None and not node.children

    def remove_block(self, block_id: int) -> None:
        """Remove a (leaf) node from the tree; interior nodes must not be
        removed or descendant chains would dangle."""
        node = self._nodes_by_block.get(block_id)
        if node is None:
            return
        if node.children:
            raise ValueError(f"cannot evict interior radix block {block_id}")
        del self._nodes_by_block[block_id]
        assert node.parent is not None
        del node.parent.children[node.edge]  # type: ignore[index]

    def __len__(self) -> int:
        return len(self._nodes_by_block)


def make_radix_index(block_size: int = KV_BLOCK_TOKENS,
                     prefer_native: bool = True):
    """Prefix index factory: C++ implementation when the native library is
    buildable/loadable (``native/src/radix_index.cpp``), exact-semantics
    Python fallback otherwise. ``TPU_NATIVE=0`` forces the fallback."""
    if prefer_native:
        try:
            from distributed_gpu_inference_tpu.native import native_available

            if native_available():
                from distributed_gpu_inference_tpu.native.radix import (
                    NativeRadixPrefixIndex,
                )

                return NativeRadixPrefixIndex(block_size)
        except Exception as exc:  # any native issue → fallback, but say so
            logging.getLogger("tpu_native").warning(
                "native radix index unavailable, using Python fallback: %s",
                exc,
            )
    return RadixPrefixIndex(block_size)


@dataclass
class KVCacheStats:
    """Hit-rate statistics (reference kv_cache.py:544 get_stats)."""

    prefix_queries: int = 0
    prefix_hit_tokens: int = 0
    prefix_total_tokens: int = 0
    l1_hits: int = 0
    l2_hits: int = 0
    l3_hits: int = 0
    misses: int = 0
    evictions: int = 0
    spills: int = 0
    cow_copies: int = 0
    allocated_blocks: int = 0
    cached_blocks: int = 0
    free_blocks: int = 0
    window_released_blocks: int = 0

    def as_dict(self) -> Dict[str, Any]:
        d = dict(self.__dict__)
        d["prefix_hit_rate"] = (
            self.prefix_hit_tokens / self.prefix_total_tokens
            if self.prefix_total_tokens
            else 0.0
        )
        return d


class HostKVStore:
    """L2 host-RAM spill tier: block-content-keyed entries with LRU cap.

    An entry is one spilled BLOCK: a bare page array, or a
    ``(page, scale_page | None)`` tuple for int8 pools — one LRU slot per
    block either way, so ``max_blocks`` means what it says.

    Reference analogue: DistributedKVCacheManager's CPU OrderedDict tier
    (kv_cache.py:326, promote-on-hit :447-462).
    """

    def __init__(self, max_blocks: int = 1024) -> None:
        self.max_blocks = max_blocks
        self._store: "OrderedDict[str, Any]" = OrderedDict()

    def get(self, key: str) -> Optional[Any]:
        # chaos seam: host-RAM tier IO (an injected error models the NUMA
        # pool / pinned-buffer allocation failing, not bit rot — RAM
        # entries are objects, so corrupt/torn kinds live on the remote
        # tier's byte seams instead)
        _faults.io_fault("io.spill.host.get", key=key)
        arr = self._store.get(key)
        if arr is not None:
            self._store.move_to_end(key)
        return arr

    def put(self, key: str, value: Any) -> None:
        if self.max_blocks <= 0:
            return
        _faults.io_fault("io.spill.host.put", key=key)
        self._store[key] = value
        self._store.move_to_end(key)
        while len(self._store) > self.max_blocks:
            self._store.popitem(last=False)

    def delete(self, key: str) -> None:
        """Quarantine hook: drop one entry (no seam — eviction of a bad
        entry must never be blockable by the chaos that exposed it)."""
        self._store.pop(key, None)

    def __len__(self) -> int:
        return len(self._store)


class RemoteKVStore:
    """L3 tier interface (reference: Redis with TTL, kv_cache.py:477-520).

    The in-process default is a TTL dict; a Redis/remote-store client can be
    dropped in by implementing get/put. Values are serialized frames so this
    tier can sit behind a network boundary.
    """

    def __init__(self, ttl_s: float = 3600.0) -> None:
        self.ttl_s = ttl_s
        self._store: Dict[str, Tuple[float, bytes]] = {}

    def get(self, key: str) -> Optional[bytes]:
        item = self._store.get(key)
        if item is None:
            # the seam still fires on a miss (an io_error is a failed READ,
            # hit or not) — mutating kinds pass None through untouched
            return _faults.io_bytes("io.spill.remote.get", None, key=key)
        expires, data = item
        if time.monotonic() > expires:
            del self._store[key]
            return None
        # chaos seam: corrupt reads flip a byte, short reads truncate,
        # errors raise OSError — what the entry CRC + quarantine defend
        return _faults.io_bytes("io.spill.remote.get", data, key=key)

    def put(self, key: str, data: bytes) -> None:
        # chaos seam: a torn write persists only a prefix — detected at
        # read time by the CRC, exactly like real partial-flush loss
        data = _faults.io_bytes("io.spill.remote.put", data, key=key)
        self._store[key] = (time.monotonic() + self.ttl_s, data)

    def delete(self, key: str) -> None:
        """Quarantine hook: evict one (corrupt) entry."""
        self._store.pop(key, None)

    def purge_expired(self) -> int:
        now = time.monotonic()
        dead = [k for k, (exp, _) in self._store.items() if now > exp]
        for k in dead:
            del self._store[k]
        return len(dead)


class PagedKVCacheManager:
    """Metadata brain for the device KV pools.

    Responsibilities (reference PagedKVCache:79 + KVCachePool:250 +
    DistributedKVCacheManager:326, unified):

    - allocate/free per-sequence block chains with rollback on exhaustion
    - radix prefix reuse with refcounted sharing + copy-on-write
    - LRU eviction of cached (ref==0) leaf blocks, optional spill to L2/L3
    - emits :class:`PendingDeviceOps` for the engine's jitted pool updates
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int = KV_BLOCK_TOKENS,
        enable_prefix_cache: bool = True,
        host_store: Optional[HostKVStore] = None,
        remote_store: Optional[RemoteKVStore] = None,
        spill_on_evict: bool = False,
        kv_dtype: Optional[Any] = None,
    ) -> None:
        """``kv_dtype``: the engine's pool dtype — a probe hit must match
        it exactly (a token-keyed store shared across engines must never
        hand a bf16 engine int8 codes, f32 pages to a bf16 engine, etc.),
        and int8 hits must carry their scale page (spilled as one atomic
        (page, scale) entry). None disables the screen (manager used
        standalone in tests)."""
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_cache = enable_prefix_cache
        self.host_store = host_store
        self.remote_store = remote_store
        self.spill_on_evict = spill_on_evict
        self.kv_dtype = np.dtype(kv_dtype) if kv_dtype is not None else None
        self.quantized_kv = self.kv_dtype == np.int8

        # durable-tier immunity (round 19): per-tier circuit breakers +
        # cumulative error/quarantine counters. A tier put/get that raises
        # is counted and SKIPPED — an optional cache tier can never fail
        # eviction or a request (the PR 13 contract) — and a tier failing
        # repeatedly trips open so serving stops paying its latency tax.
        # Counters ride heartbeats (spill_wire_stats → engine_stats
        # ["kv_spill"]) into kv_spill_errors_total / spill_quarantined_
        # total / io_breaker_state on the plane.
        from distributed_gpu_inference_tpu.runtime.io_guard import (
            IOBreaker,
            breaker_env_config,
        )

        bcfg = breaker_env_config()
        self.breakers: Dict[str, Any] = {}
        if not bcfg["disabled"]:
            for tier in ("host", "remote"):
                self.breakers[tier] = IOBreaker(
                    tier, threshold=bcfg["threshold"],
                    open_s=bcfg["open_s"], jitter=bcfg["jitter"],
                )
        self.spill_io: Dict[str, int] = {
            "host_put_errors": 0, "host_get_errors": 0,
            "remote_put_errors": 0, "remote_get_errors": 0,
            "host_quarantined_corrupt": 0, "remote_quarantined_corrupt": 0,
            "breaker_skips": 0,
        }

        self.metas: Dict[int, KVBlockMeta] = {}
        self.free_list: List[int] = list(range(num_blocks - 1, 0, -1))  # pop() → 1..
        self.cached_lru: "OrderedDict[int, None]" = OrderedDict()  # ref==0, indexed
        self.radix = make_radix_index(block_size)
        self.seq_blocks: Dict[str, List[int]] = {}
        self.seq_tokens: Dict[str, List[int]] = {}
        self.seq_shared_count: Dict[str, int] = {}
        # first logical block not yet window-released, per sequence — keeps
        # release_out_of_window O(1) amortized instead of rescanning the
        # released prefix every decoded token
        self.seq_window_front: Dict[str, int] = {}
        self.stats = KVCacheStats()
        self.pending = PendingDeviceOps()

    # -- core alloc ---------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self.free_list)

    @property
    def num_reclaimable(self) -> int:
        return len(self.free_list) + len(self.cached_lru)

    def _pop_free_block(self) -> int:
        # chaos seam: a fired ``pressure`` rule makes this allocation see a
        # pool with zero free (and zero evictable) blocks — the same
        # OutOfBlocksError a saturated pool raises, so seeded storms drive
        # the engine/batcher preempt → spill → resume path end to end
        if _faults.kv_pressure("kv.block.alloc", num_free=len(self.free_list)):
            raise OutOfBlocksError(
                f"KV pool exhausted (kv_pressure fault injected with "
                f"{len(self.free_list)} actually free)"
            )
        if self.free_list:
            bid = self.free_list.pop()
        else:
            bid = self._evict_one()
        self.metas[bid] = KVBlockMeta(block_id=bid, capacity=self.block_size)
        self.stats.allocated_blocks += 1
        return bid

    def _evict_one(self) -> int:
        """Evict the LRU cached *leaf* block (reference LRU evict :229-238)."""
        for bid in list(self.cached_lru.keys()):
            if self.radix.is_leaf(bid):
                self._evict_block(bid)
                return bid
        raise OutOfBlocksError(
            f"KV pool exhausted: 0 free, {len(self.cached_lru)} cached "
            "(all interior), all others pinned by active sequences"
        )

    def clear_cached(self, spill: bool = False) -> int:
        """Drop EVERY reclaimable cached block back to the free list →
        count dropped. For bench sweeps (each measured configuration must
        start cold) and admin cache flushes. ``spill`` False suppresses
        spill-on-evict so a flush doesn't flood the spill tiers with
        pages nobody asked to keep."""
        n = 0
        saved = self.spill_on_evict
        self.spill_on_evict = spill and saved
        try:
            # leaf-at-a-time: parents become leaves as children go
            while self.cached_lru:
                self.free_list.append(self._evict_one())
                n += 1
        finally:
            self.spill_on_evict = saved
        return n

    def _evict_block(self, bid: int) -> None:
        meta = self.metas.pop(bid, None)
        if self.cached_lru.pop(bid, False) is None:
            # was present (values are literal None): keep the gauge honest
            self.stats.cached_blocks -= 1
        if self.spill_on_evict and meta is not None and meta.prefix_hash \
                and (self.host_store is not None
                     or self.remote_store is not None):
            # the block id is about to be reused: the engine pulls the page
            # to host FIRST (downloads run before any write in
            # _apply_pending) and hands it to store_spilled()
            self.pending.downloads.append((bid, meta.prefix_hash))
            self.stats.spills += 1
        self.radix.remove_block(bid)
        self.stats.evictions += 1

    # -- spill tiers (reference get_or_compute chain, kv_cache.py:389-462) ---

    # -- tier guards (round 19): breaker gating + error isolation ------------

    def _tier_allow(self, tier: str) -> bool:
        """Breaker gate for one tier; an open breaker skips the tier
        entirely (and counts the skip) — no per-op latency tax from a
        browned-out device."""
        br = self.breakers.get(tier)
        if br is None or br.allow():
            return True
        self.spill_io["breaker_skips"] += 1
        return False

    def _tier_result(self, tier: str, ok: bool, op: str) -> None:
        br = self.breakers.get(tier)
        if ok:
            if br is not None:
                br.record_success()
            return
        self.spill_io[f"{tier}_{op}_errors"] += 1
        if br is not None:
            br.record_failure()
            if not br.closed:
                logging.getLogger("dgi_kv_spill").warning(
                    "spill tier %r breaker %s after %s failure",
                    tier, br.state, op,
                )

    def _quarantine(self, tier: str, key: str, reason: str) -> None:
        """A provably bad entry (CRC mismatch, torn frame) is deleted from
        its tier — best-effort: the delete itself failing must not block
        the degraded read path — and counted. Mirrors the handoff
        corrupt-piece contract: poison stays local, requests recompute."""
        store = self.host_store if tier == "host" else self.remote_store
        try:
            delete = getattr(store, "delete", None)
            if delete is not None:
                delete(key)
        except Exception:  # noqa: BLE001 — quarantine is best-effort
            pass
        self.spill_io[f"{tier}_quarantined_{reason}"] += 1

    def spill_wire_stats(self) -> Dict[str, int]:
        """Cumulative spill-IO counters + breaker states for the heartbeat
        ``engine_stats["kv_spill"]`` channel (plane delta-anchors the
        counters; breaker states are gauges)."""
        out = dict(self.spill_io)
        for tier, br in self.breakers.items():
            out[f"breaker_{tier}_state"] = br.state_code
            out[f"breaker_{tier}_trips"] = br.trips
        return out

    def store_spilled(self, key: str, page: np.ndarray,
                      scale_page: Optional[np.ndarray] = None) -> None:
        """Engine callback with the evicted page bytes: L2 host store plus
        write-through to L3 (reference async Redis writeback :506-520).

        ``scale_page`` (int8 pools, [L, 2, Bk, D] bf16): packed WITH the
        page as one atomic entry per block in both tiers — a page without
        its scale is garbage, the pair costs one LRU slot, and there is no
        orphaned-scale state.

        Tier writes are ISOLATED: a raising put is counted and skipped —
        losing a spill is a future miss, never a failed eviction (and
        never a failed request). A tier failing repeatedly trips its
        breaker and is skipped wholesale until a half-open probe heals."""
        if self.host_store is not None and self._tier_allow("host"):
            try:
                self.host_store.put(key, (page, scale_page))
            except Exception:  # noqa: BLE001 — optional tier, never fatal
                self._tier_result("host", False, "put")
            else:
                self._tier_result("host", True, "put")
        if self.remote_store is not None and self._tier_allow("remote"):
            try:
                self.remote_store.put(key, _pack_spill(page, scale_page))
            except Exception:  # noqa: BLE001 — optional tier, never fatal
                self._tier_result("remote", False, "put")
            else:
                self._tier_result("remote", True, "put")

    def _spill_entry_valid(self, page: np.ndarray,
                           scale: Optional[np.ndarray]) -> bool:
        """Screen a probed entry BEFORE adopting (or promoting) it: the
        page dtype must match this engine's pools exactly — a token-keyed
        store shared across engines of different dtypes must degrade to a
        miss, never a silent cast — and int8 entries must carry scales."""
        if self.kv_dtype is not None and page.dtype != self.kv_dtype:
            return False
        if self.quantized_kv and scale is None:
            return False
        return True

    def _probe_spill(
        self, key: str
    ) -> Optional[Tuple[np.ndarray, Optional[np.ndarray]]]:
        """Probe the tiers for a spilled block → (page, scale_page | None),
        or None on miss. An L3 hit is promoted to L2 (reference
        promote-on-hit :447-462) — but only AFTER validation, so a
        known-rejected entry never pollutes the bounded L2.

        Failure semantics (round 19): a RAISING tier get is counted,
        charged to the tier's breaker, and falls through to the next tier;
        a corrupt L3 entry (CRC mismatch / torn frame) is QUARANTINED
        (deleted + counted) and degrades to a miss; a failing promote put
        never discards the successfully fetched page. Nothing in here can
        fail the request that probed."""
        if self.host_store is not None and self._tier_allow("host"):
            entry: Any = None
            try:
                entry = self.host_store.get(key)
            except Exception:  # noqa: BLE001 — fall through to L3
                self._tier_result("host", False, "get")
            else:
                self._tier_result("host", True, "get")
            if entry is not None:
                page, scale = (
                    entry if isinstance(entry, tuple) else (entry, None)
                )
                if self._spill_entry_valid(page, scale):
                    self.stats.l2_hits += 1
                    return page, scale
                return None
        if self.remote_store is not None and self._tier_allow("remote"):
            raw = None
            try:
                raw = self.remote_store.get(key)
            except Exception:  # noqa: BLE001 — degraded tier = miss
                self._tier_result("remote", False, "get")
            else:
                self._tier_result("remote", True, "get")
            if raw is not None:
                try:
                    page, scale = _unpack_spill(raw)
                except Exception:
                    # corrupt entry: quarantine so the NEXT probe doesn't
                    # pay the deserialize-and-fail tax again, then miss
                    self._quarantine("remote", key, "corrupt")
                    return None
                if self._spill_entry_valid(page, scale):
                    self.stats.l3_hits += 1
                    if self.host_store is not None:
                        # promote-on-hit is advisory: a failing host put
                        # must NOT discard the page we already fetched
                        try:
                            self.host_store.put(key, (page, scale))
                        except Exception:  # noqa: BLE001
                            self._tier_result("host", False, "put")
                    return page, scale
        return None

    # -- sequence lifecycle -------------------------------------------------

    def allocate_sequence(self, seq_id: str, token_ids: Sequence[int]) -> Tuple[List[int], int]:
        """Allocate the block chain for a prompt. Returns (block_ids,
        num_cached_tokens) — the first ``num_cached_tokens`` positions already
        hold valid KV from the prefix cache (engine skips recomputing them).

        Rollback on exhaustion (reference KVCachePool:283-313).
        """
        if seq_id in self.seq_blocks:
            raise ValueError(f"sequence {seq_id} already allocated")
        # probe the radix index with the CALLER's representation: a numpy
        # array crosses the native ABI zero-copy (the fast path — engines and
        # tokenizers should pass arrays); only the stored copy is a list
        probe = token_ids
        if isinstance(token_ids, np.ndarray):
            token_ids = token_ids.tolist()  # one C pass, python ints out
        else:
            token_ids = [int(t) for t in token_ids]
        n_tokens = len(token_ids)
        needed_blocks = max(1, -(-n_tokens // self.block_size))

        cached: List[int] = []
        spill_pages: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
        if self.enable_prefix_cache:
            self.stats.prefix_queries += 1
            self.stats.prefix_total_tokens += n_tokens
            cached = self.radix.match_prefix(probe)
            # never reuse the *entire* prompt from cache: the last token's
            # logits must be recomputed, so keep at least one token fresh
            while cached and len(cached) * self.block_size >= n_tokens:
                cached.pop()
            # L1 miss past this point: probe the spill tiers block-by-block
            # (reference get_or_compute chain) — restored pages re-upload
            # into freshly allocated blocks, same fresh-token rule applies
            if self.host_store is not None or self.remote_store is not None:
                idx = len(cached)
                while (idx + 1) * self.block_size < n_tokens:
                    key = compute_prefix_hash(
                        token_ids, (idx + 1) * self.block_size
                    )
                    hit = self._probe_spill(key)
                    if hit is None:
                        break
                    spill_pages.append(hit)
                    idx += 1
        num_cached_tokens = (len(cached) + len(spill_pages)) * self.block_size
        self.stats.prefix_hit_tokens += num_cached_tokens
        if cached or spill_pages:
            self.stats.l1_hits += len(cached)
        else:
            self.stats.misses += 1

        blocks: List[int] = []
        try:
            for bid in cached:
                meta = self.metas[bid]
                if bid in self.cached_lru:  # revive from cached → active
                    del self.cached_lru[bid]
                    self.stats.cached_blocks -= 1
                    meta.ref_count = 1
                else:
                    meta.incref()
                meta.touch()
                blocks.append(bid)
            for page, scale_page in spill_pages:
                bid = self._pop_free_block()
                self.pending.uploads.append((bid, page))
                if scale_page is not None:
                    self.pending.scale_uploads.append((bid, scale_page))
                blocks.append(bid)
            for _ in range(needed_blocks - len(blocks)):
                blocks.append(self._pop_free_block())
        except OutOfBlocksError:
            # undo exactly what was done: drop OUR reference only; a block
            # another sequence still holds must never reach the free list.
            # Staged uploads for OUR fresh blocks must not fire either.
            ours = set(blocks) - set(cached)
            if ours:
                self.pending.uploads = [
                    (b, p) for b, p in self.pending.uploads if b not in ours
                ]
                self.pending.scale_uploads = [
                    (b, p) for b, p in self.pending.scale_uploads
                    if b not in ours
                ]
            for bid in blocks:
                if self.metas[bid].decref() == 0:
                    self._deactivate_block(bid)
            raise
        if spill_pages:
            # index the restored chain so concurrent/future requests hit L1
            n_idx = len(cached) + len(spill_pages)
            self.radix.insert(
                token_ids[: n_idx * self.block_size], blocks[:n_idx]
            )
        self.seq_blocks[seq_id] = blocks
        self.seq_tokens[seq_id] = token_ids
        self.seq_shared_count[seq_id] = len(cached) + len(spill_pages)
        return blocks, num_cached_tokens

    def append_token(self, seq_id: str, token_id: int) -> Optional[int]:
        """Account one generated token; returns a newly allocated block id if
        the sequence crossed a block boundary, else None. Applies CoW if the
        tail block is shared."""
        blocks = self.seq_blocks[seq_id]
        tokens = self.seq_tokens[seq_id]
        pos = len(tokens)
        tokens.append(token_id)
        logical = pos // self.block_size
        if logical >= len(blocks):
            bid = self._pop_free_block()
            blocks.append(bid)
            return bid
        tail = blocks[logical]
        meta = self.metas[tail]
        if meta.is_shared:
            new_bid = self._pop_free_block()
            meta.decref()
            blocks[logical] = new_bid
            self.pending.copies.append((tail, new_bid))
            self.stats.cow_copies += 1
            return new_bid
        return None

    def reserve_tokens(self, seq_id: str, n: int) -> List[int]:
        """Pre-allocate blocks so the sequence can grow by ``n`` tokens without
        further allocation (required before a multi-step on-device decode scan,
        where the host cannot allocate mid-scan). Also copy-on-writes a shared
        tail block. Returns newly allocated block ids."""
        blocks = self.seq_blocks[seq_id]
        cur = len(self.seq_tokens[seq_id])
        needed = max(1, -(-(cur + n) // self.block_size))
        added: List[int] = []
        try:
            # CoW the block the next token lands in, if shared
            logical = cur // self.block_size
            if logical < len(blocks):
                tail = blocks[logical]
                meta = self.metas[tail]
                if meta.is_shared:
                    new_bid = self._pop_free_block()
                    meta.decref()
                    blocks[logical] = new_bid
                    self.pending.copies.append((tail, new_bid))
                    self.stats.cow_copies += 1
                    added.append(new_bid)
            while len(blocks) < needed:
                bid = self._pop_free_block()
                blocks.append(bid)
                added.append(bid)
        except OutOfBlocksError:
            raise
        return added

    def trim_reserved(self, seq_id: str) -> List[int]:
        """Release trailing reserved blocks beyond what the sequence's
        tokens (committed + pending) occupy — the precise rollback after a
        partially rejected speculative verify window. Leaves the sequence
        holding exactly ``ceil(len(seq_tokens)/block_size)`` blocks, i.e.
        the same footprint a never-speculated per-step engine keeps.
        Returns the freed block ids (the engine refreshes its block-table
        mirror; device state never reads the trimmed tail — its positions
        are beyond the committed length)."""
        blocks = self.seq_blocks[seq_id]
        needed = max(1, -(-len(self.seq_tokens[seq_id]) // self.block_size))
        freed: List[int] = []
        while len(blocks) > needed:
            bid = blocks.pop()
            meta = self.metas.get(bid)
            # reserved tail blocks are exclusively owned and unindexed, but
            # go through decref/_deactivate_block so an unexpected share
            # can never be force-freed
            if meta is not None and meta.decref() == 0:
                self._deactivate_block(bid)
            freed.append(bid)
        return freed

    def commit_tokens(self, seq_id: str, token_ids: Sequence[int]) -> None:
        """Record tokens whose KV was written on-device into already-reserved
        blocks (the multi-step decode path's post-scan bookkeeping)."""
        self.seq_tokens[seq_id].extend(int(t) for t in token_ids)
        if (len(self.seq_tokens[seq_id]) + self.block_size - 1) // self.block_size \
                > len(self.seq_blocks[seq_id]):
            raise RuntimeError(
                f"sequence {seq_id} outgrew its reserved blocks — reserve_tokens "
                "must cover the scan horizon"
            )

    def release_out_of_window(self, seq_id: str, window: int) -> List[int]:
        """Sliding-window models (Mistral): free leading blocks every future
        query is past. A query at position p sees keys in (p - window, p];
        the earliest future query is the pending token at position cur - 1
        (``seq_tokens`` counts committed + pending), which still sees key
        cur - window — so only keys ≤ cur - 1 - window are dead. Freed
        logical slots are pinned to the reserved pad block 0 — the attention
        window mask already drops those logical positions, so a pad-block
        read is never visible. Returns the released logical indices (the
        engine zeroes its block-table rows to match).

        This converts mask-only SWA into window-bounded KV memory — the
        rolling-buffer benefit vLLM gets for Mistral, without re-indexing."""
        blocks = self.seq_blocks[seq_id]
        cur = len(self.seq_tokens[seq_id])
        released: List[int] = []
        lb = self.seq_window_front.get(seq_id, 0)
        while lb < len(blocks):
            # block lb covers positions [lb*Bk, (lb+1)*Bk); dead iff its last
            # position (lb+1)*Bk - 1 ≤ cur - 1 - window
            if (lb + 1) * self.block_size > cur - window:
                break
            bid = blocks[lb]
            meta = self.metas.get(bid)
            if meta is not None and meta.decref() == 0:
                self._deactivate_block(bid)
            blocks[lb] = 0
            released.append(lb)
            lb += 1
        if released:
            self.seq_window_front[seq_id] = lb
            self.stats.window_released_blocks += len(released)
        return released

    def seed_window_front(self, seq_id: str, front_blocks: int) -> List[int]:
        """Replicate a donor's sliding-window release state on an adopted
        sequence (PD handoff): force-release the leading ``front_blocks``
        logical blocks — decref/free the physical blocks, pin the chain
        entries to pad block 0, and record ``seq_window_front`` so
        ``free_sequence`` keeps the truncated chain out of the radix index
        (ADVICE r1 #1). Returns the released logical indices."""
        blocks = self.seq_blocks[seq_id]
        released: List[int] = []
        lb = self.seq_window_front.get(seq_id, 0)
        while lb < min(front_blocks, len(blocks)):
            bid = blocks[lb]
            if bid != 0:
                meta = self.metas.get(bid)
                if meta is not None and meta.decref() == 0:
                    self._deactivate_block(bid)
            blocks[lb] = 0
            released.append(lb)
            lb += 1
        if lb > self.seq_window_front.get(seq_id, 0):
            self.seq_window_front[seq_id] = lb
        return released

    def free_sequence(self, seq_id: str, cache: bool = True) -> None:
        """Release a sequence's blocks; full blocks are kept as prefix cache
        (ref 0, LRU-ordered) when ``cache=True``."""
        blocks = self.seq_blocks.pop(seq_id)
        tokens = self.seq_tokens.pop(seq_id, [])
        self.seq_shared_count.pop(seq_id, None)
        n_full = len(tokens) // self.block_size
        if self.seq_window_front.pop(seq_id, 0) > 0 or 0 in blocks[:n_full]:
            # window-released leading blocks: the chain is no longer a valid
            # prefix, so it cannot enter the radix index
            cache = False
        if cache and self.enable_prefix_cache and n_full > 0:
            idx_tokens: Sequence[int] = tokens
            if getattr(self.radix, "wants_arrays", False):
                # one bulk conversion → zero-copy across the native ABI
                idx_tokens = np.asarray(tokens, np.int32)
            self.radix.insert(idx_tokens, blocks[:n_full])
        for i, bid in enumerate(blocks):
            meta = self.metas.get(bid)
            if meta is None:
                continue
            remaining = meta.decref()
            if remaining == 0:
                if cache and self.enable_prefix_cache and i < n_full and \
                        self.radix.contains_block(bid):
                    full_tokens = (i + 1) * self.block_size
                    meta.prefix_hash = compute_prefix_hash(tokens, full_tokens)
                self._deactivate_block(bid)

    def _scrub_pending_for(self, bid: int) -> None:
        """Withdraw staged device ops that reference a block returning to
        the free list: the id can be reallocated before the ops apply, and
        a stale upload/copy would clobber the new owner's pages. Downloads
        are never scrubbed — a spill-on-evict download is the evicted
        page's only copy."""
        p = self.pending
        if p.copies:
            # filter by DESTINATION only: a freed source's page bytes are
            # still intact until the id is reallocated AND rewritten, and
            # the CoW owner needs them — the dst, though, must never be
            # written once it can belong to someone else
            p.copies = [c for c in p.copies if c[1] != bid]
        if p.uploads:
            p.uploads = [u for u in p.uploads if u[0] != bid]
        if p.scale_uploads:
            p.scale_uploads = [u for u in p.scale_uploads if u[0] != bid]

    def _deactivate_block(self, bid: int) -> None:
        """A block whose refcount just hit 0: park it as reusable cache if the
        radix still indexes it (interior nodes CANNOT be freed — descendant
        chains would dangle and match_prefix would hand out a freed id);
        otherwise return it to the free list."""
        if self.radix.contains_block(bid):
            self.cached_lru[bid] = None
            self.cached_lru.move_to_end(bid)
            self.stats.cached_blocks += 1
        else:
            self.metas.pop(bid, None)
            self._scrub_pending_for(bid)
            self.free_list.append(bid)

    def _release_block(self, bid: int) -> None:
        """Force-free a block KNOWN to be unreferenced and unindexed."""
        self.metas.pop(bid, None)
        self.cached_lru.pop(bid, None)
        if self.radix.contains_block(bid):
            if not self.radix.is_leaf(bid):
                raise ValueError(
                    f"refusing to force-free interior radix block {bid}"
                )
            self.radix.remove_block(bid)
        self._scrub_pending_for(bid)
        self.free_list.append(bid)

    # -- engine handshake ---------------------------------------------------

    def take_pending_ops(self) -> PendingDeviceOps:
        ops, self.pending = self.pending, PendingDeviceOps()
        return ops

    def block_table_for(self, seq_id: str, max_blocks: int, pad: int = 0) -> np.ndarray:
        blocks = self.seq_blocks[seq_id]
        if len(blocks) > max_blocks:
            raise ValueError(
                f"sequence {seq_id} uses {len(blocks)} blocks > table width {max_blocks}"
            )
        table = np.full((max_blocks,), pad, dtype=np.int32)
        table[: len(blocks)] = blocks
        return table

    def get_stats(self) -> Dict[str, Any]:
        self.stats.free_blocks = len(self.free_list)
        return self.stats.as_dict()
