"""Worker-side radix summary: the compact, bounded view of a worker's
prefix cache that rides heartbeats so the control plane can route for
locality (server/prefix_routing.py consumes it).

Design constraints, in order:

- **Bounded.** A worker serving millions of requests must advertise a
  fixed-size summary: entries are boundary fingerprints
  (``utils/prefixes.py``) held in an LRU of ``top_n`` — the *hot* set,
  not the whole radix tree.
- **Cheap on the hot path.** ``note()`` is called once per built request
  (one rolling-hash pass over ≤ ``MAX_PREFIX_BLOCKS`` blocks of text) and
  takes a lock only for dict bookkeeping.
- **Small on the wire.** Heartbeats carry deltas against the last state
  the server ACKed; a full snapshot goes out only on first contact or
  when the server asks for a resync (its view was lost — restart,
  missed delta, version change). The ack protocol is explicit because
  heartbeats are lossy: a delta is only committed as "known to the
  server" after the heartbeat round-trip succeeds.
- **Advisory.** Entries describe what was recently *seen* (and therefore
  very likely cached), not a transactional cache inventory. Eviction on
  the worker quietly invalidates entries; the server's staleness TTL and
  the engine's own prefix-cache probe bound the cost of a wrong hint to
  one re-prefill.

Wire format (versioned — the server rejects unknown versions):

    full:  {"v": 1, "seq": S, "block_chars": B, "full": [[fp, d, t], ...]}
    delta: {"v": 1, "seq": S, "base_seq": S0, "block_chars": B,
            "add": [[fp, d, t], ...], "del": [fp, ...]}

``fp`` is a boundary fingerprint, ``d`` its 1-based block depth, ``t`` a
tier tag (``dev`` = device-resident, ``host`` = host-RAM spill tier,
``spill`` = REMOTE-store spill tier). Since round 13 the tag is priced by
the router's KV-migration cost model (a remote-tier pull costs more than
a dev-tier one), so workers advertise the tier their evicted KV actually
landed in, not a blanket demotion.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..utils.prefixes import (
    MAX_PREFIX_BLOCKS,
    PREFIX_BLOCK_CHARS,
    canonical_prompt_text,
    prefix_fingerprints,
)

SUMMARY_WIRE_VERSION = 1

TIER_DEVICE = "dev"
TIER_HOST = "host"
TIER_SPILL = "spill"


class PrefixHotSet:
    """Bounded LRU of hot prefix-boundary fingerprints + delta encoder."""

    def __init__(self, top_n: int = 128,
                 block_chars: int = PREFIX_BLOCK_CHARS,
                 max_blocks: int = MAX_PREFIX_BLOCKS) -> None:
        self.top_n = max(1, int(top_n))
        self.block_chars = int(block_chars)
        self.max_blocks = int(max_blocks)
        self._lock = threading.Lock()
        # fp -> (depth, tier); insertion/touch order IS the LRU order
        self._entries: "OrderedDict[str, Tuple[int, str]]" = OrderedDict()
        self.seq = 0
        # last state the server ACKed (None = never synced → send full)
        self._acked: Optional[Dict[str, Tuple[int, str]]] = None
        self._acked_seq = 0
        # state shipped in the last wire() payload, committed by ack()
        self._pending: Optional[Dict[str, Tuple[int, str]]] = None
        self._pending_seq = 0
        self.stats = {"notes": 0, "evicted": 0, "wire_full": 0,
                      "wire_delta": 0, "resyncs": 0}

    # -- hot-path recording --------------------------------------------------

    def note(self, prompt_or_messages: Any,
             tier: str = TIER_DEVICE) -> int:
        """Record one served prompt: every full-block boundary fingerprint
        of its canonical text enters (or refreshes) the hot set. Returns
        the number of boundaries recorded."""
        return self.note_fingerprints(
            prefix_fingerprints(
                canonical_prompt_text(prompt_or_messages),
                self.block_chars, self.max_blocks,
            ),
            tier=tier,
        )

    def note_fingerprints(self, fps: List[str],
                          tier: str = TIER_DEVICE) -> int:
        """Record an already-computed boundary-fingerprint chain (depth
        order). Split from :meth:`note` so callers that hold the chain —
        a completed proactive-replication pull advertising adopted KV, a
        request builder that also feeds the export fp→tokens map — skip
        the hash pass. Semantics identical to :meth:`note`."""
        if not fps:
            return 0
        with self._lock:
            for depth, fp in enumerate(fps, start=1):
                if fp in self._entries:
                    # refresh recency; deepen/repair tier but never let a
                    # shallower duplicate shrink a recorded depth
                    d0, _ = self._entries[fp]
                    self._entries[fp] = (max(d0, depth), tier)
                    self._entries.move_to_end(fp)
                else:
                    self._entries[fp] = (depth, tier)
            while len(self._entries) > self.top_n:
                self._entries.popitem(last=False)
                self.stats["evicted"] += 1
            self.seq += 1
            self.stats["notes"] += 1
        return len(fps)

    def clear(self) -> None:
        """Empty the hot set (e.g. the engine's prefix cache was wiped):
        the next :meth:`wire` advertises the deletions so the control
        plane stops routing to KV that no longer exists."""
        with self._lock:
            if self._entries:
                self._entries.clear()
                self.seq += 1

    def drop(self, fraction: float) -> int:
        """Forget the coldest ``fraction`` of entries — used when the pool
        evicts WITHOUT a spill tier: those blocks are simply gone, and
        keeping them advertised (even demoted) would over-promise KV the
        worker must fully re-prefill."""
        with self._lock:
            n = int(len(self._entries) * max(0.0, min(1.0, fraction)))
            for fp in list(self._entries.keys())[:n]:
                del self._entries[fp]
            if n:
                self.seq += 1
                self.stats["evicted"] += n
            return n

    def demote(self, fraction: float, tier: str = TIER_HOST) -> int:
        """Mark the coldest ``fraction`` of entries as spilled off-device
        (the engine calls this when its manager reports evictions with
        spill tiers enabled — an estimate, like everything here)."""
        with self._lock:
            n = int(len(self._entries) * max(0.0, min(1.0, fraction)))
            changed = 0
            for fp in list(self._entries.keys())[:n]:
                depth, t0 = self._entries[fp]
                if t0 == TIER_DEVICE:
                    self._entries[fp] = (depth, tier)
                    changed += 1
            if changed:
                self.seq += 1
            return changed

    def __len__(self) -> int:
        return len(self._entries)

    # -- wire protocol --------------------------------------------------------

    def wire(self) -> Optional[Dict[str, Any]]:
        """Build the next heartbeat payload, or None when the server is
        already up to date. The snapshot it describes is held as *pending*
        until :meth:`ack` (heartbeat succeeded) or :meth:`resync`
        (heartbeat lost / server asked for a full)."""
        with self._lock:
            snap = dict(self._entries)
            if self._acked is None:
                self._pending, self._pending_seq = snap, self.seq
                self.stats["wire_full"] += 1
                return {
                    "v": SUMMARY_WIRE_VERSION, "seq": self.seq,
                    "block_chars": self.block_chars,
                    "full": [[fp, d, t] for fp, (d, t) in snap.items()],
                }
            if self.seq == self._acked_seq:
                self._pending, self._pending_seq = snap, self.seq
                return None
            add = [
                [fp, d, t] for fp, (d, t) in snap.items()
                if self._acked.get(fp) != (d, t)
            ]
            dels = [fp for fp in self._acked if fp not in snap]
            if not add and not dels:
                # recency-only churn (note() refreshed LRU order but no
                # entry changed): the server's view is already identical —
                # adopt the seq locally instead of shipping an empty delta
                # (which would cost an ingest + summary DB write per
                # heartbeat, fleet-wide, forever in steady state)
                self._acked, self._acked_seq = snap, self.seq
                self._pending = None
                return None
            self._pending, self._pending_seq = snap, self.seq
            self.stats["wire_delta"] += 1
            return {
                "v": SUMMARY_WIRE_VERSION, "seq": self.seq,
                "base_seq": self._acked_seq,
                "block_chars": self.block_chars,
                "add": add, "del": dels,
            }

    def ack(self) -> None:
        """The heartbeat that carried the last :meth:`wire` payload landed
        (and the server did not ask for a resync): commit the pending
        snapshot as the server's known state."""
        with self._lock:
            if self._pending is not None:
                self._acked = self._pending
                self._acked_seq = self._pending_seq
                self._pending = None

    def resync(self) -> None:
        """Forget what the server knows — the next :meth:`wire` sends a
        full snapshot. Called when a heartbeat fails or the server
        answers ``prefix_summary_resync``."""
        with self._lock:
            self._acked = None
            self._pending = None
            self.stats["resyncs"] += 1

    def snapshot(self) -> Dict[str, Tuple[int, str]]:
        with self._lock:
            return dict(self._entries)


def summary_age_s(updated_at: Optional[float],
                  now: Optional[float] = None) -> float:
    if not updated_at:
        return float("inf")
    return max(0.0, (time.time() if now is None else now) - float(updated_at))
