"""Real Redis L3 KV tier: a first-party RESP2 client over raw sockets.

The reference ships an actual Redis tier with async writeback + TTL
(``worker/distributed/kv_cache.py:477-520``); round 1 left an in-process TTL
dict behind the :class:`runtime.kv_cache.RemoteKVStore` protocol (VERDICT r1
missing #2). This module closes that gap without a ``redis`` pip dependency
(not in the image): the RESP2 wire protocol is ~60 lines.

Design:

- **Protocol**: implements the same ``get(key) -> bytes | None`` /
  ``put(key, bytes)`` surface the spill chain consumes
  (``kv_cache.PagedKVCacheManager._probe_spill`` / ``store_spilled``), so it
  drops into ``EngineConfig.spill_remote_store``.
- **Async writeback**: ``put`` enqueues to a bounded queue drained by a
  daemon writer thread issuing ``SET key val PX ttl`` — the serving path
  never blocks on the network (reference ``_async_redis_set`` semantics).
  A full queue drops the oldest pending write: L3 is a cache, losing a
  spill is a future miss, not an error.
- **Fail-open**: connection errors make ``get`` return None (miss) and
  ``put`` a no-op while a reconnect backs off in the writer thread. The
  serving path must never fail because the cache tier is down.
- **TTL** rides the Redis server (PX), so entries expire even if this
  process dies — warm state across worker restarts (reference kv_cache.py
  TTL 3600 s).

``remote_store_from_url`` maps config strings to stores:
``redis://host:port/db`` → :class:`RedisKVStore`, ``memory://`` → the
in-process TTL dict (tests, single-node).
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import List, Optional, Tuple
from urllib.parse import urlparse

from distributed_gpu_inference_tpu.testing import faults as _faults


class RESPError(Exception):
    """Server-reported RESP error reply."""


def _encode_command(*args: bytes) -> bytes:
    """RESP2 array-of-bulk-strings command frame."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        out.append(b"$%d\r\n%s\r\n" % (len(a), a))
    return b"".join(out)


class _Conn:
    """One blocking RESP connection with buffered reads."""

    def __init__(self, host: str, port: int, db: int, password: Optional[str],
                 timeout_s: float) -> None:
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.sock.settimeout(timeout_s)
        self._buf = b""
        if password:
            self.command(b"AUTH", password.encode())
        if db:
            self.command(b"SELECT", str(db).encode())

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":                      # simple string
            return rest
        if kind == b"-":                      # error
            raise RESPError(rest.decode(errors="replace"))
        if kind == b":":                      # integer
            return int(rest)
        if kind == b"$":                      # bulk string
            n = int(rest)
            if n == -1:
                return None
            data = self._read_exact(n)
            self._read_exact(2)               # trailing \r\n
            return data
        if kind == b"*":                      # array
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RESPError(f"unknown RESP type byte {kind!r}")

    def command(self, *args: bytes):
        self.sock.sendall(_encode_command(*args))
        return self._read_reply()


class RedisKVStore:
    """L3 spill tier backed by a real Redis server (RESP2 over sockets).

    Implements the :class:`runtime.kv_cache.RemoteKVStore` protocol:
    ``get``/``put`` of opaque serialized page frames.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        db: int = 0,
        password: Optional[str] = None,
        ttl_s: float = 3600.0,
        key_prefix: str = "dgi:kv:",
        timeout_s: float = 2.0,
        probe_timeout_s: float = 0.25,
        writeback_queue: int = 256,
        reconnect_backoff_s: float = 5.0,
        conn_factory=None,           # tests inject a fake-connection factory
    ) -> None:
        self.ttl_s = ttl_s
        self.key_prefix = key_prefix
        self._timeout_s = timeout_s
        # reads sit on the engine admission path, serialized under _lock: a
        # slow-but-responsive server must not stall admissions for the full
        # connect timeout per probe, so GETs run under this much tighter
        # deadline and a breach trips the same _down_until backoff a
        # connection failure does (latency fail-open, ADVICE r2 medium)
        self.probe_timeout_s = probe_timeout_s
        self._factory = conn_factory or (
            lambda: _Conn(host, port, db, password, timeout_s)
        )
        self._backoff = reconnect_backoff_s
        self._lock = threading.Lock()          # serializes the read conn
        self._conn: Optional[_Conn] = None
        self._down_until = 0.0
        self.stats = {"gets": 0, "hits": 0, "puts": 0, "dropped": 0,
                      "errors": 0, "slow_trips": 0}
        # async writeback: bounded queue + daemon writer (its own conn);
        # (key, None) is a delete tombstone (quarantine of a corrupt entry)
        self._q: "queue.Queue[Tuple[str, Optional[bytes]]]" = queue.Queue(
            maxsize=writeback_queue
        )
        self._stop = threading.Event()
        self._inflight = 0                     # dequeued, not yet durable
        self._wconn: Optional[_Conn] = None    # the writer's connection
        self._writer = threading.Thread(
            target=self._writeback_loop, name="redis-kv-writeback", daemon=True
        )
        self._writer.start()

    # ------------------------------------------------------------ plumbing

    def _get_conn(self) -> Optional[_Conn]:
        # backoff window suppresses probes even while a connection is live —
        # the slow-trip path (get() below) backs off WITHOUT dropping the
        # socket, so this check must come first
        if time.monotonic() < self._down_until:
            return None
        if self._conn is not None:
            return self._conn
        try:
            self._conn = self._factory()
        except (OSError, ConnectionError, RESPError):
            # RESPError covers AUTH/SELECT rejections at connect: a wrong
            # password must degrade to misses, not break the serving path
            self._down_until = time.monotonic() + self._backoff
            self.stats["errors"] += 1
            return None
        return self._conn

    def _drop_conn(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        self._down_until = time.monotonic() + self._backoff
        self.stats["errors"] += 1

    def _key(self, key: str) -> bytes:
        return (self.key_prefix + key).encode()

    # ------------------------------------------------------------ protocol

    def get(self, key: str) -> Optional[bytes]:
        """Synchronous read (the spill probe is on the admission path and a
        hit saves a whole prefill chunk); fail-open to a miss — on
        connection errors AND on latency: the probe runs under
        ``probe_timeout_s`` (much tighter than the connect timeout), and a
        deadline breach or a slow-but-successful reply trips the same
        ``_down_until`` backoff, so a degraded server costs at most one slow
        probe per backoff window instead of one per admission."""
        self.stats["gets"] += 1
        with self._lock:
            conn = self._get_conn()
            if conn is None:
                return None
            t0 = time.monotonic()
            try:
                # chaos seam INSIDE the guarded block: an injected io_error /
                # io_slow rides the exact fail-open path a real outage takes
                _faults.io_fault("io.spill.redis.get", key=key)
                conn.sock.settimeout(self.probe_timeout_s)
                data = conn.command(b"GET", self._key(key))
            except socket.timeout:
                self.stats["slow_trips"] += 1
                self._drop_conn()
                return None
            except (OSError, ConnectionError, RESPError):
                self._drop_conn()
                return None
            finally:
                if self._conn is not None:
                    try:
                        self._conn.sock.settimeout(self._timeout_s)
                    except OSError:
                        pass
            # a large payload can exceed the per-recv deadline in aggregate:
            # keep the hit, but stop probing for a backoff window
            if time.monotonic() - t0 > self.probe_timeout_s:
                self.stats["slow_trips"] += 1
                self._down_until = time.monotonic() + self._backoff
        if data is not None:
            self.stats["hits"] += 1
        return data

    def put(self, key: str, data: bytes) -> None:
        """Asynchronous writeback: enqueue and return; a full queue drops
        the OLDEST pending write (newest pages are the likeliest reuse)."""
        self.stats["puts"] += 1
        while True:
            try:
                self._q.put_nowait((key, data))
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                    self.stats["dropped"] += 1
                except queue.Empty:
                    pass

    def delete(self, key: str) -> None:
        """Best-effort async delete (quarantine of a corrupt/poisoned
        entry): rides the writeback queue as a ``(key, None)`` tombstone so
        it serializes after any pending put of the same key."""
        while True:
            try:
                self._q.put_nowait((key, None))
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                    self.stats["dropped"] += 1
                except queue.Empty:
                    pass

    # ------------------------------------------------------------ writer

    def _writeback_loop(self) -> None:
        px = str(int(self.ttl_s * 1000)).encode()
        while not self._stop.is_set():
            try:
                key, data = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            # the dequeued item is invisible to the queue but not yet
            # durable — flush() must count it until the SET/DEL lands,
            # or a stuck writer reads as drained
            self._inflight = 1
            self._write_one(key, data, px)
            self._inflight = 0

    def _write_one(self, key: str, data: Optional[bytes],
                   px: bytes) -> None:
        conn = self._wconn
        while not self._stop.is_set():
                if conn is None:
                    try:
                        conn = self._factory()
                    except (OSError, ConnectionError, RESPError):
                        self.stats["errors"] += 1
                        if self._stop.wait(self._backoff):
                            return
                        continue
                try:
                    _faults.io_fault("io.spill.redis.put", key=key)
                    if data is None:
                        conn.command(b"DEL", self._key(key))
                    else:
                        conn.command(b"SET", self._key(key), data, b"PX", px)
                    self._wconn = conn
                    return
                except (OSError, ConnectionError, RESPError):
                    # server-side rejections (MISCONF/OOM/READONLY) must
                    # back off like connect failures — a tight
                    # reconnect+SET spin would peg a core and hammer redis
                    self.stats["errors"] += 1
                    conn.close()
                    conn = None
                    self._wconn = None
                    if self._stop.wait(self._backoff):
                        return

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Drain pending writebacks (tests, graceful shutdown). Counts the
        dequeued-but-not-yet-durable item too: a writer stuck in its
        reconnect loop reports False at the deadline instead of reading
        as drained."""
        deadline = time.monotonic() + timeout_s
        while not self._q.empty() or self._inflight:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.01)
        return True

    def close(self) -> None:
        self._stop.set()
        self._writer.join(timeout=2.0)
        if self._wconn is not None:
            self._wconn.close()
            self._wconn = None
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None

    def ping(self) -> bool:
        with self._lock:
            conn = self._get_conn()
            if conn is None:
                return False
            try:
                return conn.command(b"PING") == b"PONG"
            except (OSError, ConnectionError, RESPError):
                self._drop_conn()
                return False


def remote_store_from_url(url: Optional[str], ttl_s: float = 3600.0):
    """Config-string → L3 store. ``redis://[:password@]host[:port][/db]`` →
    :class:`RedisKVStore`; ``memory://`` → in-process TTL dict; None/"" →
    no L3 tier."""
    if not url:
        return None
    parsed = urlparse(url)
    if parsed.scheme == "memory":
        from distributed_gpu_inference_tpu.runtime.kv_cache import (
            RemoteKVStore,
        )

        return RemoteKVStore(ttl_s=ttl_s)
    if parsed.scheme != "redis":
        raise ValueError(f"unsupported KV remote url scheme: {url!r}")
    db = 0
    if parsed.path and parsed.path.strip("/"):
        db = int(parsed.path.strip("/"))
    return RedisKVStore(
        host=parsed.hostname or "127.0.0.1",
        port=parsed.port or 6379,
        db=db,
        password=parsed.password,
        ttl_s=ttl_s,
    )
