"""Continuous batching scheduler driving the slot-based jitted engine.

Capability parity with the reference's ``worker/batch_processor.py``
(``ContinuousBatcher.submit``:130 future-based API, priority heap, full-batch
OR max-wait trigger :177-182, prefix-grouped batch selection :267-300, stats
:359, ``AdaptiveBatcher`` latency-targeted tuning :413-431) — re-designed for
TPU serving:

- The reference batches *whole requests* into one engine call per batch; here
  requests are admitted into fixed engine **slots** and every decode step runs
  one compiled graph over all slots (true continuous batching — a request
  joins/leaves the batch between steps, nothing waits for stragglers).
- Prefix grouping doesn't reorder a Python batch; it orders *admission* so
  sequences sharing cached prefix blocks land while those pages are hot.
- The adaptive knob is the **multi-step scan horizon** (device steps per host
  round-trip): deep horizon = throughput, shallow = admission latency. The
  reference tunes batch size ±20% against a latency target; we tune the
  horizon by the same rule.

Engine calls execute on a single dedicated thread (the engine is not
thread-safe); the asyncio side only schedules and resolves futures.
"""

from __future__ import annotations

import asyncio
import functools
import heapq
import itertools
import logging
import threading
import time
from concurrent.futures import Future as _Future
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from distributed_gpu_inference_tpu.runtime.engine import (
    ChunkedAdmission,
    PreemptedSequence,
    TPUEngine,
)
from distributed_gpu_inference_tpu.runtime.kv_cache import OutOfBlocksError
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    InferenceResponse,
    compute_prefix_hash,
)
from distributed_gpu_inference_tpu.utils.data_structures import KV_BLOCK_TOKENS

log = logging.getLogger(__name__)


class RequestMigrated(Exception):
    """A submitted request was frozen at a step boundary by its *interrupt*
    event (graceful drain): the generation did not fail — it carries a
    portable :class:`PreemptedSequence` the caller hands to the control
    plane so another worker resumes it. The serving layer translates this
    into the worker-level ``JobMigrated``."""

    def __init__(self, pre: PreemptedSequence) -> None:
        super().__init__(
            f"request migrated with {len(pre.generated)} generated tokens"
        )
        self.pre = pre


def synthesize_checkpoint(request: InferenceRequest) -> PreemptedSequence:
    """A zero-token checkpoint for a request the engine never admitted
    (interrupted while still queued, or the admission-time stream record).
    The slot key mirrors ``TPUEngine._bind_slot``'s derivation for seeded
    requests so a resume elsewhere stays seed-stable; unseeded sampling was
    never deterministic, so the (0, 0) fallback loses nothing."""
    seed = request.sampling.seed
    key = (
        ((int(seed) >> 32) & 0xFFFFFFFF, int(seed) & 0xFFFFFFFF)
        if seed is not None else (0, 0)
    )
    return PreemptedSequence(
        request=request,
        prompt_len=len(request.prompt_token_ids or []),
        generated=[],
        slot_key=key,
        start_time=request.arrival_time,
        first_token_time=None,
        cached_tokens=0,
    )


@dataclass
class BatcherConfig:
    max_wait_ms: float = 5.0          # admission latch (reference max_wait)
    multi_step: int = 8               # initial decode horizon
    min_multi_step: int = 1
    max_multi_step: int = 64
    adaptive: bool = True
    target_step_latency_ms: float = 100.0  # per host round-trip
    queue_limit: int = 1024
    default_timeout_s: float = 300.0
    # KV-pressure preemption policy: a request preempted more than this
    # many times errors with a distinct ``preempted_too_often`` reason
    # instead of thrashing the pool forever (pool genuinely too small for
    # the working set). Victims are picked (lowest priority first, then
    # most-recently-admitted — LIFO) and requeued at the FRONT of the heap
    # with their full generated context, so resume restores spilled/cached
    # pages instead of recomputing.
    max_preemptions: int = 3
    # horizon when admission work is waiting: bounded so a queued request
    # never waits more than this many decode steps for a slot, while still
    # amortizing host round-trips (decode_step per token would pay one RTT
    # per token on a tunneled TPU)
    busy_multi_step: int = 4
    # adaptive speculation (VERDICT r3 #7): when a SpeculativeDecoder is
    # attached and the ENTIRE waiting load is <= this many greedy requests,
    # they decode through the spec tree — the low-depth regime where
    # drafting wins; deeper load decodes vanilla (batched weight streaming
    # already amortizes better there). 0 = never.
    spec_max_batch: int = 2
    # a wave may START while up to this many paged slots are still active
    # (spec dispatches and paged rounds interleave in the serving loop, so
    # a busy slot only bounds, not blocks, the other path). 0 = round-4
    # behavior: require a fully idle engine — which made routing STICKY at
    # steady low rates (the first paged request kept the engine active
    # when each next one arrived, so no wave ever started again).
    spec_max_active: int = 2
    # RAGGED rounds (round 6, the default): admission appends prefill-chunk
    # rows to the next engine round instead of scheduling competing prefill
    # dispatches — the round loop collapses to build-ragged-batch →
    # dispatch → commit, and the subwave/interleave admission-stall knobs
    # are obsolete. None = auto (ragged whenever the engine supports it:
    # every paged engine including spec-integrated since round 8 — their
    # rounds carry verify rows; only seq-sharded pools keep the split
    # paths). False forces the legacy wave/chunk-interleaved admission —
    # kept for A/B benchmarking (worker_serving --compare-legacy), not
    # production.
    ragged: Optional[bool] = None
    # per-ROUND prefill token budget for ragged rounds (PR 17, long-context
    # serving): the total prefill-chunk tokens all in-flight admissions may
    # land in one ragged round, split fairly across them (water-fill, with
    # a rotating start so sub-token shares starve nobody). Bounds the
    # matmul work a giant admission adds to each co-dispatched decode
    # round, so decode ITL for short requests stays flat while a 32k
    # prompt streams in over many rounds. 0 = unbudgeted (pre-PR-17
    # behavior: every admission gets a full ``ragged_chunk`` slice per
    # round — byte-identical outputs either way; the budget only shapes
    # WHEN prefill work lands). Live-pushable (serving.prefill_budget).
    prefill_budget: int = 0
    # hopeless-work abandonment (gray-failure round): when ON, the serving
    # loop drops work whose deadline has ALREADY passed and whose projected
    # remaining decode (tokens_left × observed ITL) still cannot land
    # within ``deadline_grace_s`` — resolving the future with a typed
    # ``deadline_abandoned`` error and freeing the blocks at the next step
    # boundary, so a degraded worker stops burning rounds on answers nobody
    # will read. NEVER fires for deadline-less requests (deadline_s=None);
    # OFF (the default) leaves every request byte-identical to the
    # pre-round scheduler.
    abandon_deadlines: bool = False
    deadline_grace_s: float = 0.5
    # predictive abandonment (round 20): when ON (requires
    # abandon_deadlines too), the projection fires BEFORE the deadline
    # passes — a job whose remaining decode (tokens_left × observed ITL)
    # already overruns deadline + grace stops burning ragged-round slots
    # now instead of limping to the deadline first. Same typed
    # ``deadline_abandoned`` error, counted separately
    # (stats["abandoned_predictive"]); OFF keeps the reactive-only round-15
    # behavior byte-identical.
    predictive_abandon: bool = False

    @property
    def horizon_levels(self) -> Tuple[int, ...]:
        """The ONLY decode horizons the batcher may request. decode_multi
        compiles one scan per distinct T — an unquantized adaptive horizon
        triggers an XLA compile mid-serving for nearly every retune. Powers
        of four between the min/max bound the graph count at 4."""
        levels = [t for t in (1, 4, 16, 64)
                  if self.min_multi_step <= t <= self.max_multi_step]
        return tuple(levels) or (self.min_multi_step,)


def split_prefill_budget(needs: List[int], budget: int,
                         start: int = 0) -> List[int]:
    """Fair water-fill of a per-round prefill token ``budget`` across
    concurrent admissions. ``needs[i]`` is admission i's remaining demand
    this round (min of its unprefilled tokens and the chunk cap); returns
    per-admission grants summing to <= budget.

    Water-fill: every still-hungry admission repeatedly receives an equal
    share of what is left, so small admissions finish inside their share
    and release the remainder to large ones — a 32k prompt co-admitted
    with a 40-token prompt cannot crowd it out, and N giant prompts split
    the budget evenly instead of first-come-takes-all. When the budget is
    smaller than the admission count the integer share floors to zero;
    the minimum 1-token share plus the rotating ``start`` offset hands
    the scarce tokens to a DIFFERENT admission subset each round
    (starvation-free round-robin). Deterministic: same inputs, same
    grants."""
    n = len(needs)
    grants = [0] * n
    if n == 0 or budget <= 0:
        return grants
    remaining = budget
    order = [(start + k) % n for k in range(n)]
    while remaining > 0:
        hungry = [i for i in order if grants[i] < needs[i]]
        if not hungry:
            break
        share = max(1, remaining // len(hungry))
        for i in hungry:
            if remaining <= 0:
                break
            give = min(share, needs[i] - grants[i], remaining)
            grants[i] += give
            remaining -= give
    return grants


@dataclass(order=True)
class _QueueItem:
    # (-priority, deadline_at, arrival_time, seq): EDF *within* a priority
    # band (round 12) — deadline_at is +inf for deadline-less requests, so
    # with no deadlines set every comparison falls through to the
    # arrival/seq components and admission order is byte-identical to the
    # pre-deadline batcher
    sort_key: Tuple[int, float, float, int]
    request: InferenceRequest = field(compare=False)
    future: "asyncio.Future[InferenceResponse]" = field(compare=False)
    enqueued_at: float = field(compare=False, default_factory=time.time)
    # KV-pressure state: a preempted request waits in the heap carrying its
    # frozen sequence; _admit resumes it instead of submitting fresh
    preempted: Optional[PreemptedSequence] = field(compare=False, default=None)
    preempt_count: int = field(compare=False, default=0)
    # consecutive resume failures seen while the engine held NOTHING else:
    # an idle pool that cannot re-admit the sequence never will
    idle_resume_oob: int = field(compare=False, default=0)
    # serving hooks (all optional): ``observer(tokens)`` is called on the
    # event-loop thread after every decode round the sequence survived with
    # the monotonic generated-token list (SSE streaming reads deltas off
    # it); ``cancel`` aborts at the next step boundary (client gone);
    # ``interrupt`` freezes into a checkpoint and fails the future with
    # :class:`RequestMigrated` (graceful drain)
    observer: Optional[Callable[[List[int]], None]] = \
        field(compare=False, default=None)
    cancel: Optional[Any] = field(compare=False, default=None)
    interrupt: Optional[Any] = field(compare=False, default=None)
    # flight recorder (round 14): the request's Timeline, when it carries
    # a trace_id — queue wait, admission, chunk rounds, first token,
    # preempt/resume, and completion are noted at their step boundaries.
    # None for untraced requests: the recorder-off path costs one None
    # check per boundary, nothing per token.
    flight: Optional[Any] = field(compare=False, default=None)


class ContinuousBatcher:
    """Admission queue + decode loop over a :class:`TPUEngine`."""

    def __init__(self, engine: TPUEngine, cfg: Optional[BatcherConfig] = None,
                 spec: Optional[Any] = None) -> None:
        """``spec``: a ``runtime.speculative.SpeculativeDecoder`` sharing the
        engine's target weights (its own KV pool). When set, low-depth
        all-greedy load routes through the incremental spec-wave API
        (one bounded fused dispatch per loop iteration, interleaved with
        paged decode rounds — never a blocking whole-generation call)."""
        self.engine = engine
        self.cfg = cfg or BatcherConfig()
        self.spec = spec
        if spec is not None and \
                getattr(engine.cfg, "speculative", None) is not None:
            raise ValueError(
                "engine already speculates in-engine "
                "(EngineConfig.speculative); attaching a standalone "
                "SpeculativeDecoder would draft twice — pick one"
            )
        self._check_ragged_supported(self.cfg.ragged)
        # (wave, items) while a speculative wave is in flight
        self._spec_wave: Optional[Tuple[Any, List["_QueueItem"]]] = None
        # True while start_wave runs on the executor: the requests are off
        # the heap but the wave isn't registered yet — drain must wait
        self._spec_starting = False
        self._heap: List[_QueueItem] = []
        self._seq = itertools.count()
        self._wake = asyncio.Event()
        self._stopping = False
        self._run_task: Optional[asyncio.Task] = None
        self._exec = ThreadPoolExecutor(max_workers=1, thread_name_prefix="engine")
        self._levels: Tuple[int, ...] = ()
        self._level = 0
        self._horizon = 0.0
        self._rebuild_levels(float(self.cfg.multi_step))
        self._slot_items: Dict[int, _QueueItem] = {}
        # admission stamps for LIFO victim selection (slot indices recycle,
        # so recency must be tracked per admission, not per slot number)
        self._admit_stamp: Dict[int, int] = {}
        self._stamp = itertools.count()
        # after a preemption, resumes pause until one round runs
        # unpressured: the FROZEN slots must reserve the freed blocks
        # first, or the resume takes them straight back and the pressure
        # recurs every round until the victim dies preempted_too_often
        self._resume_hold = False
        # legacy path only: at most one chunk-interleaved long-prompt
        # admission in flight; its prefill advances one chunk per loop
        # iteration, between decode rounds (VERDICT r1 next-step #4)
        self._chunked: Optional[Tuple[ChunkedAdmission, _QueueItem]] = None
        # ragged mode (the default): EVERY admission — short or long — is a
        # bound-but-unprefilled engine slot whose chunk rows ride the next
        # ragged round(s) co-dispatched with the active decodes. Several
        # may be in flight at once; an admission leaves this list for
        # _slot_items when its final chunk samples the first token.
        self._ragged: List[Tuple[ChunkedAdmission, _QueueItem]] = []
        # rotating start offset for the per-round prefill-budget split:
        # when the budget floors below one token per admission, a
        # different admission subset receives the scarce tokens each
        # round (split_prefill_budget's starvation-freedom)
        self._prefill_rr = 0
        self.stats: Dict[str, Any] = {
            "submitted": 0, "completed": 0, "rejected": 0, "timeouts": 0,
            "decode_rounds": 0, "admitted": 0, "queue_peak": 0,
            "step_latency_ema_ms": 0.0, "occupancy_sum": 0, "horizon": self._horizon,
            "chunked_admissions": 0, "batched_waves": 0,
            "ragged_admissions": 0, "ragged_rounds": 0,
            "budgeted_rounds": 0, "budget_skipped_admissions": 0,
            "spec_waves": 0, "spec_completed": 0, "spec_errors": 0,
            "preemptions": 0, "resumes": 0, "preemption_block_pressure": 0,
            "preempted_too_often": 0,
            "cancelled": 0, "migrated": 0, "adopted": 0,
            "abandoned": 0, "abandoned_predictive": 0,
        }

    @property
    def use_ragged(self) -> bool:
        """Ragged rounds are the DEFAULT serving path: admission appends
        rows to the next round instead of dispatching competing prefills.
        ``cfg.ragged=False`` forces the legacy path (A/B benches);
        ``cfg.ragged=True`` REQUIRES it (init/reconfigure reject engines
        that cannot serve it — a silent legacy fallback would make every
        A/B ratio downstream a lie); ``None`` = auto: engines without
        ragged support (seq-sharded, fakes) fall back automatically.
        Spec-integrated engines serve ragged since round 8 — a round with
        admissions in flight dispatches verify rows + chunk rows in one
        invocation."""
        if self.cfg.ragged is False:
            return False
        return bool(getattr(self.engine, "supports_ragged", False))

    def _check_ragged_supported(self, requested: Any) -> None:
        """``ragged=True`` is REQUIRE, not prefer — reject it loudly on an
        engine that keeps the split admission paths. Spec-integrated
        engines are an explicit ACCEPT since round 8 (their ragged rounds
        carry verify rows); only seq-sharded pools remain fenced."""
        if requested is True and \
                not getattr(self.engine, "supports_ragged", False):
            raise ValueError(
                "serving.ragged=true requires an engine with ragged-round "
                "support (paged engines, spec-integrated included since "
                "round 8); kv_seq_sharded engines keep the split "
                "admission paths — their decode rows read through a "
                "dedicated shard_map op with no ragged variant. Use "
                "ragged=null (auto) to fall back silently"
            )

    def _rebuild_levels(self, anchor: float) -> None:
        """THE quantized-horizon level-set derivation (init + live
        reconfigure): adaptive mode exposes the power-of-4 levels, fixed
        mode honors the clamped ``multi_step`` verbatim; the current level
        snaps to the one nearest ``anchor`` so a retune never requests an
        uncompiled scan length mid-flight."""
        if self.cfg.adaptive:
            levels = self.cfg.horizon_levels
        else:
            # a fixed horizon compiles exactly one graph — honor it verbatim
            levels = (max(self.cfg.min_multi_step,
                          min(self.cfg.multi_step,
                              self.cfg.max_multi_step)),)
        self._levels = levels
        self._level = min(
            range(len(levels)), key=lambda i: abs(levels[i] - anchor)
        )
        self._horizon = float(levels[self._level])
        if hasattr(self, "stats"):
            self.stats["horizon"] = self._horizon

    # ---------------------------------------------------- speculative routing

    def _spec_eligible(self, item: "_QueueItem") -> bool:
        """A request may decode through the spec tree iff it is greedy
        (verify is an argmax match), its prompt fits one spec prefill
        bucket, the generation fits the spec pool, and it did not opt out
        (``request.params['speculative'] = False``)."""
        r = item.request
        ids = r.prompt_token_ids or []
        if not ids or r.sampling.temperature > 0.0:
            return False
        if r.params.get("speculative") is False:
            return False
        if item.observer is not None or item.cancel is not None \
                or item.interrupt is not None:
            # serving hooks need round-granular slot access (streaming
            # deltas, step-boundary abort/migrate) — a whole-wave spec
            # dispatch offers none of that
            return False
        s = self.spec
        max_bucket = s.prefill_buckets[-1]
        eng_buckets = getattr(self.engine.cfg, "prefill_buckets", None)
        if eng_buckets:
            # prompts beyond the PAGED engine's largest bucket take the
            # chunk-interleaved admission; spec routing honors the same
            # boundary so the long-prompt path is one contract across
            # serving modes (the worker's legacy driver gated on it too)
            max_bucket = min(max_bucket, eng_buckets[-1])
        if len(ids) > max_bucket:
            return False
        # headroom must cover the WORST verify tree (incl. adaptive depth
        # growth): the spec fits-freeze ends a row early at
        # prefix + nodes + 1 > ctx, which would return fewer tokens than
        # the paged engine serves for the same request
        margin = s.worst_case_tree_nodes() + 1
        return len(ids) + r.sampling.max_new_tokens + margin <= s.max_seq_len

    async def _maybe_start_spec_wave(self) -> bool:
        """Route the ENTIRE waiting queue through the spec decoder when it
        is a low-depth all-greedy moment: queue depth <= spec_max_batch,
        every request eligible, at most spec_max_active paged slots still
        decoding (waves and paged rounds interleave in the serving loop),
        no wave in flight. Mixed/deep load never waits on drafting."""
        spec_cap = (
            min(self.cfg.spec_max_batch, self.spec.max_batch_size)
            if self.spec is not None else 0
        )
        if (
            self.spec is None
            or spec_cap <= 0
            or self._spec_wave is not None
            or self._chunked is not None
            or self._ragged
            or not self._heap
            or len(self._heap) > spec_cap
            or self.engine.num_active > self.cfg.spec_max_active
        ):
            return False
        items = [it for it in list(self._heap) if not it.future.cancelled()]
        if not items or not all(self._spec_eligible(it) for it in items):
            return False
        if any(it.preempted is not None for it in items):
            # a preempted sequence must RESUME (restoring its generated
            # context, TTFT origin, and warm pages) — a spec wave would
            # silently regenerate it from token 0
            return False
        loop = asyncio.get_running_loop()
        self._heap.clear()
        self._spec_starting = True
        try:
            wave = await loop.run_in_executor(
                self._exec, self.spec.start_wave,
                [it.request for it in items],
            )
        except Exception:
            # fall back to the paged engine, which can serve these requests
            # (a transient spec failure must not error a servable request);
            # mark them so a persistent spec fault can't retry-loop
            for it in items:
                it.request.params["speculative"] = False
                heapq.heappush(self._heap, it)
            return False
        finally:
            self._spec_starting = False
        self._spec_wave = (wave, items)
        self.stats["spec_waves"] += 1
        self.stats["admitted"] += len(items)
        return True

    async def _step_spec_wave(self) -> None:
        """Advance the in-flight spec wave by ONE fused dispatch; finish and
        resolve futures when every row is done (or a caller gave up)."""
        if self._spec_wave is None:
            return
        wave, items = self._spec_wave
        loop = asyncio.get_running_loop()
        if all(it.future.done() for it in items):
            self._spec_wave = None          # every caller timed out/cancelled
            await loop.run_in_executor(self._exec, self.spec.abort_wave, wave)
            return
        try:
            done = await loop.run_in_executor(
                self._exec, self.spec.advance_wave, wave
            )
        except Exception as e:
            self._spec_wave = None
            await loop.run_in_executor(self._exec, self.spec.abort_wave, wave)
            for it in items:
                if not it.future.done():
                    it.future.set_result(InferenceResponse(
                        request_id=it.request.request_id,
                        error=f"speculative engine error: {e}",
                    ))
                    self.stats["completed"] += 1
                    self.stats["spec_errors"] += 1
            return
        if done:
            self._spec_wave = None
            resps = await loop.run_in_executor(
                self._exec, self.spec.finish_wave, wave
            )
            # completed counts responses actually DELIVERED — a row whose
            # caller already timed out was counted by submit()'s timeout
            # path, not here, so stats stay reconcilable per-request
            for it, resp in zip(items, resps):
                if not it.future.done():
                    it.future.set_result(resp)
                    self.stats["completed"] += 1
                    self.stats["spec_completed"] += 1

    # ---------------------------------------------------------------- API

    @staticmethod
    def _note(item: "_QueueItem", name: str, at: Optional[float] = None,
              **attrs: Any) -> None:
        """Flight-recorder boundary note for one request: a None check
        when untraced, a list append when traced. ``at`` records an
        engine-observed wall-clock instant (e.g. the slot's first-token
        time) instead of "now"."""
        f = item.flight
        if f is None:
            return
        if at is not None:
            f.note_at(name, at, **attrs)
        else:
            f.note(name, **attrs)

    async def submit(
        self, request: InferenceRequest, timeout_s: Optional[float] = None,
        *,
        observer: Optional[Callable[[List[int]], None]] = None,
        cancel: Optional[Any] = None,
        interrupt: Optional[Any] = None,
        resume_from: Optional[PreemptedSequence] = None,
        flight: Optional[Any] = None,
    ) -> InferenceResponse:
        """Enqueue and await completion (reference submit:130 semantics:
        future resolves with the response; queue-full and timeout surface as
        errors in the response).

        Serving hooks: ``observer`` receives the monotonic generated-token
        list after every decode round (SSE streaming); ``cancel`` (an
        Event) aborts at the next step boundary; ``interrupt`` (an Event)
        freezes the sequence into a checkpoint and raises
        :class:`RequestMigrated` here instead of resolving (graceful
        drain). ``resume_from`` re-admits a server-held checkpoint instead
        of prefilling from scratch — head-of-line, through the same
        cache/spill-restoring resume path KV-pressure preemptions use."""
        if self._stopping:
            raise RuntimeError("batcher is stopping")
        if len(self._heap) >= self.cfg.queue_limit:
            self.stats["rejected"] += 1
            return InferenceResponse(
                request_id=request.request_id, error="queue full",
                # machine-readable: nothing ran — an overload shed, safe
                # to retry elsewhere (vs request_timeout, which may still
                # be generating here)
                error_code="shed_overload",
            )
        if resume_from is None and not self.engine.request_fits_pool(request):
            # the PROMPT alone cannot fit even an idle pool: no amount of
            # preemption could ever admit it — reject up front. (The check
            # is deliberately not worst-case on max_new_tokens; generation
            # that outgrows the pool is handled dynamically by preemption,
            # bounded by max_preemptions and the idle-resume abort.
            # Checkpoint resumes skip it: they were admitted once and the
            # preempted_too_often cap owns their capacity endgame.)
            self.stats["rejected"] += 1
            return InferenceResponse(
                request_id=request.request_id,
                error="request exceeds KV pool capacity (worst case "
                      "cannot fit even an idle pool)",
                error_code="over_capacity",
            )
        loop = asyncio.get_running_loop()
        fut: "asyncio.Future[InferenceResponse]" = loop.create_future()
        item = _QueueItem(
            sort_key=(-request.priority, request.deadline_at,
                      request.arrival_time, next(self._seq)),
            request=request,
            future=fut,
            observer=observer,
            cancel=cancel,
            interrupt=interrupt,
            preempted=resume_from,
            flight=flight,
        )
        self._note(item, "batcher.enqueued",
                   queue_depth=len(self._heap))
        heapq.heappush(self._heap, item)
        self.stats["submitted"] += 1
        self.stats["queue_peak"] = max(self.stats["queue_peak"], len(self._heap))
        self._wake.set()
        timeout_s = timeout_s or self.cfg.default_timeout_s
        try:
            return await asyncio.wait_for(fut, timeout=timeout_s)
        except asyncio.TimeoutError:
            self.stats["timeouts"] += 1
            return InferenceResponse(
                request_id=request.request_id,
                error=f"timeout after {timeout_s}s",
                # distinct from shed_overload: the caller's wait budget
                # elapsed — the request was (or may still be) running
                error_code="request_timeout",
            )

    async def adopt_slot(self, slot: int,
                         request: Optional[InferenceRequest] = None,
                         flight: Optional[Any] = None
                         ) -> InferenceResponse:
        """Drive an ALREADY-ADMITTED engine slot (PD decode stage: the
        sequence arrived through a KV handoff, not through submit) inside
        the shared decode rounds, and await its completion. The slot joins
        the batch exactly like a submitted request — it can be preempted,
        resumed, and counted — so PD decode no longer monopolizes the
        engine for its whole generation."""
        if self._stopping:
            # same race submit() guards: a stop() between the caller's
            # serving.active check and this coroutine running would leave
            # the item in _slot_items with no run task to ever resolve it
            raise RuntimeError("batcher is stopping")
        s = self.engine.slots[slot]
        if s is None:
            raise ValueError(f"slot {slot} is empty")
        self.stats["adopted"] += 1
        if s.finish_reason is not None:
            # the sequence already finished (it decoded alongside earlier
            # batcher rounds while awaiting adoption): resolve immediately
            loop = asyncio.get_running_loop()
            resp = await loop.run_in_executor(
                self._exec, self.engine.finish_slot, slot
            )
            if flight is not None:
                flight.note("batcher.adopted", slot=slot)
                flight.note("batcher.completed",
                            finish_reason=resp.finish_reason,
                            tokens=resp.completion_tokens)
            return resp
        req = request or s.request
        loop = asyncio.get_running_loop()
        fut: "asyncio.Future[InferenceResponse]" = loop.create_future()
        item = _QueueItem(
            sort_key=(-req.priority, req.deadline_at, req.arrival_time,
                      next(self._seq)),
            request=req,
            future=fut,
            flight=flight,
        )
        self._note(item, "batcher.adopted", slot=slot)
        self._slot_items[slot] = item
        self._admit_stamp[slot] = next(self._stamp)
        self._wake.set()
        return await fut

    def start(self) -> None:
        if self._run_task is None:
            self._stopping = False
            self._run_task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self, drain: bool = True) -> None:
        """Graceful shutdown: optionally finish queued + active work first
        (reference worker drain semantics, main.py:444). Without drain,
        every still-pending future resolves with an error response so no
        caller is left waiting out its timeout against a dead loop."""
        self._stopping = True
        self._wake.set()
        if drain:
            # drain batcher-OWNED work only: a foreign engine slot (e.g. a
            # PD sequence retained between stages) is not ours to wait on
            while self._heap or self._slot_items or self._chunked is not None \
                    or self._ragged \
                    or self._spec_wave is not None or self._spec_starting:
                await asyncio.sleep(0.01)
        if self._run_task:
            self._run_task.cancel()
            try:
                await self._run_task
            except asyncio.CancelledError:
                pass
            self._run_task = None
        pending = list(self._slot_items.values()) + list(self._heap)
        self._slot_items.clear()
        self._heap.clear()
        loop = asyncio.get_running_loop()
        if self._chunked is not None:
            # a request mid chunk-interleaved prefill is in NEITHER
            # collection above — abort its engine state and resolve it,
            # or its submit() would wait on a dead loop forever
            adm, chunk_item = self._chunked
            self._chunked = None
            try:
                await loop.run_in_executor(
                    self._exec, self.engine.abort_chunked, adm
                )
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                pass
            pending.append(chunk_item)
        for adm, rag_item in self._ragged:
            # mid-prefill ragged admissions are in NEITHER collection above
            # either — abort their engine state and resolve them too
            try:
                await loop.run_in_executor(
                    self._exec, self.engine.abort_chunked, adm
                )
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                pass
            pending.append(rag_item)
        self._ragged = []
        if self._spec_wave is not None:
            wave, items = self._spec_wave
            self._spec_wave = None
            try:
                await loop.run_in_executor(
                    self._exec, self.spec.abort_wave, wave
                )
            except Exception:  # noqa: BLE001
                pass
            pending.extend(items)
        for item in pending:
            if item.future.done():
                continue
            item.future.set_result(InferenceResponse(
                request_id=item.request.request_id,
                error="batcher stopped",
            ))
            self.stats["completed"] += 1
        self._exec.shutdown(wait=False)

    def reconfigure(self, **updates: Any) -> None:
        """Apply server-pushed SLO knobs to a LIVE batcher between rounds:
        any :class:`BatcherConfig` field by name (None values are ignored).
        Horizon-shaping fields (``max_multi_step``, ``min_multi_step``,
        ``multi_step``, ``adaptive``) rebuild the quantized level set; the
        current level snaps to the nearest surviving horizon so retuning
        never requests an uncompiled scan length mid-flight.

        ``ragged_chunk`` is the one ENGINE knob accepted here (PR 17):
        the per-admission chunk-row width of ragged rounds. It is read
        per round, never compile-baked — chunk widths bucket through
        ``prefill_buckets``, so retuning it live only selects among
        already-compiled graph widths. Together with ``prefill_budget``
        it makes the long-context prefill geometry live-pushable."""
        ragged_chunk = updates.pop("ragged_chunk", None)
        if ragged_chunk is not None:
            rc = int(ragged_chunk)
            if rc < 1:
                raise ValueError(
                    f"ragged_chunk must be >= 1, got {ragged_chunk}"
                )
        coerced: Dict[str, Any] = {}
        for key, val in updates.items():
            if val is None or not hasattr(self.cfg, key):
                continue
            cur = getattr(self.cfg, key)
            if (isinstance(cur, bool) or key == "ragged") \
                    and isinstance(val, str):
                # remote pushes arrive through an untyped dict and env/YAML
                # tooling stringifies scalars — bool("false") is True, so
                # coerce by content, not constructor ("ragged" is tri-state
                # Optional[bool], so its current value may be None)
                val = val.strip().lower() in ("1", "true", "yes", "on")
            if key == "ragged":
                self._check_ragged_supported(bool(val))
                coerced[key] = bool(val)
                continue
            coerced[key] = type(cur)(val) if cur is not None else val
        # all-or-nothing: coercion above raised before any cfg mutation,
        # so one bad value can't leave a half-applied retune
        for key, val in coerced.items():
            setattr(self.cfg, key, val)
        if ragged_chunk is not None and \
                hasattr(self.engine.cfg, "ragged_chunk"):
            self.engine.cfg.ragged_chunk = rc
        self._rebuild_levels(self._horizon)

    # ------------------------------------------------------------- internals

    def _admission_order(self) -> List[_QueueItem]:
        """Prefix-grouped admission (reference :267-300): group queued
        requests by their first-block prefix hash; largest group first, then
        priority/FIFO inside the group. Preempted sequences ALWAYS lead:
        their pages are still warm in the prefix cache / spill tiers, and
        head-of-line resume is what bounds a preempted request's extra
        latency to one pressure episode."""
        resumes = sorted(
            (it for it in self._heap if it.preempted is not None),
            key=lambda it: it.sort_key,
        )
        groups: Dict[str, List[_QueueItem]] = {}
        for item in self._heap:
            if item.preempted is not None:
                continue
            ids = item.request.prompt_token_ids or []
            key = (
                compute_prefix_hash(ids, KV_BLOCK_TOKENS)
                if len(ids) >= KV_BLOCK_TOKENS
                else f"solo-{id(item)}"
            )
            groups.setdefault(key, []).append(item)
        ordered: List[_QueueItem] = []
        # largest group first; equal-size groups ordered by their best member
        # (priority, then FIFO) so priority still wins between singletons
        for _, members in sorted(
            groups.items(),
            key=lambda kv: (-len(kv[1]), min(it.sort_key for it in kv[1])),
        ):
            ordered.extend(sorted(members, key=lambda it: it.sort_key))
        return resumes + ordered

    async def _admit(self) -> int:
        """Admit queued requests into free slots. Heap mutation and future
        resolution happen HERE on the event-loop thread (asyncio futures and
        the heap are not thread-safe); only the engine call itself runs on the
        engine executor thread.

        Ragged mode (the default): every fresh admission binds its slot NOW
        (``engine.submit_chunked_start``) and its prompt rides the next
        ragged round(s) as chunk rows co-dispatched with the active decodes
        — admission IS "append rows to the next round"; several may be in
        flight at once. Legacy mode (``cfg.ragged=False`` or an engine
        without ragged support): short prompts are collected into a WAVE
        and admitted through ``engine.submit_batch`` — one batched prefill
        device call per bucket instead of one per request (VERDICT r1
        next-step #3) — while prompts longer than the largest prefill
        bucket start a chunk-interleaved admission instead (one at a time);
        their chunks run between decode rounds in ``_run``."""
        admitted = 0
        if self._resume_hold:
            # the round after a preemption belongs to the FROZEN slots:
            # neither resumes nor fresh admissions may take the freed
            # blocks before they re-reserve, or the pressure recurs every
            # round (thrash) no matter who stole them
            return 0
        free = self.engine.free_slots()
        if not free or not self._heap:
            return 0
        loop = asyncio.get_running_loop()
        max_bucket = self.engine.cfg.prefill_buckets[-1]
        wave: List[_QueueItem] = []
        requeue: List[_QueueItem] = []

        def _defer(item: "_QueueItem") -> bool:
            """Requeue an item the pool could not hold RIGHT NOW — unless
            its worst case statically can never fit the pool, in which
            case it errors out (the one capacity error that legitimately
            reaches a client). A PREEMPTED sequence is never statically
            rejected: it was admitted once and carries generated tokens —
            requeue it and let the preempted_too_often cap (which returns
            the partial output) decide if the pool can't sustain it.
            Returns True when the item was deferred."""
            if item.preempted is None and \
                    not self.engine.request_fits_pool(item.request):
                if not item.future.done():
                    item.future.set_result(InferenceResponse(
                        request_id=item.request.request_id,
                        error="request exceeds KV pool capacity (worst "
                              "case cannot fit even an idle pool)",
                    ))
                    # same counter as the submit()-time static rejection:
                    # one condition, one metric, wherever it is detected
                    self.stats["rejected"] += 1
                return False
            requeue.append(item)
            return True

        for item in self._admission_order():
            if not free:
                break
            # remove from the queue before any await so a concurrent submit()
            # (which only pushes) can never interleave with a removal
            try:
                self._heap.remove(item)
            except ValueError:
                continue  # already handled
            if item.future.cancelled():
                continue
            if item.preempted is not None:
                # resume a preempted sequence: head-of-line, restores
                # cached/spilled pages through the normal allocate+prefill
                # path. Pool still too tight → stop admitting ANYTHING this
                # pass (new work must not steal the blocks the resume
                # needs) and retry next loop.
                try:
                    slot = await loop.run_in_executor(
                        self._exec, self.engine.resume, item.preempted,
                    )
                except OutOfBlocksError:
                    if self.engine.num_active == 0 and \
                            self._chunked is None:
                        # an IDLE pool that STATICALLY cannot hold the
                        # sequence never will (nothing left to free):
                        # after a few consecutive tries, deliver the
                        # partial output instead of spinning until the
                        # client's timeout. A statically-fitting resume
                        # keeps retrying — an idle-pool allocation failure
                        # is then transient by construction (cache
                        # eviction in flight, injected chaos pressure),
                        # and aborting would turn a 2-second storm into a
                        # permanently failed request (fleet chaos suite).
                        item.idle_resume_oob += 1
                        if item.idle_resume_oob > 2 and not \
                                self.engine.resume_fits_pool(
                                    item.preempted):
                            pre = item.preempted
                            if not item.future.done():
                                item.future.set_result(InferenceResponse(
                                    request_id=item.request.request_id,
                                    token_ids=list(pre.generated),
                                    finish_reason="abort",
                                    prompt_tokens=pre.prompt_len,
                                    completion_tokens=len(pre.generated),
                                    error="request exceeds KV pool "
                                          "capacity: generated context "
                                          f"({len(pre.generated)} tokens) "
                                          "can no longer be resumed",
                                ))
                                self.stats["completed"] += 1
                            continue
                    else:
                        item.idle_resume_oob = 0
                    if _defer(item):
                        break
                    continue
                except Exception as e:
                    if not item.future.done():
                        item.future.set_result(InferenceResponse(
                            request_id=item.request.request_id,
                            error=f"resume failed: {e}",
                        ))
                        self.stats["completed"] += 1
                    continue
                item.preempted = None
                item.idle_resume_oob = 0
                if slot in free:
                    free.remove(slot)
                self._slot_items[slot] = item
                self._admit_stamp[slot] = next(self._stamp)
                self.stats["resumes"] += 1
                self._note(item, "batcher.resumed", slot=slot)
                admitted += 1
                continue
            if self.use_ragged:
                # ragged admission (the default): bind the slot NOW, run no
                # prefill — the prompt's chunk rows ride the next ragged
                # round(s) co-dispatched with the active decodes, so there
                # is no competing prefill dispatch and no short/long split
                try:
                    adm = await loop.run_in_executor(
                        self._exec, self.engine.submit_chunked_start,
                        item.request,
                    )
                except OutOfBlocksError:
                    _defer(item)
                    continue
                except Exception as e:
                    if not item.future.done():
                        item.future.set_result(
                            InferenceResponse(
                                request_id=item.request.request_id,
                                error=str(e),
                                # typed admission failures (e.g. the
                                # engine's over_length rejection) stay
                                # machine-readable through the batcher
                                error_code=getattr(e, "error_code", None),
                            )
                        )
                    continue
                free.pop(0)
                self._ragged.append((adm, item))
                self.stats["ragged_admissions"] += 1
                self._note(item, "batcher.admitted", slot=adm.slot,
                           mode="ragged",
                           tokens=len(item.request.prompt_token_ids or []))
                continue
            n_prompt = len(item.request.prompt_token_ids or [])
            if n_prompt > max_bucket:
                if self._chunked is not None:
                    # one interleaved admission at a time — requeue this one
                    # and keep admitting the rest (a second long prompt must
                    # not starve short requests behind it)
                    heapq.heappush(self._heap, item)
                    continue
                try:
                    adm = await loop.run_in_executor(
                        self._exec, self.engine.submit_chunked_start,
                        item.request,
                    )
                except OutOfBlocksError:
                    _defer(item)
                    continue
                except Exception as e:
                    if not item.future.done():
                        item.future.set_result(
                            InferenceResponse(
                                request_id=item.request.request_id,
                                error=str(e),
                                error_code=getattr(e, "error_code", None),
                            )
                        )
                    continue
                # consume the slot only on SUCCESS: a failed chunked start
                # rolled the engine back, and burning a free slot for it
                # would under-admit the rest of this pass (the slot leak)
                free.pop(0)
                self._chunked = (adm, item)
                self.stats["chunked_admissions"] += 1
                self._note(item, "batcher.admitted", slot=adm.slot,
                           mode="chunked", tokens=n_prompt)
                continue
            free.pop(0)
            wave.append(item)

        if wave:
            # admission instant for the whole wave: submit_batch prefills
            # AND samples the first token before returning, so noting
            # "admitted" after it would land LATER than first_token and
            # phase derivation would drop prefill and inflate queue_wait
            t_admit = time.time()
            try:
                slots = await loop.run_in_executor(
                    self._exec,
                    functools.partial(
                        self.engine.submit_batch,
                        [it.request for it in wave], partial=True,
                    ),
                )
            except OutOfBlocksError:
                # pool can't hold the wave right now: requeue silently —
                # completions/preemptions free blocks and the requests
                # retry; clients never see the pressure
                for item in wave:
                    _defer(item)
                slots = None
            except Exception:
                # the wave is all-or-nothing (engine rolls back); isolate the
                # failing request(s) by falling back to per-request admission
                slots = None
                for item in wave:
                    t_admit = time.time()
                    try:
                        slot = await loop.run_in_executor(
                            self._exec, self.engine.submit, item.request
                        )
                    except OutOfBlocksError:
                        _defer(item)
                        continue
                    except Exception as e:
                        if not item.future.done():
                            item.future.set_result(
                                InferenceResponse(
                                    request_id=item.request.request_id,
                                    error=str(e),
                                    error_code=getattr(e, "error_code",
                                                       None),
                                )
                            )
                        continue
                    self._slot_items[slot] = item
                    self._admit_stamp[slot] = next(self._stamp)
                    self._note(item, "batcher.admitted", at=t_admit,
                               slot=slot, mode="wave",
                               tokens=len(item.request.prompt_token_ids
                                          or []))
                    self._note_first_token(item, slot)
                    admitted += 1
            if slots is not None:
                if slots:
                    self.stats["batched_waves"] += 1
                for item, slot in zip(wave, slots):
                    self._slot_items[slot] = item
                    self._admit_stamp[slot] = next(self._stamp)
                    self._note(item, "batcher.admitted", at=t_admit,
                               slot=slot, mode="wave",
                               tokens=len(item.request.prompt_token_ids
                                          or []))
                    self._note_first_token(item, slot)
                admitted += len(slots)
                # pressure deferred the wave's tail (possibly the whole
                # wave): requeue without error
                for item in wave[len(slots):]:
                    _defer(item)

        for item in requeue:
            heapq.heappush(self._heap, item)
        if self._heap:
            heapq.heapify(self._heap)
        self.stats["admitted"] += admitted
        return admitted

    def _note_first_token(self, item: "_QueueItem", slot: int) -> None:
        """Note the first-token boundary at the ENGINE's wall-clock stamp
        (``SequenceSlot.first_token_time`` — the instant the token was
        sampled) rather than the loop's observation time, so ttft on the
        timeline matches the engine's own ttft_ms."""
        if item.flight is None:
            return
        s = self.engine.slots[slot]
        t = getattr(s, "first_token_time", None) if s is not None else None
        self._note(item, "batcher.first_token", at=t)

    async def _step_chunked(self) -> None:
        """Advance the in-flight chunk-interleaved admission by ONE chunk."""
        if self._chunked is None:
            return
        adm, item = self._chunked
        loop = asyncio.get_running_loop()
        if item.future.done():  # caller gave up (timeout/cancel): release
            await loop.run_in_executor(
                self._exec, self.engine.abort_chunked, adm
            )
            self._chunked = None
            return
        try:
            done = await loop.run_in_executor(
                self._exec, self.engine.submit_chunked_step, adm
            )
        except Exception as e:
            self._chunked = None
            if not item.future.done():
                item.future.set_result(
                    InferenceResponse(
                        request_id=item.request.request_id,
                        error=str(e),
                        error_code=getattr(e, "error_code", None),
                    )
                )
            return
        if done:
            self._slot_items[adm.slot] = item
            self._chunked = None
            self.stats["admitted"] += 1
            self._note_first_token(item, adm.slot)

    async def _check_pressure(self, after_round: bool = False) -> None:
        """Consume the engine's KV-pressure signal and apply the preemption
        policy. Decode-sourced pressure (active slots frozen, progress
        blocked) always preempts a victim; admission-sourced pressure
        preempts only when the waiting work outranks the victim — otherwise
        the deferred admissions simply wait for completions."""
        p = self.engine.take_pressure()
        if p is None:
            if after_round:
                # one full engine round ran unpressured: the frozen slots
                # got their reservations, resumes may flow again
                self._resume_hold = False
            return
        self.stats["preemption_block_pressure"] += 1
        if p.source == "decode":
            # skip if every frozen slot resolved meanwhile (finished this
            # very round and its blocks are already back)
            still_frozen = any(
                (s := self.engine.slots[sl]) is not None
                and s.finish_reason is None
                for sl in p.slots
            )
            if still_frozen:
                await self._preempt_victim(mandatory=True)
        else:
            await self._preempt_victim(mandatory=False)

    async def _preempt_victim(self, mandatory: bool) -> None:
        """Pick and preempt one victim: lowest priority first, then (round
        12, deadline-aware) the slot with the MOST deadline slack —
        deadline-less sequences before late-deadline ones before
        tight-deadline ones — ties broken most-recently-admitted (LIFO —
        the youngest sequence has the least compute invested and the
        warmest prefix to resume from; with no deadlines set the policy is
        byte-identical to the pre-deadline batcher). The frozen sequence
        requeues at the FRONT of the heap; past ``max_preemptions`` the
        request errors with ``preempted_too_often``."""
        cands = []
        for slot, item in self._slot_items.items():
            s = self.engine.slots[slot]
            if s is None or s.finish_reason is not None or s.prefilling:
                continue
            cands.append((item.request.priority,
                          -item.request.deadline_at,
                          -self._admit_stamp.get(slot, -1), slot, item))
        if not cands:
            return
        prio, _, _, slot, item = min(cands)
        if not mandatory:
            # admission pressure: only preempt for strictly higher-priority
            # waiting work — FIFO fairness is not worth a spill round-trip
            waiting = max(
                (it.request.priority for it in self._heap
                 if not it.future.done()),
                default=None,
            )
            if waiting is None or waiting <= prio:
                return
        loop = asyncio.get_running_loop()
        try:
            pre = await loop.run_in_executor(
                self._exec, self.engine.preempt_slot, slot
            )
        except Exception:
            return      # slot finished/changed under us: nothing to preempt
        self._slot_items.pop(slot, None)
        self.stats["preemptions"] += 1
        item.preempt_count += 1
        pre.preempt_count = item.preempt_count
        self._note(item, "batcher.preempted", slot=slot,
                   generated=len(pre.generated))
        if item.preempt_count > self.cfg.max_preemptions:
            self.stats["preempted_too_often"] += 1
            if not item.future.done():
                item.future.set_result(InferenceResponse(
                    request_id=item.request.request_id,
                    token_ids=list(pre.generated),
                    finish_reason="abort",
                    prompt_tokens=pre.prompt_len,
                    completion_tokens=len(pre.generated),
                    error=f"preempted_too_often: evicted "
                          f"{item.preempt_count} times under KV pressure",
                ))
                self.stats["completed"] += 1
            return
        item.preempted = pre
        # resort to the FRONT of the heap: resumes outrank every waiting
        # admission (their pages are warm; head-of-line bounds added
        # latency) — but pause resumes until one round runs unpressured,
        # so the frozen slots reserve the freed blocks first
        self._resume_hold = True
        item.sort_key = (
            -(1 << 20) - item.request.priority,
            item.request.deadline_at,
            item.request.arrival_time,
            next(self._seq),
        )
        heapq.heappush(self._heap, item)

    def _abort_slot(self, slot: int) -> Optional[InferenceResponse]:
        """Runs on the engine executor: mark a live slot aborted and finish
        it (partial tokens included). None when the slot vanished."""
        s = self.engine.slots[slot]
        if s is None:
            return None
        s.finish_reason = s.finish_reason or "abort"
        return self.engine.finish_slot(slot)

    async def _scan_signals(self) -> None:
        """Honor per-request cancel/interrupt events at the loop boundary —
        the only place slot state is quiescent. Cancels resolve with the
        partial output (finish_reason="abort"); interrupts freeze into a
        checkpoint and fail the future with :class:`RequestMigrated` so the
        serving layer migrates the job without burning a retry."""
        loop = asyncio.get_running_loop()
        changed = False
        for item in list(self._heap):
            if item.future.done():
                continue
            if item.cancel is not None and item.cancel.is_set():
                self._heap.remove(item)
                changed = True
                pre = item.preempted
                item.future.set_result(InferenceResponse(
                    request_id=item.request.request_id,
                    token_ids=list(pre.generated) if pre else [],
                    finish_reason="abort",
                    prompt_tokens=pre.prompt_len if pre
                    else len(item.request.prompt_token_ids or []),
                    completion_tokens=len(pre.generated) if pre else 0,
                ))
                self.stats["completed"] += 1
                self.stats["cancelled"] += 1
            elif item.interrupt is not None and item.interrupt.is_set():
                self._heap.remove(item)
                changed = True
                pre = item.preempted or synthesize_checkpoint(item.request)
                pre.preempt_count = item.preempt_count
                item.future.set_exception(RequestMigrated(pre))
                self.stats["migrated"] += 1
        if changed:
            heapq.heapify(self._heap)
        if self._chunked is not None:
            adm, item = self._chunked
            cancelled = item.cancel is not None and item.cancel.is_set()
            interrupted = item.interrupt is not None \
                and item.interrupt.is_set()
            if cancelled or interrupted:
                # a request mid chunk-interleaved prefill holds no
                # resumable engine state yet: abort the admission (frees
                # its slot + staged blocks) and either resolve with an
                # empty abort or migrate with a synthesized zero-token
                # checkpoint — burning the remaining prefill rounds on an
                # abandoned/draining request would stall everyone else
                self._chunked = None
                try:
                    await loop.run_in_executor(
                        self._exec, self.engine.abort_chunked, adm
                    )
                except Exception:  # noqa: BLE001 — abort is best-effort
                    pass
                if not item.future.done():
                    if cancelled:
                        item.future.set_result(InferenceResponse(
                            request_id=item.request.request_id,
                            finish_reason="abort",
                            prompt_tokens=len(
                                item.request.prompt_token_ids or []),
                        ))
                        self.stats["completed"] += 1
                        self.stats["cancelled"] += 1
                    else:
                        pre = synthesize_checkpoint(item.request)
                        pre.preempt_count = item.preempt_count
                        item.future.set_exception(RequestMigrated(pre))
                        self.stats["migrated"] += 1
        for adm, item in list(self._ragged):
            cancelled = item.cancel is not None and item.cancel.is_set()
            interrupted = item.interrupt is not None \
                and item.interrupt.is_set()
            if not (cancelled or interrupted or item.future.done()):
                continue
            # same contract as the legacy chunk-interleaved admission: a
            # request mid ragged prefill holds no resumable engine state
            # yet — abort (frees the slot + staged blocks) and resolve /
            # migrate with a synthesized zero-token checkpoint; a done
            # future (caller timeout) just releases the engine side
            self._ragged.remove((adm, item))
            try:
                await loop.run_in_executor(
                    self._exec, self.engine.abort_chunked, adm
                )
            except Exception:  # noqa: BLE001 — abort is best-effort
                pass
            if item.future.done():
                continue
            if cancelled:
                item.future.set_result(InferenceResponse(
                    request_id=item.request.request_id,
                    finish_reason="abort",
                    prompt_tokens=len(item.request.prompt_token_ids or []),
                ))
                self.stats["completed"] += 1
                self.stats["cancelled"] += 1
            else:
                pre = synthesize_checkpoint(item.request)
                pre.preempt_count = item.preempt_count
                item.future.set_exception(RequestMigrated(pre))
                self.stats["migrated"] += 1
        for slot, item in list(self._slot_items.items()):
            s = self.engine.slots[slot]
            if s is None or s.finish_reason is not None:
                continue  # the round loop resolves finished slots
            if item.cancel is not None and item.cancel.is_set():
                try:
                    resp = await loop.run_in_executor(
                        self._exec, self._abort_slot, slot
                    )
                except Exception:
                    continue
                self._slot_items.pop(slot, None)
                if resp is not None and not item.future.done():
                    item.future.set_result(resp)
                    self.stats["completed"] += 1
                    self.stats["cancelled"] += 1
            elif item.interrupt is not None and item.interrupt.is_set() \
                    and not s.prefilling:
                try:
                    pre = await loop.run_in_executor(
                        self._exec, self.engine.preempt_slot, slot
                    )
                except Exception:
                    continue  # finished/changed under us — next pass
                self._slot_items.pop(slot, None)
                pre.preempt_count = item.preempt_count
                if not item.future.done():
                    item.future.set_exception(RequestMigrated(pre))
                    self.stats["migrated"] += 1

    def _deadline_hopeless(self, request: InferenceRequest,
                           tokens_left: int, now: float) -> bool:
        """True when ``request`` missed its deadline AND its projected
        remaining decode (``tokens_left`` × observed ITL) cannot land even
        within the grace window — the typed-abandonment trigger. Guarded
        three ways: the feature flag, an explicit ``deadline_s is None``
        check (deadline-less requests must NEVER abandon — asserted by
        tests, not merely implied by the +inf deadline_at), and
        ``tokens_left > 0`` (a sequence about to finish frees nothing by
        aborting)."""
        if not self.cfg.abandon_deadlines:
            return False
        if request.deadline_s is None:
            return False
        if tokens_left <= 0:
            return False
        deadline_at = request.deadline_at
        if now <= deadline_at and not self.cfg.predictive_abandon:
            # reactive mode waits for the deadline to actually pass;
            # predictive mode (round 20) lets the ITL projection below
            # fire EARLY — the projection test is identical either way,
            # so a request reactive mode would carry to its deadline and
            # then drop is dropped now, before burning the rounds
            return False
        # observed inter-token latency; floor at 1ms so a cold EMA (no
        # rounds yet) still projects SOME forward progress instead of 0
        itl_s = max(float(self.stats["step_latency_ema_ms"]), 1.0) / 1000.0
        return now + tokens_left * itl_s > \
            deadline_at + self.cfg.deadline_grace_s

    def _count_abandon(self, request: InferenceRequest, now: float) -> None:
        """Bump the abandonment counters: every abandonment lands in
        ``abandoned``; one that fired BEFORE the deadline passed (only
        possible with ``predictive_abandon``) also lands in
        ``abandoned_predictive`` — the A/B-visible split."""
        self.stats["completed"] += 1
        self.stats["abandoned"] += 1
        if now <= request.deadline_at:
            self.stats["abandoned_predictive"] += 1

    def _abandon_response(self, request: InferenceRequest,
                          token_ids: List[int],
                          prompt_tokens: int) -> InferenceResponse:
        return InferenceResponse(
            request_id=request.request_id,
            token_ids=list(token_ids),
            finish_reason="abort",
            prompt_tokens=prompt_tokens,
            completion_tokens=len(token_ids),
            error=f"deadline exceeded by {self.cfg.deadline_grace_s:.1f}s "
                  "grace and projected remaining decode cannot land",
            # machine-readable: the WORK was dropped (vs request_timeout,
            # where only the caller's wait budget elapsed and the request
            # may still be generating). Callers must not silently retry a
            # deadline-abandoned request — its deadline already passed.
            error_code="deadline_abandoned",
        )

    async def _scan_deadlines(self) -> None:
        """Abandon hopeless deadline-carrying work at the step boundary —
        queued items resolve immediately; mid-prefill admissions abort
        their staged blocks; active slots free their KV at this quiescent
        point via the same abort path cancels use. No-op (not even a
        clock read) unless ``cfg.abandon_deadlines`` is on."""
        if not self.cfg.abandon_deadlines:
            return
        loop = asyncio.get_running_loop()
        now = time.time()
        changed = False
        for item in list(self._heap):
            if item.future.done():
                continue
            req = item.request
            pre = item.preempted
            tokens_left = max(0, int(req.sampling.max_new_tokens)
                              - (len(pre.generated) if pre else 0))
            if not self._deadline_hopeless(req, tokens_left, now):
                continue
            self._heap.remove(item)
            changed = True
            item.future.set_result(self._abandon_response(
                req, list(pre.generated) if pre else [],
                pre.prompt_len if pre
                else len(req.prompt_token_ids or []),
            ))
            self._count_abandon(req, now)
        if changed:
            heapq.heapify(self._heap)
        if self._chunked is not None:
            adm, item = self._chunked
            if not item.future.done() and self._deadline_hopeless(
                    item.request,
                    int(item.request.sampling.max_new_tokens), now):
                self._chunked = None
                try:
                    await loop.run_in_executor(
                        self._exec, self.engine.abort_chunked, adm
                    )
                except Exception:  # noqa: BLE001 — abort is best-effort
                    pass
                if not item.future.done():
                    item.future.set_result(self._abandon_response(
                        item.request, [],
                        len(item.request.prompt_token_ids or []),
                    ))
                    self._count_abandon(item.request, now)
        for adm, item in list(self._ragged):
            if item.future.done() or not self._deadline_hopeless(
                    item.request,
                    int(item.request.sampling.max_new_tokens), now):
                continue
            self._ragged.remove((adm, item))
            try:
                await loop.run_in_executor(
                    self._exec, self.engine.abort_chunked, adm
                )
            except Exception:  # noqa: BLE001 — abort is best-effort
                pass
            if not item.future.done():
                item.future.set_result(self._abandon_response(
                    item.request, [],
                    len(item.request.prompt_token_ids or []),
                ))
                self._count_abandon(item.request, now)
        for slot, item in list(self._slot_items.items()):
            s = self.engine.slots[slot]
            if s is None or s.finish_reason is not None:
                continue  # the round loop resolves finished slots
            req = item.request
            tokens_left = max(
                0, int(req.sampling.max_new_tokens) - len(s.generated))
            if not self._deadline_hopeless(req, tokens_left, now):
                continue
            try:
                resp = await loop.run_in_executor(
                    self._exec, self._abort_slot, slot
                )
            except Exception:
                continue  # finished/changed under us — next pass
            self._slot_items.pop(slot, None)
            if resp is not None and not item.future.done():
                item.future.set_result(self._abandon_response(
                    req, list(resp.token_ids), resp.prompt_tokens))
                self._count_abandon(req, now)

    def _notify_observers(self) -> None:
        """Push per-round progress to streaming observers (loop thread;
        observers must only enqueue). Finished slots are excluded — their
        full token list rides the resolving response."""
        for slot, item in list(self._slot_items.items()):
            if item.observer is None:
                continue
            s = self.engine.slots[slot]
            if s is None or s.finish_reason is not None:
                continue
            try:
                item.observer(list(s.generated))
            except Exception:  # noqa: BLE001 — an observer must never wedge serving
                pass

    def _prefill_chunk_caps(
        self, adms: List[ChunkedAdmission],
    ) -> Optional[Dict[int, int]]:
        """Per-round prefill-budget split (PR 17): the per-admission token
        caps the next ragged round may land, keyed by slot. None when the
        budget is off (``prefill_budget <= 0``) — the engine then runs its
        pre-budget behavior verbatim (every admission gets a full
        ``ragged_chunk`` slice), so budget-OFF is byte-identical to the
        pre-PR scheduler by construction. Runs on the engine thread just
        before the round (``_engine_round``), so the caps always reflect
        the admissions actually dispatched."""
        budget = int(self.cfg.prefill_budget)
        if budget <= 0 or not adms:
            return None
        eng_cfg = self.engine.cfg
        chunk_cap = min(
            max(int(getattr(eng_cfg, "ragged_chunk", budget)), 1),
            eng_cfg.prefill_buckets[-1],
        )
        # a fully-cached admission (empty ``fresh``) still needs ONE
        # budget token to ride a round and sample its first token — a
        # zero need would grant a zero cap and skip it forever
        needs = [max(1, min(len(adm.fresh), chunk_cap)) for adm in adms]
        grants = split_prefill_budget(needs, budget,
                                      start=self._prefill_rr)
        self._prefill_rr += 1
        if sum(grants) < sum(needs):
            self.stats["budgeted_rounds"] += 1
            self.stats["budget_skipped_admissions"] += sum(
                1 for g in grants if g <= 0
            )
        return {adm.slot: g for adm, g in zip(adms, grants)}

    def _engine_round(self) -> float:
        """One blocking engine round on the worker thread. Returns latency ms.

        Ragged mode with admissions in flight dispatches ONE
        ``engine.ragged_round``: every active decode slot advances one
        token and every admission advances one prefill chunk in the same
        invocation — build-ragged-batch → dispatch → commit, no competing
        prefill dispatch, no subwave/interleave stall shaping. With no
        admission in flight a ragged round degenerates to pure decode, so
        the multi-step scan (horizon amortization of the host RTT) is the
        better dispatch for the identical math and runs instead."""
        t0 = time.perf_counter()
        if self._ragged:
            adms = [adm for adm, _ in self._ragged]
            self.engine.ragged_round(adms, self._prefill_chunk_caps(adms))
            self.stats["ragged_rounds"] += 1
            return (time.perf_counter() - t0) * 1000.0
        steps = self._levels[self._level]
        if self._heap or self._chunked is not None:
            # work is waiting (queued requests or a mid-prefill chunked
            # admission): bounded horizon so admission latency stays low
            # without falling back to one-RTT-per-token stepping; snap
            # to the largest level ≤ the cap, or the smallest level when
            # every level exceeds it (only compiled lengths may run)
            cap = min(steps, self.cfg.busy_multi_step)
            eligible = [t for t in self._levels if t <= cap]
            steps = max(eligible) if eligible else min(self._levels)
        self.engine.decode_multi(steps)
        return (time.perf_counter() - t0) * 1000.0

    def _retune(self, latency_ms: float) -> None:
        """AdaptiveBatcher analogue (reference :413-431): one quantized
        horizon level up/down against the latency target — levels only, so
        the set of compiled decode graphs stays bounded."""
        ema = self.stats["step_latency_ema_ms"]
        ema = latency_ms if ema == 0 else 0.8 * ema + 0.2 * latency_ms
        self.stats["step_latency_ema_ms"] = ema
        if not self.cfg.adaptive:
            return
        if ema > self.cfg.target_step_latency_ms * 1.1:
            self._level = max(0, self._level - 1)
        elif ema < self.cfg.target_step_latency_ms * 0.9:
            self._level = min(len(self._levels) - 1, self._level + 1)
        self._horizon = float(self._levels[self._level])
        self.stats["horizon"] = self._horizon

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        latch_until = 0.0
        while True:
            # idle = no batcher-OWNED work. Deliberately not engine.num_active:
            # a foreign slot (PD sequence retained/adopted between stages,
            # awaiting its decode job) must neither keep this loop spinning
            # nor be decoded/finished behind its owner's back — it joins the
            # batch only through adopt_slot().
            if not self._heap and not self._slot_items \
                    and self._chunked is None and not self._ragged \
                    and self._spec_wave is None:
                self._wake.clear()
                if self._stopping:
                    return
                await self._wake.wait()
                # admission latch: give co-arriving requests a window to form
                # a batch (reference max_wait trigger :177-199)
                latch_until = time.time() + self.cfg.max_wait_ms / 1000.0
            while time.time() < latch_until and \
                    len(self._heap) < len(self.engine.slots):
                await asyncio.sleep(0.001)
            # cancel/interrupt events land at this quiescent boundary:
            # aborted requests release their slots BEFORE admission so the
            # freed capacity admits waiting work this very pass
            await self._scan_signals()
            # hopeless deadline work drops at the same boundary, so its
            # freed blocks admit waiting on-time work this very pass
            await self._scan_deadlines()
            # low-depth all-greedy load routes through the spec tree BEFORE
            # paged admission claims it; requests arriving mid-wave admit to
            # paged slots below and the two interleave round for round
            await self._maybe_start_spec_wave()
            await self._admit()
            # admission-sourced KV pressure: deferred requests wait, or a
            # higher-priority arrival preempts the lowest-priority victim
            await self._check_pressure()
            # one prefill chunk of the in-flight long admission per loop
            # iteration — decode rounds below run between chunks, so active
            # slots stall at most one chunk per round
            await self._step_chunked()
            # one bounded fused dispatch of the in-flight spec wave
            await self._step_spec_wave()
            if not self._slot_items and self._chunked is None \
                    and not self._ragged:
                # no batcher-owned slot decodes: no frozen slot of OURS is
                # waiting on freed blocks, so resumes may flow immediately
                # (foreign slots are left untouched for their owner)
                self._resume_hold = False
                if self._heap:
                    # deferred (pressured) work with an idle engine: yield
                    # briefly instead of hot-spinning the admission loop
                    await asyncio.sleep(0.001)
                continue
            try:
                latency = await loop.run_in_executor(
                    self._exec, self._engine_round
                )
                self.stats["decode_rounds"] += 1
                self.stats["occupancy_sum"] += self.engine.num_active
                self._retune(latency)
                # admission-chunk rounds on the timeline: one bounded note
                # per in-flight traced admission per round (saturates at
                # the per-request event cap on pathological prompts)
                for adm, item in self._ragged:
                    if item.flight is not None:
                        self._note(item, "batcher.chunk_round", off=adm.off)
                # ragged admissions whose final chunk sampled its first
                # token this round join the batch (the finished-slot sweep
                # below then resolves any that immediately hit stop/length)
                for adm, item in [p for p in self._ragged if p[0].done]:
                    self._ragged.remove((adm, item))
                    self._slot_items[adm.slot] = item
                    self._admit_stamp[adm.slot] = next(self._stamp)
                    self.stats["admitted"] += 1
                    self._note_first_token(item, adm.slot)
                for i, s in enumerate(list(self.engine.slots)):
                    if s is not None and s.finish_reason is not None \
                            and i in self._slot_items:
                        # OWNED slots only: a foreign sequence that finished
                        # while sharing our rounds (PD retained/awaiting
                        # adoption) keeps its slot until its owner collects
                        # it — finishing it here would discard the response
                        resp = await loop.run_in_executor(
                            self._exec, self.engine.finish_slot, i
                        )
                        item = self._slot_items.pop(i, None)
                        if item and not item.future.done():
                            self._note(item, "batcher.completed",
                                       finish_reason=resp.finish_reason,
                                       tokens=resp.completion_tokens)
                            item.future.set_result(resp)
                            self.stats["completed"] += 1
                # streaming observers see each surviving slot's monotonic
                # token list once per round (finished slots resolved above)
                self._notify_observers()
                # decode-sourced KV pressure: slots froze this round —
                # preempt the policy victim so the next round progresses
                # (completions above may already have freed blocks; the
                # check skips if every frozen slot resolved). An
                # unpressured round releases the resume hold.
                await self._check_pressure(after_round=True)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # a failed round must not wedge the batcher: fail every
                # in-flight request, abort its slot, keep serving the queue
                self.stats["engine_errors"] = self.stats.get("engine_errors", 0) + 1
                if self._chunked is not None:
                    # the mid-prefill admission isn't in _slot_items yet —
                    # release its slot and resolve its future here or the
                    # caller hangs until timeout
                    adm, chunk_item = self._chunked
                    self._chunked = None
                    try:
                        await loop.run_in_executor(
                            self._exec, self.engine.abort_chunked, adm
                        )
                    except Exception:
                        pass
                    if not chunk_item.future.done():
                        chunk_item.future.set_result(
                            InferenceResponse(
                                request_id=chunk_item.request.request_id,
                                error=f"engine error: {e}",
                            )
                        )
                        self.stats["completed"] += 1
                # mid-prefill ragged admissions likewise aren't in
                # _slot_items yet — release their slots and resolve
                for adm, rag_item in list(self._ragged):
                    try:
                        await loop.run_in_executor(
                            self._exec, self.engine.abort_chunked, adm
                        )
                    except Exception:
                        pass
                    if not rag_item.future.done():
                        rag_item.future.set_result(
                            InferenceResponse(
                                request_id=rag_item.request.request_id,
                                error=f"engine error: {e}",
                            )
                        )
                        self.stats["completed"] += 1
                self._ragged.clear()
                for i in list(self._slot_items):
                    # fail OWNED slots only — a foreign slot's owner handles
                    # its own engine-error cleanup (PD decode already does)
                    if self.engine.slots[i] is not None:
                        try:
                            await loop.run_in_executor(
                                self._exec,
                                lambda i=i: self.engine.finish_slot(
                                    i, cache=False),
                            )
                        except Exception:
                            pass
                    item = self._slot_items.pop(i, None)
                    if item and not item.future.done():
                        item.future.set_result(
                            InferenceResponse(
                                request_id=item.request.request_id,
                                error=f"engine error: {e}",
                            )
                        )
                        self.stats["completed"] += 1

    def get_stats(self) -> Dict[str, Any]:
        out = dict(self.stats)
        out["queue_depth"] = len(self._heap)
        out["active_slots"] = self.engine.num_active
        out["ragged_mode"] = self.use_ragged
        out["ragged_in_flight"] = len(self._ragged)
        out["spec_wave_active"] = self._spec_wave is not None
        if self.spec is not None:
            out["spec"] = self.spec.get_stats()
        if getattr(self.engine.cfg, "speculative", None) is not None:
            # engine-integrated speculation: every decode round commits
            # 1..K+1 tokens per slot, so these are THE serving-efficiency
            # numbers for this batcher (accept-rate, weight-stream
            # amortization factor)
            es = self.engine.get_stats()
            out["spec_integrated"] = {
                "accept_rate": es.get("spec_accept_rate", 0.0),
                "tokens_per_step": es.get("spec_tokens_per_step", 0.0),
                "steps": es.get("spec_steps", 0),
                "accepted": es.get("spec_accepted", 0),
                "drafted": es.get("spec_drafted", 0),
            }
        if out["decode_rounds"]:
            out["avg_occupancy"] = out["occupancy_sum"] / out["decode_rounds"]
        return out


class BatcherServing:
    """Thread-hosted serving front-end over a :class:`ContinuousBatcher`.

    The batcher is asyncio-native; the worker's callers are plain threads
    (the poll loop, the direct server's handlers, PD stages, tests). This
    wrapper owns a dedicated event loop thread running ONE batcher and
    exposes a thread-safe surface:

    - :meth:`submit` — blocking submit from any thread (the batcher's
      serving hooks — observer / cancel / interrupt / resume_from — pass
      through), raising :class:`RequestMigrated` on drain.
    - :meth:`adopt_slot` — drive an externally-admitted engine slot (PD
      decode) inside the shared decode rounds.
    - :meth:`run_exclusive` — run an engine-touching callable on the
      batcher's single engine-executor thread, serialized with decode
      rounds (PD prefill / KV-handoff adoption compose with live serving
      without a second lock hierarchy).
    - :meth:`reconfigure` — apply server-pushed SLO knobs between rounds.
    """

    def __init__(self, engine: TPUEngine,
                 cfg: Optional[BatcherConfig] = None,
                 spec: Optional[Any] = None) -> None:
        self.engine = engine
        self._cfg = cfg
        self._spec = spec
        self.batcher: Optional[ContinuousBatcher] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._ready = threading.Event()
        self._stopped = False
        self._boot_error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="batcher-serving", daemon=True
        )
        self._thread.start()
        if not self._ready.wait(timeout=10.0):
            raise RuntimeError("batcher serving loop failed to start")
        if self._boot_error is not None:
            raise RuntimeError(
                f"batcher serving loop failed: {self._boot_error}"
            )

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def boot() -> None:
            try:
                self.batcher = ContinuousBatcher(
                    self.engine, self._cfg, spec=self._spec
                )
                self.batcher.start()
            except BaseException as exc:  # noqa: BLE001 — surfaced to ctor
                self._boot_error = exc
            finally:
                self._ready.set()

        loop.run_until_complete(boot())
        if self._boot_error is None:
            loop.run_forever()
        loop.close()

    # -- thread-safe surface -------------------------------------------------

    def submit_async(self, request: InferenceRequest,
                     timeout_s: Optional[float] = None,
                     **hooks: Any) -> "_Future[InferenceResponse]":
        assert self.batcher is not None and self._loop is not None
        if self._stopped or not self._thread.is_alive():
            # a coroutine scheduled on a dead loop never runs and its
            # future never resolves — fail fast instead of hanging callers.
            # NOT loop.is_running(): that is False in the window between
            # boot() completing and run_forever() starting, and a coroutine
            # scheduled in that window runs fine once the loop spins up.
            raise RuntimeError("batcher serving is stopped")
        return asyncio.run_coroutine_threadsafe(
            self.batcher.submit(request, timeout_s, **hooks), self._loop
        )

    def submit(self, request: InferenceRequest,
               timeout_s: Optional[float] = None,
               **hooks: Any) -> InferenceResponse:
        return self.submit_async(request, timeout_s, **hooks).result()

    def adopt_slot(self, slot: int,
                   request: Optional[InferenceRequest] = None,
                   flight: Optional[Any] = None) -> InferenceResponse:
        assert self.batcher is not None and self._loop is not None
        return asyncio.run_coroutine_threadsafe(
            self.batcher.adopt_slot(slot, request, flight=flight),
            self._loop
        ).result()

    def run_exclusive(self, fn: Callable[..., Any], *args: Any,
                      **kw: Any) -> Any:
        """Run ``fn`` on the batcher's engine-executor thread. Every engine
        call the batcher makes runs on that SAME single thread, so this is
        the serialization point for out-of-band engine work (PD prefill,
        handoff adoption): no lock ordering, no mid-round interleaving —
        the work simply runs between rounds."""
        assert self.batcher is not None
        return self.batcher._exec.submit(fn, *args, **kw).result()

    def reconfigure(self, **updates: Any) -> None:
        """Thread-safe config push: applied on the loop thread between
        iterations (the batcher reads its cfg only at loop boundaries)."""
        if self._loop is None or self.batcher is None:
            return

        def _apply() -> None:
            try:
                self.batcher.reconfigure(**updates)
            except Exception:  # noqa: BLE001 — an operator push must not
                # die in the event loop's default handler unseen
                log.exception("serving config push rejected: %r", updates)

        self._loop.call_soon_threadsafe(_apply)

    def get_stats(self) -> Dict[str, Any]:
        return self.batcher.get_stats() if self.batcher is not None else {}

    @property
    def active(self) -> bool:
        # explicit lifecycle flag, NOT loop.is_running(): the latter is
        # False between boot() and run_forever(), and a request arriving
        # in that window would silently fall through to the legacy
        # engine-lock path while the batcher thread comes up — two
        # drivers on one engine
        return (
            self.batcher is not None
            and self._loop is not None
            and not self._stopped
            and self._thread.is_alive()
        )

    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        if self._loop is None or self.batcher is None or self._stopped:
            return
        self._stopped = True   # reject new submits before draining old ones
        try:
            asyncio.run_coroutine_threadsafe(
                self.batcher.stop(drain=drain), self._loop
            ).result(timeout=timeout_s)
        except Exception:  # noqa: BLE001 — drain stuck/timed out
            # the loop must NOT die with futures still pending (every
            # thread blocked in submit().result() would hang forever):
            # force a non-drain stop, which resolves all outstanding
            # futures with "batcher stopped" before the loop goes down
            try:
                asyncio.run_coroutine_threadsafe(
                    self.batcher.stop(drain=False), self._loop
                ).result(timeout=5.0)
            except Exception:  # noqa: BLE001 — loop wedged: give up
                pass
        try:
            self._loop.call_soon_threadsafe(self._loop.stop)
        except RuntimeError:   # loop already closed (boot failed earlier)
            pass
        self._thread.join(timeout=5.0)
