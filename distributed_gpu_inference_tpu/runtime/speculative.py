"""Speculative decoding: EAGLE-style feature-level draft head + token-tree
verification, executed as ONE jitted device step (draft → verify → accept →
KV-compact) with no host round-trips inside the step.

Capability parity with the reference's ``worker/engines/speculative.py``
(DraftHead:59 predicting the next hidden from [hidden; tok-emb]:98-125 and
sharing the target's embedding/LM head:94, token tree with ancestor-visibility
attention mask:184-213, longest-accepted-path trace:215-245,
draft→verify→accept loop decode_step:305-365, greedy match acceptance
:445-453, adaptive depth on accept-rate:456-463, MedusaHead:474-513) —
re-designed TPU-first (SURVEY §7 item 5, BASELINE north star: "rewrite the
EAGLE-3 draft/verify loop as a single XLA computation with on-device tree
verification"):

- The reference drafts token-by-token in Python and verifies with a dynamic
  mask built per step; here the tree SHAPE is static (widths per depth), so
  the whole draft+verify+accept step is one compiled graph.
- Tree-node KV lands in the same paged pools the engine serves from, written
  at node-indexed slots; the accepted path is compacted on device (gather →
  scatter of the winning pages), so a speculative step leaves the cache
  exactly as 1+A committed decode steps would have.
- **Greedy-equivalence invariant**: with temperature 0 the emitted stream is
  bit-identical to vanilla greedy decode regardless of draft quality — the
  draft only affects speed. Tests enforce this.
"""

from __future__ import annotations

import functools
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_gpu_inference_tpu.models import llama
from distributed_gpu_inference_tpu.models.configs import ModelConfig, get_model_config
from distributed_gpu_inference_tpu.runtime.kv_cache import PagedKVCacheManager
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    InferenceResponse,
)


# ---------------------------------------------------------------------------
# Static token-tree topology
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TreeTopology:
    """Node 0 is the root (the pending token); ``widths[d]`` children per
    frontier node at depth d+1. Static → the step compiles once per shape."""

    widths: Tuple[int, ...] = (4, 2)

    @functools.cached_property
    def parents(self) -> np.ndarray:
        parents = [-1]
        frontier = [0]
        for w in self.widths:
            nxt: List[int] = []
            for p in frontier:
                for _ in range(w):
                    parents.append(p)
                    nxt.append(len(parents) - 1)
            frontier = nxt
        return np.asarray(parents, np.int32)

    @functools.cached_property
    def depths(self) -> np.ndarray:
        d = np.zeros(len(self.parents), np.int32)
        for i, p in enumerate(self.parents):
            if p >= 0:
                d[i] = d[p] + 1
        return d

    @property
    def num_nodes(self) -> int:
        return len(self.parents)

    @property
    def max_depth(self) -> int:
        return len(self.widths)

    @functools.cached_property
    def ancestor_mask(self) -> np.ndarray:
        """mask[i, j] = node i attends node j (ancestor-or-self)."""
        n = self.num_nodes
        m = np.zeros((n, n), bool)
        for i in range(n):
            cur = i
            while cur >= 0:
                m[i, cur] = True
                cur = int(self.parents[cur])
        return m

    @functools.cached_property
    def level_slices(self) -> List[Tuple[int, int]]:
        """[(start, end)] node-index range per depth level (root excluded)."""
        out = []
        start = 1
        count = 1
        for w in self.widths:
            count *= w
            out.append((start, start + count))
            start += count
        return out


@dataclass
class SpecDecodeConfig:
    """Engine-integrated speculative decoding (``TPUEngine`` decode mode).

    Unlike :class:`SpeculativeConfig` (the standalone tree decoder), this
    drives CHAIN drafts inside the continuous-batching engine: every active
    slot drafts ``num_draft_tokens`` greedily with the EAGLE-style head,
    then ONE multi-query target pass (q_len = K+1 per slot) verifies the
    chain and each slot commits 1..K+1 tokens. Chain positions are
    sequential, so accepted KV is already in place and a rejected suffix is
    simply overwritten by the next step — no tree compaction, and it
    composes with prefix caching, CoW, int8 KV, and sliding windows.
    """

    # K drafted tokens per slot per step; the verify pass scores K+1
    # queries. Since round 6 the verify pass dispatches through the ragged
    # paged-attention kernel (ops.attention.resolve_impl → "ragged"), which
    # stages pages once per query TILE — the old small-q path's q_len <= 8
    # cap (pages re-staged per query) is gone, so K is bounded only by the
    # block-growth checks below.
    num_draft_tokens: int = 4
    # EAGLE-style head weights (init_draft_params layout). None = random
    # init from ``draft_seed`` — near-zero acceptance but still CORRECT
    # (greedy outputs are target-verified regardless of draft quality);
    # distill with ``TPUEngine.distill_draft`` / distill_draft_params.
    draft_params: Optional[Dict[str, jax.Array]] = None
    draft_seed: int = 1
    # acceptance-adaptive draft depth (round 8): a per-slot EMA of the
    # ACCEPTED length selects each slot's draft depth from
    # ``k_choices()`` — a small static set, so every depth runs through
    # the SAME compiled graph (``num_draft_tokens`` stays the drafted
    # width; per-slot depths beyond a slot's selected K are masked, never
    # re-traced). Slots that accept little draft shallow (less wasted
    # verify KV + reservation pressure — sampled slots, which never
    # accept, converge to depth ``adaptive_min_k``); slots on a roll
    # draft deep. The selection is host-side float arithmetic over
    # integer accept counts: same seed → same K schedule, bit-for-bit.
    adaptive: bool = False
    adaptive_min_k: int = 1
    adaptive_ema: float = 0.8            # EMA weight on the PREVIOUS value
    adaptive_k_choices: Optional[Tuple[int, ...]] = None  # None = powers
    #   of two from adaptive_min_k up, plus num_draft_tokens itself
    # ORACLE draft (round 8, VERDICT r5 #3): force the per-round accepted
    # length to ``rate * K`` (fractional rates dither deterministically)
    # instead of matching against the target. Draft cost, verify cost, KV
    # writes, commits, and rollback are all REAL — only the acceptance
    # decision is forced — so the serving bench can measure the
    # tok/s-vs-acceptance curve without trained draft weights
    # (``benchmarks/worker_serving.py --spec``). Committed tokens are the
    # (garbage) drafts: outputs are meaningless, pair with ignore_eos
    # requests. None = real acceptance (the only production value).
    oracle_accept_rate: Optional[float] = None

    def k_choices(self) -> Tuple[int, ...]:
        """The static set adaptive depth selects from (ascending, ending
        at ``num_draft_tokens`` — ``validate`` rejects custom sets whose
        top choice is below K, since the chain always DRAFTS K tokens and
        a lower ceiling would make part of every round structurally
        unacceptable; cap ``num_draft_tokens`` instead)."""
        if self.adaptive_k_choices is not None:
            return tuple(sorted(set(int(c) for c in self.adaptive_k_choices)))
        lo = max(1, int(self.adaptive_min_k))
        out = []
        c = lo
        while c < self.num_draft_tokens:
            out.append(c)
            c *= 2
        out.append(self.num_draft_tokens)
        return tuple(out)

    def validate(self, engine_cfg: Any) -> None:
        """Reject configs whose worst-case per-step block growth cannot fit
        the engine's per-sequence block table. A step writes K+1 new KV
        rows and keeps one pending token, so the worst case touches
        ``ceil((K+2)/block_size) + 1`` blocks (straddle) on top of nothing —
        that must fit ``max_blocks_per_seq`` or the very first speculative
        step on a fresh sequence would outgrow its table."""
        k = self.num_draft_tokens
        if k < 1:
            raise ValueError(
                f"SpecDecodeConfig.num_draft_tokens={k}: need at least 1 "
                "drafted token (0 would be vanilla decode — disable "
                "speculative instead)"
            )
        bs = engine_cfg.block_size
        m = engine_cfg.max_blocks_per_seq
        # per-step worst case: K+1 fed tokens + 1 pending bonus, straddling
        # a block boundary
        growth = -(-(k + 2) // bs) + 1
        if growth > m:
            raise ValueError(
                f"SpecDecodeConfig.num_draft_tokens={k}: worst-case "
                f"per-step block growth {growth} exceeds max_blocks_per_seq="
                f"{m} (max_seq_len={engine_cfg.max_seq_len} / block_size="
                f"{bs}); num_draft_tokens is the limiting field — reduce it "
                "or raise max_seq_len"
            )
        if k + 2 >= engine_cfg.max_seq_len:
            raise ValueError(
                f"SpecDecodeConfig.num_draft_tokens={k}: a verify window of "
                f"{k + 1} tokens does not fit max_seq_len="
                f"{engine_cfg.max_seq_len}; num_draft_tokens is the "
                "limiting field"
            )
        if getattr(engine_cfg, "kv_seq_sharded", False):
            # name the fence instead of silently falling back to split
            # paths: seq-sharded pools read decode rows through a
            # dedicated shard_map partial-softmax op with no multi-token
            # verify-window variant, and the in-graph draft chain has no
            # sharded-pool read path either
            raise ValueError(
                "speculative + kv_seq_sharded is fenced: the seq-sharded "
                "pool decode read (shard_map partial-softmax op) has no "
                "multi-query verify-window variant, so draft/verify "
                "rounds cannot read sharded pools — drop kv_seq_sharded "
                "or EngineConfig.speculative"
            )
        if self.oracle_accept_rate is not None and not (
            0.0 <= float(self.oracle_accept_rate) <= 1.0
        ):
            raise ValueError(
                f"SpecDecodeConfig.oracle_accept_rate="
                f"{self.oracle_accept_rate}: must be in [0, 1] (fraction "
                "of drafted tokens force-accepted per round)"
            )
        if self.adaptive:
            if not (0.0 <= float(self.adaptive_ema) < 1.0):
                raise ValueError(
                    f"SpecDecodeConfig.adaptive_ema={self.adaptive_ema}: "
                    "must be in [0, 1)"
                )
            if not (1 <= int(self.adaptive_min_k) <= k):
                # k_choices() would silently collapse to (K,) — pinning
                # every slot at full depth while the config promises a
                # floor — so reject instead
                raise ValueError(
                    f"SpecDecodeConfig.adaptive_min_k="
                    f"{self.adaptive_min_k}: must be in "
                    f"[1, num_draft_tokens={k}]"
                )
            choices = self.k_choices()
            if choices[0] < 1 or choices[-1] != k:
                # a top choice above K is unreachable; one BELOW K would
                # silently waste draft/verify work every round (the chain
                # always drafts K tokens) — lower num_draft_tokens instead
                raise ValueError(
                    f"SpecDecodeConfig adaptive depth choices {choices} "
                    f"must lie in [1, num_draft_tokens={k}] and end at "
                    f"num_draft_tokens; adaptive_min_k/adaptive_k_choices "
                    "are the limiting fields"
                )


@dataclass
class SpeculativeConfig:
    """Reference SpeculativeConfig:28 analogue."""

    widths: Tuple[int, ...] = (4, 2)
    adaptive: bool = True
    min_accept_rate: float = 0.3       # shrink depth below this
    grow_accept_rate: float = 0.7      # grow depth above this
    min_depth: int = 1
    max_depth: int = 4
    ema: float = 0.8
    # draft→verify→accept rounds fused into ONE device dispatch (a lax.scan
    # with device-resident done/budget/stop state, exactly how the vanilla
    # engine's decode_multi amortizes the ~10 ms tunnel RTT across 16-64
    # steps). 1 = one host round per tree round (the round-2 behavior that
    # lost to vanilla at 0.90x, VERDICT r2 weak #2). Effective depth is
    # bucketed to powers of two so at most log2 variants compile.
    rounds_per_dispatch: int = 8
    # EAGLE-3-style multi-layer draft features (VERDICT r3 #1b): indices of
    # target LAYERS whose post-layer hiddens concat into the draft input
    # (e.g. low/mid/high). None = last-layer-only (EAGLE-1 behavior). The
    # draft gains a learned [k*H, H] input projection; verify forwards
    # collect the same layers so the recursion stays consistent.
    feature_layers: Optional[Tuple[int, ...]] = None

    def validate_blocks(self, max_blocks_per_seq: int,
                        block_size: int) -> None:
        """Reject width/depth combinations whose worst-case per-round block
        growth (the verify tree — including adaptive depth growth — plus
        the pending root) exceeds the per-sequence block table: the first
        round of a fresh sequence would outgrow it mid-flight otherwise."""
        widths = tuple(self.widths)
        if not widths or any(w < 1 for w in widths):
            raise ValueError(
                f"SpeculativeConfig.widths={self.widths}: every tree level "
                "needs width >= 1; widths is the limiting field"
            )
        worst = widths
        if self.adaptive:
            worst = worst + (1,) * max(0, self.max_depth - len(worst))
        nodes = TreeTopology(worst).num_nodes
        growth = -(-(nodes + 1) // block_size) + 1
        if growth > max_blocks_per_seq:
            adapt = (
                f" (adaptive depth growth to max_depth={self.max_depth})"
                if self.adaptive else ""
            )
            raise ValueError(
                f"SpeculativeConfig.widths={self.widths}{adapt}: worst-case "
                f"verify tree of {nodes} nodes needs {growth} blocks per "
                f"round, exceeding max_blocks_per_seq={max_blocks_per_seq} "
                f"(block_size={block_size}); widths/max_depth are the "
                "limiting fields"
            )


# ---------------------------------------------------------------------------
# Draft heads
# ---------------------------------------------------------------------------


def init_draft_params(
    cfg: ModelConfig, key: jax.Array, dtype: Optional[jnp.dtype] = None,
    num_feature_layers: int = 1,
) -> Dict[str, jax.Array]:
    """EAGLE-style draft net: h_next = W2 · silu(W1 · [h ; e(tok)]).

    Shares the target's embedding and LM head (reference :94) — only the
    fusion MLP is new (~2·H² params). ``num_feature_layers > 1`` adds the
    EAGLE-3 multi-layer input projection W_feat: [k·H] features (concat of
    k target layers' hiddens) project to H before fusion; deeper draft
    levels feed the head's own H-dim predictions, so only the projection
    sees the wide input."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    h = cfg.hidden_size
    k1, k2, k3 = jax.random.split(key, 3)
    dp = {
        "w_fuse": (jax.random.normal(k1, (2 * h, h), jnp.float32) * (2 * h) ** -0.5
                   ).astype(dtype),
        "w_out": (jax.random.normal(k2, (h, h), jnp.float32) * h**-0.5
                  ).astype(dtype),
        "norm": jnp.ones((h,), dtype),
    }
    if num_feature_layers > 1:
        kh = num_feature_layers * h
        dp["w_feat"] = (
            jax.random.normal(k3, (kh, h), jnp.float32) * kh**-0.5
        ).astype(dtype)
    return dp


def draft_apply(
    cfg: ModelConfig, dp: Dict[str, jax.Array], hidden: jax.Array, tok_emb: jax.Array
) -> jax.Array:
    """[..., H or k·H] × [..., H] → predicted next hidden [..., H].

    A k·H-wide input (multi-layer features from a verify pass) goes through
    the learned W_feat projection first; H-wide inputs (the draft's own
    deeper-level predictions) skip it — static shape dispatch."""
    if "w_feat" in dp and hidden.shape[-1] == dp["w_feat"].shape[0]:
        hidden = (hidden @ dp["w_feat"].astype(hidden.dtype))
    x = jnp.concatenate([hidden, tok_emb], axis=-1)
    x = jax.nn.silu(x @ dp["w_fuse"]) @ dp["w_out"]
    return llama.rms_norm(x, dp["norm"], cfg.rms_norm_eps)


def distill_draft_params(
    cfg: ModelConfig,
    params: llama.Params,
    key: jax.Array,
    steps: int = 400,
    batch: int = 8,
    seq_len: int = 64,
    num_batches: int = 8,
    lr: float = 2e-3,
    ce_weight: float = 0.2,
    feature_layers: Optional[Tuple[int, ...]] = None,
    on_policy: bool = False,
    data_stream=None,
) -> Dict[str, jax.Array]:
    """EAGLE-style draft-head distillation against the frozen target.

    The reference assumes pretrained EAGLE/Medusa weights exist
    (``worker/engines/speculative.py`` only runs inference); here the head
    can be fit on-device in seconds: teacher-force the target over token
    streams, then regress ``draft(h_t, e(x_{t+1})) → h_{t+1}`` with a
    feature MSE plus a CE term against the target's next-token distribution
    (the EAGLE recipe: feature-level supervision dominates, logits align
    the part that matters for acceptance).

    Teacher hidden states are precomputed once for ``num_batches`` fixed
    streams; the training loop then runs ``steps`` cheap MLP updates
    jitted on device. Returns draft params in the model dtype.

    EAGLE-3 knobs (VERDICT r3 #1b):
    - ``feature_layers``: distill the draft on CONCATENATED hiddens of
      these target layers (adds the ``w_feat`` projection; pass the same
      tuple as ``SpeculativeConfig.feature_layers`` at serving).
    - ``on_policy``: draw the distill streams from the TARGET's own
      sampled generations instead of uniform-random tokens — the
      distribution the draft must match at serving time.
    - ``data_stream``: ``fn(key, batch, seq_len) -> [B, S] int32`` custom
      stream sampler (e.g. the toy-task chain); overrides both defaults.
    """
    import optax

    bs = 16
    kd, kt = jax.random.split(key)
    m = -(-seq_len // bs)
    positions = jnp.tile(jnp.arange(seq_len, dtype=jnp.int32), (batch, 1))
    lens = jnp.full((batch,), seq_len, jnp.int32)
    tables = jnp.asarray(
        np.arange(1, 1 + batch * m, dtype=np.int32).reshape(batch, m)
    )

    # ---- distill streams: custom sampler > on-policy rollouts > random
    if data_stream is not None:
        tokens_all = jnp.stack([
            data_stream(jax.random.fold_in(kt, i), batch, seq_len)
            for i in range(num_batches)
        ]).astype(jnp.int32)
    elif on_policy:
        @jax.jit
        def rollout(params, kk):
            k0, kseq = jax.random.split(kk)
            first = jax.random.randint(k0, (batch,), 0, cfg.vocab_size,
                                       jnp.int32)
            kvp = llama.init_kv_pools(cfg, 1 + batch * m, bs)

            def step(carry, ks_):
                kvp, tok, pos = carry
                out = llama.forward_chunk(
                    cfg, params, tok[:, None], pos[:, None], kvp, tables,
                    pos + 1, block_size=bs, last_only=True,
                )
                nxt = jax.random.categorical(
                    ks_, out.logits[:, 0].astype(jnp.float32), axis=-1
                ).astype(jnp.int32)
                return (out.kv, nxt, pos + 1), nxt

            keys = jax.random.split(kseq, seq_len - 1)
            (_, _, _), rest = jax.lax.scan(
                step, (kvp, first, jnp.zeros((batch,), jnp.int32)), keys
            )
            return jnp.concatenate([first[:, None], rest.T], axis=1)

        tokens_all = jnp.stack([
            rollout(params, jax.random.fold_in(kt, i))
            for i in range(num_batches)
        ])
    else:
        tokens_all = jax.random.randint(
            kt, (num_batches, batch, seq_len), 0, cfg.vocab_size, jnp.int32
        )

    # teacher labels are TOP-K only: a full [N, B, S, V] float32 log-prob
    # table is ~20 GB at Llama-3/Qwen vocab sizes (this OOM'd 0.5B-scale
    # distillation on a 16 GB chip); the CE term only needs the head of the
    # teacher distribution, which at a sharply-trained target carries
    # essentially all the mass
    label_k = min(64, cfg.vocab_size)

    # params ride as jit ARGUMENTS, not closure constants: traced closures
    # over multi-GB pytrees get inlined as IR constants (host-materialized),
    # which OOMs at 0.5B+ scale
    collect = tuple(feature_layers) if feature_layers else None

    @jax.jit
    def teacher(params, tokens):
        kv = llama.init_kv_pools(cfg, 1 + batch * m, bs)
        out = llama.forward_chunk(
            cfg, params, tokens, positions, kv, tables, lens,
            block_size=bs, last_only=False, collect_layers=collect,
        )
        # target next-token distribution at every position (frozen labels)
        logits = llama.project_logits(cfg, params, out.hidden)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        top_lp, top_idx = jax.lax.top_k(logp, label_k)
        h32 = out.hidden.astype(jnp.float32)
        feats = out.features.astype(jnp.float32) if collect else h32
        return h32, feats, top_lp, top_idx

    hiddens, featss, top_lps, top_idxs = [], [], [], []
    for i in range(num_batches):
        h, f, lp, idx = teacher(params, tokens_all[i])
        hiddens.append(h)
        featss.append(f)
        top_lps.append(lp)
        top_idxs.append(idx)
    hiddens = jnp.stack(hiddens)   # [N, B, S, H] float32
    # no collect → features ARE the final hiddens: alias, don't duplicate
    # (a second [N,B,S,H] f32 stack matters on the 16 GB chip this distill
    # already OOM'd at 0.5B scale)
    featss = hiddens if collect is None else jnp.stack(featss)
    top_lps = jnp.stack(top_lps)   # [N, B, S, K]
    top_idxs = jnp.stack(top_idxs)  # [N, B, S, K] int32

    # ---- student: train in float32
    dp = jax.tree.map(
        lambda a: a.astype(jnp.float32),
        init_draft_params(
            cfg, kd, num_feature_layers=len(collect) if collect else 1
        ),
    )
    # draft_apply's output rms_norm pins the prediction's magnitude at the
    # norm gain — initialized at 1, while a TIED-embedding target's hiddens
    # must be large (its head rows stay near unit norm, so logit sharpness
    # lives in |h|; an untied lm_head absorbs the magnitude instead). A
    # unit-gain draft starts with a magnitude floor the optimizer must climb
    # ~|h|x to escape — measured round 3: tied mini accepted 1/732 vs the
    # untied 23/492 purely from this. Initialize the gain at the teacher's
    # hidden RMS so the draft starts on the teacher's scale for ANY head
    # convention.
    teacher_rms = jnp.sqrt(jnp.mean(jnp.square(hiddens)))
    dp["norm"] = dp["norm"] * teacher_rms
    opt = optax.adam(lr)
    opt_state = opt.init(dp)
    cfg32 = cfg  # rms eps etc. unchanged; draft_apply respects input dtype

    def loss_fn(dp, params, tokens, hidden, feats, top_lp, top_idx):
        # inputs at t: (features_t, emb(x_{t+1})) → predict h_{t+1} — the
        # TARGET stays the final-layer hidden (that is what project_logits
        # reads at verify time); only the INPUT widens to multi-layer
        emb_next = llama.embed_tokens(params, tokens[:, 1:], cfg).astype(
            jnp.float32
        )
        pred = draft_apply(cfg32, dp, feats[:, :-1], emb_next)  # [B,S-1,H]
        mse = jnp.mean(jnp.square(pred - hidden[:, 1:]))
        pred_logits = llama.project_logits(cfg, params, pred)
        pred_logp = jax.nn.log_softmax(pred_logits, axis=-1)
        # CE against the teacher's top-k next-step distribution (gathered
        # from the student's full log-softmax at the teacher's indices)
        sel = jnp.take_along_axis(pred_logp, top_idx[:, 1:], axis=-1)
        ce = -jnp.mean(jnp.sum(jnp.exp(top_lp[:, 1:]) * sel, axis=-1))
        return mse + ce_weight * ce

    # single scan = one compile + one device call (tunnel-friendly);
    # params/teacher data as arguments for the same closure-constant reason
    @jax.jit
    def train(dp, opt_state, params, tokens_all, hiddens, featss, top_lps,
              top_idxs):
        def step_fn(carry, step):
            dp, opt_state = carry
            i = step % num_batches
            loss, grads = jax.value_and_grad(loss_fn)(
                dp, params, tokens_all[i], hiddens[i], featss[i],
                top_lps[i], top_idxs[i]
            )
            updates, opt_state = opt.update(grads, opt_state)
            return (optax.apply_updates(dp, updates), opt_state), loss

        (dp, _), losses = jax.lax.scan(
            step_fn, (dp, opt_state), jnp.arange(steps)
        )
        return dp, losses

    dp, _losses = train(dp, opt_state, params, tokens_all, hiddens, featss,
                        top_lps, top_idxs)
    dtype = jnp.dtype(cfg.dtype)
    return jax.tree.map(lambda a: a.astype(dtype), dp)


def init_medusa_params(
    cfg: ModelConfig, key: jax.Array, num_heads: int = 4,
    dtype: Optional[jnp.dtype] = None,
) -> Dict[str, jax.Array]:
    """Medusa alternative (reference MedusaHead:474): K residual projections
    of the last hidden, one per lookahead distance; shares the LM head."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    h = cfg.hidden_size
    return {
        "w": (jax.random.normal(key, (num_heads, h, h), jnp.float32) * h**-0.5
              ).astype(dtype),
    }


def medusa_logits(
    cfg: ModelConfig, params: llama.Params, mp: Dict[str, jax.Array],
    hidden: jax.Array,
) -> jax.Array:
    """hidden [B, H] → [B, K, V] logits for +1..+K lookahead."""
    proj = jnp.einsum("bh,khg->bkg", hidden.astype(jnp.float32),
                      mp["w"].astype(jnp.float32))
    proj = proj + hidden.astype(jnp.float32)[:, None, :]
    head = params.get("lm_head", params["embedding"])
    return jnp.einsum("bkh,vh->bkv", proj, head.astype(jnp.float32))


# ---------------------------------------------------------------------------
# The decoder
# ---------------------------------------------------------------------------


@dataclass
class _SpecWave:
    """In-flight speculative wave state (``SpeculativeDecoder.start_wave``).

    Exists so a serving loop can interleave bounded spec dispatches with
    other engine work (adaptive speculation in the batcher, VERDICT r3 #7)
    instead of blocking on a whole generation."""

    requests: List[InferenceRequest]
    seq_ids: List[str]
    start: float
    first_token_time: float
    pendings: np.ndarray
    h_last: Any
    tables: np.ndarray
    prefix_lens: np.ndarray
    cached_counts: List[int]
    emitted: List[List[int]]
    done: List[bool]
    finish: List[Optional[str]]
    stops: List[set]
    stop_pad: np.ndarray
    budgets_full: np.ndarray

    def emit(self, i: int, tok: int) -> None:
        if self.done[i]:
            return
        if tok in self.stops[i]:
            self.done[i] = True
            self.finish[i] = "stop"
            return
        self.emitted[i].append(tok)
        if len(self.emitted[i]) >= self.requests[i].sampling.max_new_tokens:
            self.done[i] = True
            self.finish[i] = "length"

    @property
    def all_done(self) -> bool:
        return all(self.done)


class SpeculativeDecoder:
    """Greedy speculative generation over the paged-KV substrate.

    Batched: every sequence in the batch drafts/verifies the same tree shape
    each step; per-sequence accept lengths differ freely.
    """

    def __init__(
        self,
        model_cfg: ModelConfig | str,
        params: Optional[llama.Params] = None,
        draft_params: Optional[Dict[str, jax.Array]] = None,
        spec_cfg: Optional[SpeculativeConfig] = None,
        max_batch_size: int = 4,
        max_seq_len: int = 1024,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        seed: int = 0,
        eos_token_id: Optional[int] = None,
        prefill_buckets: Tuple[int, ...] = (16, 32, 64, 128, 256, 512, 1024),
        kv_cache_dtype: Optional[str] = None,
    ) -> None:
        """``kv_cache_dtype``: ``"int8"`` stores the decoder's pools
        quantized (per-(page, token) scale pools ride alongside; the tree
        verify pass dequantizes context-sized through the shared
        ``ops.attention.dequantize_kv`` arithmetic, and path compaction
        moves code + scale rows as an atomic pair). Sliding-window models
        speculate at any tree depth since round 8 — the tree-attention
        mask windows within-chunk node visibility by semantic position
        (``ops.attention.paged_tree_attention``)."""
        self.model_cfg = (
            get_model_config(model_cfg) if isinstance(model_cfg, str) else model_cfg
        )
        self.spec_cfg = spec_cfg or SpeculativeConfig()
        if kv_cache_dtype not in (None, "int8"):
            raise ValueError(
                f"SpeculativeDecoder kv_cache_dtype={kv_cache_dtype!r}: "
                "only int8 (or None = model dtype) is wired"
            )
        self.kv_dtype = jnp.int8 if kv_cache_dtype == "int8" else None
        self.block_size = block_size
        self.max_batch_size = max_batch_size
        self.max_seq_len = max_seq_len
        self.max_blocks_per_seq = -(-max_seq_len // block_size)
        self.spec_cfg.validate_blocks(self.max_blocks_per_seq, block_size)
        self.num_blocks = num_blocks or int(
            max_batch_size * self.max_blocks_per_seq * 1.5
        ) + 1
        key = jax.random.PRNGKey(seed)
        self.params = params if params is not None else llama.init_params(
            self.model_cfg, key
        )
        self._collect = (
            tuple(self.spec_cfg.feature_layers)
            if self.spec_cfg.feature_layers else None
        )
        self.draft_params = (
            draft_params
            if draft_params is not None
            else init_draft_params(
                self.model_cfg, jax.random.PRNGKey(seed + 1),
                num_feature_layers=(
                    len(self._collect) if self._collect else 1
                ),
            )
        )
        self.kv = llama.init_kv_pools(
            self.model_cfg, self.num_blocks, block_size, dtype=self.kv_dtype
        )
        self.manager = PagedKVCacheManager(self.num_blocks, block_size)
        self.eos_token_id = eos_token_id
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        self._step_fns: Dict[Tuple[int, ...], Any] = {}
        self._scan_fns: Dict[Tuple[Any, int], Any] = {}
        self._prefill_fn = self._build_prefill()
        self._widths = tuple(self.spec_cfg.widths)
        self.accept_rate_ema = 0.5
        self.stats: Dict[str, Any] = {
            "steps": 0, "drafted": 0, "accepted": 0, "emitted": 0,
            "depth_changes": 0,
        }

    # ----------------------------------------------------------- jit builders

    def _build_prefill(self):
        cfg, bs = self.model_cfg, self.block_size
        collect = self._collect

        def prefill(params, kv, tokens, positions, block_table, kv_len):
            out = llama.forward_chunk(
                cfg, params, tokens, positions, kv, block_table, kv_len,
                block_size=bs, last_only=True, collect_layers=collect,
            )
            src = out.features if collect else out.hidden
            n_valid = jnp.sum((positions >= 0).astype(jnp.int32), axis=1)
            last_idx = jnp.maximum(n_valid - 1, 0)
            h_last = jnp.take_along_axis(
                src, last_idx[:, None, None].astype(jnp.int32), axis=1
            )[:, 0, :]
            return out.logits[:, 0, :], h_last, out.kv

        return jax.jit(prefill, donate_argnums=(1,))

    def _make_round(self, widths: Tuple[int, ...]):
        """The raw draft→verify→accept→compact round body (un-jitted), shared
        by the single-round step API and the multi-round scan."""
        topo = TreeTopology(widths)
        cfg = self.model_cfg
        bs = self.block_size
        collect = self._collect
        parents = jnp.asarray(topo.parents)
        depths = jnp.asarray(topo.depths)
        tree_mask = jnp.asarray(topo.ancestor_mask)
        n = topo.num_nodes
        dmax = topo.max_depth
        level_slices = topo.level_slices

        def step(params, dp, kv, pending, h_last, prefix_lens, block_tables,
                 active):
            b = pending.shape[0]

            # token embedding must follow the target model's convention
            # (Gemma scales by sqrt(H)) or the draft head sees inputs on a
            # different scale than the hidden states it fuses with
            def emb_of(ids):
                return llama.embed_tokens(params, ids, cfg)

            # ---- draft phase: grow the tree level by level (static shapes)
            tokens = jnp.zeros((b, n), jnp.int32).at[:, 0].set(pending)
            h_root = draft_apply(cfg, dp, h_last, emb_of(pending))
            frontier_h = h_root[:, None, :]           # [B, F, H]
            for li, w in enumerate(widths):
                # draft logits MUST go through project_logits (final_norm +
                # head) — the distillation CE trains the draft against
                # exactly that readout (distill_draft_params loss_fn), and a
                # raw frontier_h @ head readout diverges from it badly
                # enough to zero the accept rate on tied-embedding models
                # (round-3 probe: tied mini accepted 1/732 without the norm,
                # 20x more with it)
                logits = llama.project_logits(cfg, params, frontier_h)
                _, cand = jax.lax.top_k(logits, w)    # [B, F, w]
                start, end = level_slices[li]
                tokens = tokens.at[:, start:end].set(cand.reshape(b, -1))
                # next frontier hiddens: f(parent_h, emb(child_tok))
                child_emb = emb_of(cand)                         # [B, F, w, H]
                parent_h = jnp.broadcast_to(
                    frontier_h[:, :, None, :], child_emb.shape
                )
                frontier_h = draft_apply(cfg, dp, parent_h, child_emb).reshape(
                    b, -1, cfg.hidden_size
                )

            # ---- verify phase: one target forward over the tree.
            # Finished sequences must not write ANY pages (their tables may
            # not even cover the tree range near max_seq_len): position -1
            # drops the writes.
            rope_pos = prefix_lens[:, None] + depths[None, :]
            cache_pos = prefix_lens[:, None] + jnp.arange(n, dtype=jnp.int32)[None, :]
            cache_pos = jnp.where(active[:, None], cache_pos, -1)
            out = llama.forward_tree_chunk(
                cfg, params, tokens, rope_pos, cache_pos, kv, block_tables,
                prefix_lens, tree_mask, block_size=bs,
                collect_layers=collect,
            )
            target_pred = jnp.argmax(out.logits, axis=-1).astype(jnp.int32)  # [B,N]

            # ---- acceptance: greedy match down the tree
            accept = jnp.zeros((b, n), bool).at[:, 0].set(True)
            for i in range(1, n):
                p = int(topo.parents[i])
                ok = accept[:, p] & (tokens[:, i] == target_pred[:, p])
                accept = accept.at[:, i].set(ok)
            # deepest accepted node, ties → lowest index
            score = jnp.where(
                accept, depths[None, :] * (n + 1) - jnp.arange(n)[None, :], -1
            )
            best = jnp.argmax(score, axis=-1).astype(jnp.int32)   # [B]
            n_accept = jnp.take(depths, best)                      # [B] 0..dmax

            # ---- path extraction (walk parents; static dmax iterations)
            path = jnp.full((b, dmax), n, jnp.int32)  # n = OOB sentinel
            cur = best
            for _ in range(dmax):
                d = jnp.take(depths, cur)
                row = jnp.arange(b)
                write_col = jnp.where(d >= 1, d - 1, dmax)
                path = path.at[row, write_col].set(
                    jnp.where(d >= 1, cur, n), mode="drop"
                )
                cur = jnp.where(d > 1, jnp.take(parents, cur), cur)

            path_valid = path < n                                   # [B, dmax]
            safe_path = jnp.where(path_valid, path, 0)
            accepted_tokens = jnp.where(
                path_valid,
                jnp.take_along_axis(tokens, safe_path, axis=1),
                -1,
            )                                                       # [B, dmax]
            bonus = jnp.take_along_axis(target_pred, best[:, None], axis=1)[:, 0]
            new_h = jnp.take_along_axis(
                out.features if collect else out.hidden,
                best[:, None, None].astype(jnp.int32), axis=1,
            )[:, 0, :]

            # ---- KV compaction: move accepted nodes' pages to depth order
            kv2 = out.kv
            live = path_valid & active[:, None]
            src_pos = jnp.where(live, prefix_lens[:, None] + path, -1)
            dst_pos = prefix_lens[:, None] + 1 + jnp.arange(dmax)[None, :]
            dst_pos = jnp.where(live, dst_pos, -1)
            moved = {
                "k": _move_rows(kv2["k"], block_tables, src_pos, dst_pos, bs),
                "v": _move_rows(kv2["v"], block_tables, src_pos, dst_pos, bs),
            }
            # int8 pools: a code row without its scale is garbage — the
            # compaction moves them as an atomic pair
            for sk in ("k_scale", "v_scale"):
                if sk in kv2:
                    moved[sk] = _move_scale_rows(
                        kv2[sk], block_tables, src_pos, dst_pos, bs
                    )
            return moved, accepted_tokens, n_accept, bonus, new_h

        return step

    def _build_step(self, widths: Tuple[int, ...]):
        return jax.jit(self._make_round(widths), donate_argnums=(2,))

    def _get_step(self, widths: Tuple[int, ...]):
        if widths not in self._step_fns:
            self._step_fns[widths] = self._build_step(widths)
        return self._step_fns[widths]

    def _build_scan(self, widths: Tuple[int, ...], rounds: int):
        """``rounds`` draft→verify→accept rounds in ONE dispatch: a lax.scan
        whose carry keeps KV, pending tokens, draft hiddens, prefix lengths,
        and per-row done/emitted state ON DEVICE — the speculative analogue
        of the engine's ``decode_multi`` scan (``runtime/engine.py``
        decode_multi), so the ~10 ms host RTT is paid once per ``rounds``
        tree rounds instead of once per round (VERDICT r2 weak #2 / next #2).

        Per-round records (pending-in, accepted path, accept counts, bonus,
        active mask) are returned so the host replays cache-manager commits
        and emission bookkeeping EXACTLY as the per-round loop would have —
        device state and host metadata cannot drift.
        """
        round_fn = self._make_round(widths)
        topo = TreeTopology(widths)
        n = topo.num_nodes
        dmax = topo.max_depth
        max_ctx = min(self.max_seq_len, self.max_blocks_per_seq * self.block_size)

        def scan_step(params, dp, kv, pendings, h_last, prefix_lens,
                      block_tables, done0, n_emit0, budgets, stop_ids):
            b = pendings.shape[0]

            def body(carry, _):
                kv, pending, h_last, prefix, done, n_emit = carry
                # a row whose next tree cannot fit below the context capacity
                # freezes here (host labels it "length" after the dispatch)
                fits = prefix + n + 1 <= max_ctx
                active = (~done) & fits
                kv2, acc, n_acc, bonus, new_h = round_fn(
                    params, dp, kv, pending, h_last, prefix, block_tables,
                    active,
                )
                # ---- device emission accounting (gates later rounds only;
                # the authoritative emission replay happens on host from the
                # recorded arrays). Emission order: accepted path then bonus.
                j = jnp.arange(dmax + 1, dtype=jnp.int32)[None, :]
                acc_pad = jnp.concatenate(
                    [acc, jnp.full((b, 1), -1, jnp.int32)], axis=1
                )
                ordered = jnp.where(
                    j < n_acc[:, None], acc_pad,
                    jnp.where(j == n_acc[:, None], bonus[:, None], -1),
                )
                ordered = jnp.where(active[:, None], ordered, -1)
                is_stop = (
                    (ordered[:, :, None] == stop_ids[:, None, :]).any(-1)
                    & (ordered >= 0)
                )
                cum = jnp.cumsum(is_stop.astype(jnp.int32), axis=1)
                pre_stop = (cum - is_stop.astype(jnp.int32)) == 0
                emit_j = (ordered >= 0) & pre_stop & ~is_stop
                rank = jnp.cumsum(emit_j.astype(jnp.int32), axis=1) \
                    - emit_j.astype(jnp.int32)
                emit_mask = emit_j & (n_emit[:, None] + rank < budgets[:, None])
                n_emit2 = n_emit + emit_mask.sum(axis=1)
                stop_hit = (is_stop & pre_stop).any(axis=1)
                done2 = done | (~fits) | (
                    active & (stop_hit | (n_emit2 >= budgets))
                )
                pending2 = jnp.where(active, bonus, pending)
                h2 = jnp.where(active[:, None], new_h, h_last)
                prefix2 = jnp.where(active, prefix + 1 + n_acc, prefix)
                rec = (pending, acc, n_acc, bonus, active)
                return (kv2, pending2, h2, prefix2, done2, n_emit2), rec

            carry, recs = jax.lax.scan(
                body,
                (kv, pendings, h_last, prefix_lens, done0, n_emit0),
                None,
                length=rounds,
            )
            return carry, recs

        return jax.jit(scan_step, donate_argnums=(2,))

    def _get_scan(self, widths: Tuple[int, ...], rounds: int):
        key = (widths, rounds)
        if key not in self._scan_fns:
            self._scan_fns[key] = self._build_scan(widths, rounds)
        return self._scan_fns[key]

    # ------------------------------------------------------------- generation

    def generate(self, requests: Sequence[InferenceRequest]) -> List[InferenceResponse]:
        """Greedy speculative batch generation (waves of ≤ max_batch_size).

        Only greedy sampling is supported (the verify pass is an argmax
        match); non-greedy params are rejected rather than silently ignored
        so behavior can't diverge from TPUEngine under the same request.
        """
        for r in requests:
            if r.sampling.temperature and r.sampling.temperature > 0.0:
                raise ValueError(
                    "SpeculativeDecoder is greedy-only: request "
                    f"{r.request_id} has temperature={r.sampling.temperature}; "
                    "route sampled requests to TPUEngine"
                )
        out: List[InferenceResponse] = []
        for i in range(0, len(requests), self.max_batch_size):
            out.extend(self._generate_wave(requests[i : i + self.max_batch_size]))
        return out

    def _prefill(self, req: InferenceRequest, seq_id: str) -> Tuple[int, jax.Array, int]:
        token_ids = req.prompt_token_ids or []
        if not token_ids:
            raise ValueError("request has no prompt_token_ids")
        blocks, cached = self.manager.allocate_sequence(seq_id, token_ids)
        table = self.manager.block_table_for(seq_id, self.max_blocks_per_seq)
        fresh = token_ids[cached:]
        n = len(fresh)
        # bucket-pad so prefill compiles once per bucket, not per length
        bucket = next((bkt for bkt in self.prefill_buckets if bkt >= n), n)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = fresh
        pos = np.full((1, bucket), -1, np.int32)
        pos[0, :n] = np.arange(cached, cached + n)
        logits, h_last, self.kv = self._prefill_fn(
            self.params, self.kv, jnp.asarray(toks), jnp.asarray(pos),
            jnp.asarray(table[None]), jnp.asarray([len(token_ids)], jnp.int32),
        )
        pending = int(jnp.argmax(logits[0]))
        return pending, h_last[0], cached

    def start_wave(self, requests: Sequence[InferenceRequest]) -> "_SpecWave":
        """Prefill a wave (≤ max_batch_size greedy requests) and return its
        state object. Drive with :meth:`advance_wave` (one fused multi-round
        dispatch per call — bounded work, so a serving loop can interleave
        other engine rounds between calls) and collect with
        :meth:`finish_wave`."""
        requests = list(requests)
        if not requests or len(requests) > self.max_batch_size:
            raise ValueError(
                f"wave of {len(requests)} requests (max {self.max_batch_size})"
            )
        b = len(requests)
        seq_ids = [r.session_id or uuid.uuid4().hex for r in requests]
        start = time.time()
        pendings = np.zeros((b,), np.int32)
        h_lasts = []
        cached_counts = []
        tables = np.zeros((b, self.max_blocks_per_seq), np.int32)
        prefix_lens = np.zeros((b,), np.int32)
        try:
            for i, (r, sid) in enumerate(zip(requests, seq_ids)):
                pending, h_last, cached = self._prefill(r, sid)
                pendings[i] = pending
                h_lasts.append(h_last)
                cached_counts.append(cached)
                prefix_lens[i] = len(r.prompt_token_ids or [])
                tables[i] = self.manager.block_table_for(
                    sid, self.max_blocks_per_seq
                )
        except Exception:
            # a failed prefill must not strand the rows already allocated —
            # in a serving loop each leak would shrink the spec pool forever
            for sid in seq_ids:
                if sid in self.manager.seq_blocks:
                    self.manager.free_sequence(sid, cache=False)
            raise
        h_last = jnp.stack(h_lasts)
        first_token_time = time.time()

        stops = [set(r.sampling.stop_token_ids) |
                 ({self.eos_token_id} if self.eos_token_id is not None else set())
                 for r in requests]
        # device stop-id table (pad -1 never matches: ordered tokens are >= 0)
        max_stops = max(1, max(len(s) for s in stops) if stops else 1)
        stop_pad = np.full((b, max_stops), -1, np.int32)
        for i, s in enumerate(stops):
            for si, tok in enumerate(sorted(s)):
                stop_pad[i, si] = tok

        wave = _SpecWave(
            requests=requests, seq_ids=seq_ids, start=start,
            first_token_time=first_token_time,
            pendings=pendings, h_last=h_last, tables=tables,
            prefix_lens=prefix_lens, cached_counts=cached_counts,
            emitted=[[] for _ in range(b)], done=[False] * b,
            finish=[None] * b, stops=stops, stop_pad=stop_pad,
            budgets_full=np.asarray(
                [r.sampling.max_new_tokens for r in requests], np.int32
            ),
        )
        # the prefill-sampled token is the first generated token
        for i in range(b):
            wave.emit(i, int(pendings[i]))
        return wave

    def advance_wave(self, wave: "_SpecWave") -> bool:
        """Run ONE fused multi-round dispatch for the wave; True when every
        sequence finished. Work per call is bounded by
        ``spec_cfg.rounds_per_dispatch`` tree rounds."""
        b = len(wave.requests)
        requests, seq_ids = wave.requests, wave.seq_ids
        emitted, done, finish = wave.emitted, wave.done, wave.finish
        emit = wave.emit
        pendings, h_last = wave.pendings, wave.h_last
        prefix_lens, tables = wave.prefix_lens, wave.tables
        stop_pad, budgets_full = wave.stop_pad, wave.budgets_full
        max_ctx = min(self.max_seq_len, self.max_blocks_per_seq * self.block_size)

        if not all(done):
            widths = self._widths
            topo = TreeTopology(widths)
            topo_n, dmax = topo.num_nodes, topo.max_depth
            # host mirror of the device fits-freeze: rows whose next tree
            # cannot fit finish with "length" (and must not reserve blocks)
            for i in range(b):
                if not done[i] and int(prefix_lens[i]) + topo_n + 1 > max_ctx:
                    done[i] = True
                    finish[i] = "length"
            active_rows = [i for i in range(b) if not done[i]]
            if not active_rows:
                return True
            # rounds per dispatch: capped by the largest remaining budget
            # (each active round emits >= 1 token) and bucketed to a power of
            # two so at most log2(rounds_per_dispatch) graphs compile
            max_remaining = max(
                int(budgets_full[i]) - len(emitted[i]) for i in active_rows
            )
            rounds = max(1, min(self.spec_cfg.rounds_per_dispatch, max_remaining))
            rounds = 1 << (rounds.bit_length() - 1)

            def blocks_needed(n_rounds: int) -> int:
                total = 0
                for i in active_rows:
                    cur = len(self.manager.seq_tokens[seq_ids[i]])
                    have = len(self.manager.seq_blocks[seq_ids[i]])
                    t = min(
                        (n_rounds - 1) * (dmax + 1) + topo_n + 1,
                        max_ctx - int(prefix_lens[i]),
                    )
                    total += max(
                        0,
                        -(-(cur + t) // self.block_size) - have,
                    )
                return total

            # worst-case reservation for `rounds` rounds is ~rounds/2 x the
            # old per-round peak — shrink the dispatch rather than evicting
            # the prefix cache (or aborting the batch) to pre-book blocks
            # most accept rates never use
            while rounds > 1 and \
                    blocks_needed(rounds) > self.manager.num_reclaimable:
                rounds >>= 1
            for i in active_rows:
                sid = seq_ids[i]
                # worst-case growth over the dispatch: (rounds-1) committed
                # paths of dmax+1 plus the final round's tree
                need = (rounds - 1) * (dmax + 1) + topo_n + 1
                need = min(need, max_ctx - int(prefix_lens[i]))
                self.manager.reserve_tokens(sid, need)
                tables[i] = self.manager.block_table_for(
                    sid, self.max_blocks_per_seq
                )
            scan_fn = self._get_scan(widths, rounds)
            done_np = np.asarray(done)
            budgets_rem = np.asarray(
                [int(budgets_full[i]) - len(emitted[i]) for i in range(b)],
                np.int32,
            )
            carry, recs = scan_fn(
                self.params, self.draft_params, self.kv,
                jnp.asarray(pendings), h_last,
                jnp.asarray(prefix_lens, dtype=jnp.int32),
                jnp.asarray(tables),
                jnp.asarray(done_np), jnp.zeros((b,), jnp.int32),
                jnp.asarray(budgets_rem), jnp.asarray(stop_pad),
            )
            self.kv, pend_dev, h_last, prefix_dev, done_dev, _ = carry
            rec_pend, rec_acc, rec_nacc, rec_bonus, rec_active = (
                np.asarray(r) for r in recs
            )
            # ---- host replay: commits + emission EXACTLY as the per-round
            # loop would have done them, from the recorded per-round arrays
            for r in range(rounds):
                act = rec_active[r]
                if not act.any():
                    break
                self.stats["steps"] += 1
                for i in range(b):
                    if not act[i]:
                        continue
                    self.manager.commit_tokens(
                        seq_ids[i], [int(rec_pend[r, i])]
                    )
                    for d in range(int(rec_nacc[r, i])):
                        tok = int(rec_acc[r, i, d])
                        self.manager.commit_tokens(seq_ids[i], [tok])
                        emit(i, tok)
                        if done[i]:
                            break
                    if not done[i]:
                        emit(i, int(rec_bonus[r, i]))
                    self.stats["drafted"] += topo_n - 1
                    self.stats["accepted"] += int(rec_nacc[r, i])
                    self.stats["emitted"] += int(rec_nacc[r, i]) + 1
                    self.stats["row_steps"] = self.stats.get("row_steps", 0) + 1
                # adapt on rows active THIS round (finished rows draft stale
                # state); ema replayed per round, same as the old loop
                live_rate = float(rec_nacc[r][act].mean()) / max(1, dmax)
                self.accept_rate_ema = (
                    self.spec_cfg.ema * self.accept_rate_ema
                    + (1 - self.spec_cfg.ema) * live_rate
                )
            wave.pendings = np.asarray(pend_dev)
            wave.prefix_lens = np.asarray(prefix_dev)
            wave.h_last = h_last
            # rows the device froze for capacity (fits-check) but the host
            # didn't finish otherwise: label them now so the loop terminates
            done_dev_np = np.asarray(done_dev)
            for i in range(b):
                if done_dev_np[i] and not done[i]:
                    done[i] = True
                    finish[i] = "length"
            self._maybe_adapt()
        return all(done)

    def finish_wave(self, wave: "_SpecWave") -> List[InferenceResponse]:
        """Free the wave's sequences (prefix-cached) and build responses."""
        responses = []
        now = time.time()
        for i, (r, sid) in enumerate(zip(wave.requests, wave.seq_ids)):
            self.manager.free_sequence(sid, cache=True)
            responses.append(
                InferenceResponse(
                    request_id=r.request_id,
                    token_ids=wave.emitted[i][: r.sampling.max_new_tokens],
                    finish_reason=wave.finish[i] or "length",
                    prompt_tokens=len(r.prompt_token_ids or []),
                    completion_tokens=len(
                        wave.emitted[i][: r.sampling.max_new_tokens]
                    ),
                    cached_tokens=wave.cached_counts[i],
                    ttft_ms=(wave.first_token_time - wave.start) * 1000.0,
                    e2e_ms=(now - wave.start) * 1000.0,
                )
            )
        return responses

    def abort_wave(self, wave: "_SpecWave") -> None:
        """Release a wave's sequences without caching (serving-loop error
        recovery: the batcher must be able to drop a wedged wave)."""
        for sid in wave.seq_ids:
            if sid in self.manager.seq_blocks:
                self.manager.free_sequence(sid, cache=False)

    def _generate_wave(self, requests: Sequence[InferenceRequest]) -> List[InferenceResponse]:
        wave = self.start_wave(requests)
        while not self.advance_wave(wave):
            pass
        return self.finish_wave(wave)

    def worst_case_tree_nodes(self) -> int:
        """Upper bound on the verify-tree size over adaptive depth growth —
        what an admission policy must budget per round on top of the
        generation itself (the fits-freeze ends a row at
        ``prefix + nodes + 1 > max ctx``)."""
        widths = tuple(self._widths)
        if self.spec_cfg.adaptive:
            widths = widths + (1,) * max(
                0, self.spec_cfg.max_depth - len(widths)
            )
        return TreeTopology(widths).num_nodes

    def _maybe_adapt(self) -> None:
        """Reference _adapt_depth:456-463: shrink when acceptance is poor,
        grow when it is high."""
        if not self.spec_cfg.adaptive:
            return
        depth = len(self._widths)
        if (self.accept_rate_ema < self.spec_cfg.min_accept_rate
                and depth > self.spec_cfg.min_depth):
            self._widths = self._widths[:-1]
            self.stats["depth_changes"] += 1
        elif (self.accept_rate_ema > self.spec_cfg.grow_accept_rate
                and depth < self.spec_cfg.max_depth):
            self._widths = self._widths + (1,)
            self.stats["depth_changes"] += 1

    def get_stats(self) -> Dict[str, Any]:
        out = dict(self.stats)
        # path-level acceptance (the reference's notion, speculative.py:456):
        # accepted tokens per step per sequence over the max draft depth —
        # NOT accepted/drafted nodes, which is structurally low for trees
        # (most sibling branches are always discarded)
        out["accept_rate_ema"] = self.accept_rate_ema
        if out["steps"]:
            # emitted is batch-aggregate; steps counts batch rounds
            out["tokens_per_step_batch"] = out["emitted"] / out["steps"]
            rows = max(self.stats.get("row_steps", 0), 1)
            out["tokens_per_step"] = out["emitted"] / rows
        out["current_widths"] = list(self._widths)
        return out


def _move_rows(
    pool: jax.Array,          # [L, N, Hkv, Bk, D] (head-major pages)
    block_tables: jax.Array,  # [B, M]
    src_pos: jax.Array,       # [B, P] token positions (-1 invalid)
    dst_pos: jax.Array,       # [B, P]
    block_size: int,
) -> jax.Array:
    """Copy KV rows between token positions (all layers), dropping invalid
    entries — the on-device page compaction after tree acceptance."""
    num_blocks = pool.shape[1]
    b, p = src_pos.shape

    def phys_slot(pos):
        valid = pos >= 0
        safe = jnp.maximum(pos, 0)
        logical = safe // block_size
        slot = safe % block_size
        phys = jnp.take_along_axis(block_tables, logical, axis=1)
        return jnp.where(valid, phys, num_blocks), slot, valid

    sphys, sslot, svalid = phys_slot(src_pos)
    dphys, dslot, dvalid = phys_slot(dst_pos)
    # gather first (read everything before any write); advanced indices on
    # dims 1 (page) and 3 (slot) are separated by slices, so the indexed
    # dims move FIRST: rows [B, P, L, Hkv, D]
    rows = pool[
        :, jnp.where(svalid, sphys, 0), :, jnp.where(svalid, sslot, 0)
    ]
    wphys = jnp.where(svalid & dvalid, dphys, num_blocks).reshape(-1)
    wslot = dslot.reshape(-1)
    # scatter values for .at[:, wphys, :, wslot] follow the same rule:
    # [T, L, Hkv, D]
    flat = rows.reshape(b * p, pool.shape[0], pool.shape[2], pool.shape[4])
    return pool.at[:, wphys, :, wslot].set(flat, mode="drop")


def _move_scale_rows(
    pool: jax.Array,          # [L, N, Bk, D] bf16 scale pool (int8 KV)
    block_tables: jax.Array,  # [B, M]
    src_pos: jax.Array,       # [B, P] token positions (-1 invalid)
    dst_pos: jax.Array,       # [B, P]
    block_size: int,
) -> jax.Array:
    """Scale-pool twin of :func:`_move_rows` (no head axis): int8 path
    compaction must move each code row's per-(page, token) scale with it
    or the copied page dequantizes with a stale scale."""
    num_blocks = pool.shape[1]
    b, p = src_pos.shape

    def phys_slot(pos):
        valid = pos >= 0
        safe = jnp.maximum(pos, 0)
        logical = safe // block_size
        slot = safe % block_size
        phys = jnp.take_along_axis(block_tables, logical, axis=1)
        return jnp.where(valid, phys, num_blocks), slot, valid

    sphys, sslot, svalid = phys_slot(src_pos)
    dphys, dslot, dvalid = phys_slot(dst_pos)
    # advanced indices on dims 1 (page) and 2 (slot) are adjacent here, so
    # the indexed dims stay IN PLACE: rows [L, B, P, D]
    rows = pool[
        :, jnp.where(svalid, sphys, 0), jnp.where(svalid, sslot, 0)
    ]
    wphys = jnp.where(svalid & dvalid, dphys, num_blocks).reshape(-1)
    wslot = dslot.reshape(-1)
    flat = rows.reshape(pool.shape[0], b * p, pool.shape[3])
    return pool.at[:, wphys, wslot].set(flat, mode="drop")
