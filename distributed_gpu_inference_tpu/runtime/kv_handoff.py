"""Real prefill→decode KV handoff: export a sequence's device KV pages,
move the bytes, and adopt them into another engine mid-generation.

The reference *simulates* this step — its KV migration body is a 50 ms sleep
(``server/app/services/pd_scheduler.py:462-472``) and its per-layer transfer
contract exists only as an unwired proto (``proto/inference.proto:110-135``).
Here the handoff is real:

- **Export**: gather the sequence's block chain out of the donor engine's HBM
  pools in ONE device gather (``kv["k"][:, block_ids]``), pull to host, and
  capture the exact generation state (committed kv_len, the pending sampled
  token whose KV is not yet written, generated tokens, sampling params).
- **Wire**: :func:`serialize_handoff` frames the pages with the same
  length-prefixed header + optional zstd used for all DCN/WAN tensor traffic
  (``utils/serialization.py``). Intra-slice PD pools skip this path entirely —
  prefill/decode partitions of one mesh exchange KV via device-to-device
  copies (`jax.device_put`) with no host serialization.
- **Adopt**: allocate a block chain in the recipient (prefix-cache aware — a
  shared system prompt already resident costs zero upload), stage page
  uploads through the manager's :class:`PendingDeviceOps`, and bind a slot
  with the exact pending-token state so the next ``decode_step`` continues
  the generation bit-for-bit.

Correctness invariant (tested): greedy decode continued on the recipient
produces the same tokens the donor would have produced.
"""

from __future__ import annotations

import io
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Dict, List, Optional

import numpy as np

from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
)
from distributed_gpu_inference_tpu.utils.serialization import (
    TensorSerializer,
    _pack_header,
    _unpack_header,
)

if TYPE_CHECKING:  # pragma: no cover
    from distributed_gpu_inference_tpu.runtime.engine import TPUEngine


@dataclass
class KVHandoff:
    """Everything needed to continue a generation on another engine."""

    request: InferenceRequest
    model_name: str
    block_size: int
    # token state
    token_ids: List[int]            # prompt + generated incl. pending token
    kv_len: int                     # committed positions (KV valid for [0, kv_len))
    pending_token: int              # sampled, KV not yet written
    prompt_len: int
    generated: List[int]
    # timing carried across so TTFT/E2E stay end-to-end truthful
    start_time: float
    first_token_time: Optional[float]
    # per-slot PRNG key: an UNSEEDED sampled generation keeps its exact
    # random stream across migration (seeded ones re-derive from the seed)
    slot_key: Optional[List[int]] = None
    # sliding-window models: leading logical blocks the donor already
    # released (their exported pages are pad-block garbage — the recipient
    # must skip uploading them and replicate the release state, or a
    # no-decode adopt could cache a garbage-prefixed chain; ADVICE r1 #1)
    window_front: int = 0
    # pages: [n_blocks, L, 2, n_kv_heads, block_size, head_dim] (head-major)
    pages: np.ndarray = field(repr=False, default=None)  # type: ignore[assignment]

    @property
    def num_blocks(self) -> int:
        return 0 if self.pages is None else int(self.pages.shape[0])

    @property
    def nbytes(self) -> int:
        return 0 if self.pages is None else int(self.pages.nbytes)


def export_slot_kv(engine: "TPUEngine", slot: int) -> KVHandoff:
    """Snapshot ``slot``'s sequence out of ``engine`` (slot stays live; callers
    that migrate should ``finish_slot(slot, cache=...)`` afterwards)."""
    import jax.numpy as jnp

    s = engine.slots[slot]
    if s is None:
        raise ValueError(f"slot {slot} empty")
    blocks = engine.manager.seq_blocks[s.seq_id]
    ids = jnp.asarray(np.asarray(blocks, np.int32))
    # one gather per pool, host pull in native dtype (the wire codec frames
    # bfloat16 directly — no f32 inflation, no f16 precision loss)
    k = np.asarray(engine.kv["k"][:, ids])
    v = np.asarray(engine.kv["v"][:, ids])
    # → [n, L, 2, Hkv, Bk, D] so adoption can upload per block
    pages = np.stack([k, v], axis=0).transpose(2, 1, 0, 3, 4, 5)
    tokens = list(engine.manager.seq_tokens[s.seq_id])
    return KVHandoff(
        request=s.request,
        model_name=engine.model_cfg.name,
        block_size=engine.cfg.block_size,
        token_ids=tokens,
        kv_len=int(engine._kv_lens[slot]),
        pending_token=int(engine._last_tokens[slot]),
        prompt_len=s.prompt_len,
        generated=list(s.generated),
        start_time=s.start_time,
        first_token_time=s.first_token_time,
        slot_key=[int(x) for x in engine._slot_keys[slot]],
        window_front=engine.manager.seq_window_front.get(s.seq_id, 0),
        pages=pages,
    )


def adopt_kv(engine: "TPUEngine", handoff: KVHandoff,
             slot: Optional[int] = None) -> int:
    """Materialize ``handoff`` into ``engine``: allocate blocks, stage page
    uploads, bind a slot. Returns the slot index; the next ``decode_step``
    resumes the generation."""
    from distributed_gpu_inference_tpu.runtime.engine import _Slot

    if engine.model_cfg.name != handoff.model_name:
        raise ValueError(
            f"model mismatch: engine={engine.model_cfg.name} "
            f"handoff={handoff.model_name}"
        )
    if engine.cfg.block_size != handoff.block_size:
        raise ValueError("block_size mismatch between engines")
    if slot is None:
        free = engine.free_slots()
        if not free:
            raise RuntimeError("no free slots")
        slot = free[0]
    if engine.slots[slot] is not None:
        raise RuntimeError(f"slot {slot} busy")

    req = handoff.request
    # validate capacity BEFORE touching allocator or pending-op state so a
    # rejected handoff can't leak blocks or leave stale uploads queued
    n_blocks = max(1, -(-len(handoff.token_ids) // engine.cfg.block_size))
    if n_blocks > engine.cfg.max_blocks_per_seq:
        raise ValueError(
            f"handoff needs {n_blocks} blocks > engine max_blocks_per_seq "
            f"{engine.cfg.max_blocks_per_seq}"
        )
    if len(handoff.token_ids) > engine.cfg.max_seq_len:
        raise ValueError("handoff sequence exceeds engine max_seq_len")
    # mirror submit()'s headroom check: the recipient must be able to FINISH
    # the generation, or the handoff would silently truncate with "length"
    remaining = req.sampling.max_new_tokens - len(handoff.generated)
    if handoff.kv_len + 1 + remaining > engine.cfg.max_seq_len:
        raise ValueError(
            f"handoff needs headroom for {remaining} more tokens at kv_len "
            f"{handoff.kv_len}, exceeding engine max_seq_len "
            f"{engine.cfg.max_seq_len}"
        )
    seq_id = f"{req.request_id}-pd"
    blocks, cached_tokens = engine.manager.allocate_sequence(
        seq_id, handoff.token_ids
    )
    staged: List[int] = []
    try:
        cached_blocks = cached_tokens // engine.cfg.block_size
        for i in range(cached_blocks, len(blocks)):
            if i < handoff.window_front:
                # donor released this block (sliding window): its exported
                # page is pad garbage — never upload it
                continue
            # pages[i] is [L, 2, Hkv, Bk, D] — the engine upload layout
            engine.manager.pending.uploads.append((blocks[i], handoff.pages[i]))
            staged.append(blocks[i])
        # replicate the donor's release state BEFORE binding so the slot's
        # block table starts with the released entries pinned to pad block 0
        # and free_sequence keeps the truncated chain out of the radix
        if handoff.window_front > 0:
            engine.manager.seed_window_front(seq_id, handoff.window_front)

        s = _Slot(
            request=req,
            seq_id=seq_id,
            prompt_len=handoff.prompt_len,
            generated=list(handoff.generated),
            cached_tokens=cached_tokens,
            start_time=handoff.start_time,
            first_token_time=handoff.first_token_time,
        )
        engine._bind_slot(slot, s, kv_len=handoff.kv_len)
        engine._last_tokens[slot] = handoff.pending_token
        if handoff.slot_key is not None:
            # restore the donor's random stream exactly (unseeded sampled
            # generations continue bit-for-bit too)
            engine._slot_keys[slot] = np.asarray(handoff.slot_key, np.uint32)
        engine._apply_pending()
    except Exception:
        engine.slots[slot] = None
        engine._kv_lens[slot] = 0
        # drop OUR staged uploads: after free_sequence those block ids return
        # to the free list and a later _apply_pending would write donor pages
        # over blocks that may belong to another live sequence
        if staged:
            drop = set(staged)
            engine.manager.pending.uploads = [
                (bid, page) for bid, page in engine.manager.pending.uploads
                if bid not in drop
            ]
        engine.manager.free_sequence(seq_id, cache=False)
        raise
    return slot


# ---------------------------------------------------------------------------
# Wire format (DCN / cross-host handoff)
# ---------------------------------------------------------------------------


def serialize_handoff(h: KVHandoff, compress: bool = True) -> bytes:
    """Frame a handoff for a DCN hop: pickled metadata + framed pages.

    Pages use the shared tensor wire format (header + optional zstd), and the
    metadata rides the same msgpack header codec — the wire stays
    pickle-free so a peer can never smuggle executable payloads
    (reference keeps lz4/zstd for WAN only — SURVEY §2.3; same stance here).
    """
    meta = {
        "request": {
            "request_id": h.request.request_id,
            "model": h.request.model,
            "prompt_token_ids": h.request.prompt_token_ids,
            "sampling": h.request.sampling.to_dict(),
            "priority": h.request.priority,
            "session_id": h.request.session_id,
        },
        "model_name": h.model_name,
        "block_size": h.block_size,
        "token_ids": h.token_ids,
        "kv_len": h.kv_len,
        "pending_token": h.pending_token,
        "prompt_len": h.prompt_len,
        "generated": h.generated,
        "start_time": h.start_time,
        "first_token_time": h.first_token_time,
        "slot_key": h.slot_key,
        "window_front": h.window_front,
    }
    buf = io.BytesIO()
    mb = _pack_header(meta)
    buf.write(len(mb).to_bytes(8, "little"))
    buf.write(mb)
    ser = TensorSerializer(compress=compress)
    pb = ser.serialize(h.pages)
    buf.write(len(pb).to_bytes(8, "little"))
    buf.write(pb)
    return buf.getvalue()


def deserialize_handoff(data: bytes) -> KVHandoff:
    view = memoryview(data)
    n = int.from_bytes(view[:8], "little")
    meta: Dict[str, Any] = _unpack_header(bytes(view[8 : 8 + n]))
    off = 8 + n
    pn = int.from_bytes(view[off : off + 8], "little")
    pages = TensorSerializer().deserialize(bytes(view[off + 8 : off + 8 + pn]))
    r = meta["request"]
    request = InferenceRequest(
        request_id=r["request_id"],
        model=r.get("model"),
        prompt_token_ids=r.get("prompt_token_ids"),
        sampling=SamplingParams.from_dict(r["sampling"]),
        priority=r.get("priority", 0),
        session_id=r.get("session_id"),
    )
    return KVHandoff(
        request=request,
        model_name=meta["model_name"],
        block_size=meta["block_size"],
        token_ids=meta["token_ids"],
        kv_len=meta["kv_len"],
        pending_token=meta["pending_token"],
        prompt_len=meta["prompt_len"],
        generated=meta["generated"],
        start_time=meta["start_time"],
        first_token_time=meta["first_token_time"],
        slot_key=meta.get("slot_key"),
        window_front=meta.get("window_front", 0),
        pages=pages,
    )
