"""Real prefill→decode KV handoff: export a sequence's device KV pages,
move the bytes, and adopt them into another engine mid-generation.

The reference *simulates* this step — its KV migration body is a 50 ms sleep
(``server/app/services/pd_scheduler.py:462-472``) and its per-layer transfer
contract exists only as an unwired proto (``proto/inference.proto:110-135``).
Here the handoff is real:

- **Export**: gather the sequence's block chain out of the donor engine's HBM
  pools in ONE device gather (``kv["k"][:, block_ids]``), pull to host, and
  capture the exact generation state (committed kv_len, the pending sampled
  token whose KV is not yet written, generated tokens, sampling params).
- **Wire**: :func:`serialize_handoff` frames the pages with the same
  length-prefixed header + optional zstd used for all DCN/WAN tensor traffic
  (``utils/serialization.py``). Intra-slice PD pools skip this path entirely —
  prefill/decode partitions of one mesh exchange KV via device-to-device
  copies (`jax.device_put`) with no host serialization.
- **Adopt**: allocate a block chain in the recipient (prefix-cache aware — a
  shared system prompt already resident costs zero upload), stage page
  uploads through the manager's :class:`PendingDeviceOps`, and bind a slot
  with the exact pending-token state so the next ``decode_step`` continues
  the generation bit-for-bit.

Correctness invariant (tested): greedy decode continued on the recipient
produces the same tokens the donor would have produced.
"""

from __future__ import annotations

import functools
import io
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributed_gpu_inference_tpu.testing import faults as _faults
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
)
from distributed_gpu_inference_tpu.utils.serialization import (
    TensorSerializer,
    _pack_header,
    _unpack_header,
)

if TYPE_CHECKING:  # pragma: no cover
    from distributed_gpu_inference_tpu.runtime.engine import TPUEngine


@dataclass
class KVHandoff:
    """Everything needed to continue a generation on another engine."""

    request: InferenceRequest
    model_name: str
    block_size: int
    # token state
    token_ids: List[int]            # prompt + generated incl. pending token
    kv_len: int                     # committed positions (KV valid for [0, kv_len))
    pending_token: int              # sampled, KV not yet written
    prompt_len: int
    generated: List[int]
    # timing carried across so TTFT/E2E stay end-to-end truthful
    start_time: float
    first_token_time: Optional[float]
    # per-slot PRNG key: an UNSEEDED sampled generation keeps its exact
    # random stream across migration (seeded ones re-derive from the seed)
    slot_key: Optional[List[int]] = None
    # sliding-window models: leading logical blocks the donor already
    # released (their exported pages are pad-block garbage — the recipient
    # must skip uploading them and replicate the release state, or a
    # no-decode adopt could cache a garbage-prefixed chain; ADVICE r1 #1)
    window_front: int = 0
    # donor finish state: a sequence whose FIRST sampled token hit a stop id
    # finishes with generated=[] and a stale last_token — the recipient must
    # not decode it (it would feed garbage for max_new_tokens)
    finish_reason: Optional[str] = None
    # int8-KV donors: per-(page, token) scale pages [n, L, 2, Bk, D] bf16
    # (k and v scales stacked on axis 2) — pages are raw int8 then, and the
    # recipient must be an int8 engine (real = int * scale end to end, so
    # continuation stays bit-exact with zero requantization)
    scale_pages: Optional[np.ndarray] = field(repr=False, default=None)
    # pages: [n_blocks, L, 2, n_kv_heads, block_size, head_dim] (head-major)
    pages: np.ndarray = field(repr=False, default=None)  # type: ignore[assignment]

    @property
    def num_blocks(self) -> int:
        return 0 if self.pages is None else int(self.pages.shape[0])

    @property
    def nbytes(self) -> int:
        return 0 if self.pages is None else int(self.pages.nbytes)


def export_slot_kv(engine: "TPUEngine", slot: int) -> KVHandoff:
    """Snapshot ``slot``'s sequence out of ``engine`` (slot stays live; callers
    that migrate should ``finish_slot(slot, cache=...)`` afterwards)."""
    import jax.numpy as jnp

    s = engine.slots[slot]
    if s is None:
        raise ValueError(f"slot {slot} empty")
    blocks = engine.manager.seq_blocks[s.seq_id]
    ids = jnp.asarray(np.asarray(blocks, np.int32))
    # one gather per pool, host pull in native dtype (the wire codec frames
    # bfloat16 directly — no f32 inflation, no f16 precision loss)
    k = np.asarray(engine.kv["k"][:, ids])
    v = np.asarray(engine.kv["v"][:, ids])
    # → [n, L, 2, Hkv, Bk, D] so adoption can upload per block
    pages = np.stack([k, v], axis=0).transpose(2, 1, 0, 3, 4, 5)
    scale_pages = None
    if "k_scale" in engine.kv:
        ks = np.asarray(engine.kv["k_scale"][:, ids])   # [L, n, Bk, D]
        vs = np.asarray(engine.kv["v_scale"][:, ids])
        scale_pages = np.stack([ks, vs], axis=0).transpose(2, 1, 0, 3, 4)
    tokens = list(engine.manager.seq_tokens[s.seq_id])
    return KVHandoff(
        request=s.request,
        model_name=engine.model_cfg.name,
        block_size=engine.cfg.block_size,
        token_ids=tokens,
        kv_len=int(engine._kv_lens[slot]),
        pending_token=int(engine._last_tokens[slot]),
        prompt_len=s.prompt_len,
        generated=list(s.generated),
        start_time=s.start_time,
        first_token_time=s.first_token_time,
        slot_key=[int(x) for x in engine._slot_keys[slot]],
        window_front=engine.manager.seq_window_front.get(s.seq_id, 0),
        finish_reason=s.finish_reason,
        pages=pages,
        scale_pages=scale_pages,
    )


def _validate_capacity(engine: "TPUEngine", n_tokens: int,
                       kv_len: int, remaining: int) -> None:
    """Reject a migration the recipient cannot hold or finish — BEFORE any
    allocator/device/wire work, so a rejected handoff can't leak state.
    Shared by all three migration paths (one-shot, streamed, device)."""
    n_blocks = max(1, -(-n_tokens // engine.cfg.block_size))
    if n_blocks > engine.cfg.max_blocks_per_seq:
        raise ValueError(
            f"handoff needs {n_blocks} blocks > engine max_blocks_per_seq "
            f"{engine.cfg.max_blocks_per_seq}"
        )
    if n_tokens > engine.cfg.max_seq_len:
        raise ValueError("handoff sequence exceeds engine max_seq_len")
    if kv_len + 1 + remaining > engine.cfg.max_seq_len:
        raise ValueError(
            f"handoff needs headroom for {remaining} more tokens at kv_len "
            f"{kv_len}, exceeding engine max_seq_len {engine.cfg.max_seq_len}"
        )


def _bind_migrated(engine: "TPUEngine", slot: int, *, request, seq_id: str,
                   prompt_len: int, generated, cached_tokens: int,
                   start_time: float, first_token_time, kv_len: int,
                   pending_token: int, slot_key, finish_reason) -> None:
    """Install a migrated sequence into ``slot``: the one bind sequence all
    three migration paths share (so pending-token, PRNG-stream, and
    finish-state semantics cannot drift between them). Caller owns
    allocator/session cleanup on failure."""
    from distributed_gpu_inference_tpu.runtime.engine import _Slot

    s = _Slot(
        request=request,
        seq_id=seq_id,
        prompt_len=prompt_len,
        generated=list(generated),
        cached_tokens=cached_tokens,
        start_time=start_time,
        first_token_time=first_token_time,
        # a donor that already finished (e.g. first token hit a stop id)
        # must stay finished: the recipient's decode loop skips the slot
        # and finish_slot reports the donor's reason
        finish_reason=finish_reason,
    )
    engine._bind_slot(slot, s, kv_len=kv_len)
    engine._last_tokens[slot] = int(pending_token)
    if slot_key is not None:
        engine._slot_keys[slot] = np.asarray(slot_key, np.uint32)
    engine._apply_pending()


def adopt_kv(engine: "TPUEngine", handoff: KVHandoff,
             slot: Optional[int] = None) -> int:
    """Materialize ``handoff`` into ``engine``: allocate blocks, stage page
    uploads, bind a slot. Returns the slot index; the next ``decode_step``
    resumes the generation."""
    if engine.model_cfg.name != handoff.model_name:
        raise ValueError(
            f"model mismatch: engine={engine.model_cfg.name} "
            f"handoff={handoff.model_name}"
        )
    if engine.cfg.block_size != handoff.block_size:
        raise ValueError("block_size mismatch between engines")
    if (handoff.scale_pages is not None) != ("k_scale" in engine.kv):
        raise ValueError(
            "kv_cache_dtype mismatch: an int8-KV handoff (raw int8 pages + "
            "scales) can only adopt into an int8-KV engine, and vice versa "
            "— re-serving through a different KV dtype would need a "
            "requantization pass this path does not do"
        )
    if slot is None:
        free = engine.free_slots()
        if not free:
            raise RuntimeError("no free slots")
        slot = free[0]
    if engine.slots[slot] is not None:
        raise RuntimeError(f"slot {slot} busy")

    req = handoff.request
    # validate capacity BEFORE touching allocator or pending-op state so a
    # rejected handoff can't leak blocks or leave stale uploads queued;
    # headroom mirrors submit(): the recipient must be able to FINISH the
    # generation, or the handoff would silently truncate with "length"
    _validate_capacity(
        engine, len(handoff.token_ids), handoff.kv_len,
        0 if handoff.finish_reason is not None else
        req.sampling.max_new_tokens - len(handoff.generated),
    )
    seq_id = f"{req.request_id}-pd"
    blocks, cached_tokens = engine.manager.allocate_sequence(
        seq_id, handoff.token_ids
    )
    staged: List[int] = []
    try:
        cached_blocks = cached_tokens // engine.cfg.block_size
        for i in range(cached_blocks, len(blocks)):
            if i < handoff.window_front:
                # donor released this block (sliding window): its exported
                # page is pad garbage — never upload it
                continue
            # pages[i] is [L, 2, Hkv, Bk, D] — the engine upload layout
            engine.manager.pending.uploads.append((blocks[i], handoff.pages[i]))
            if handoff.scale_pages is not None:
                engine.manager.pending.scale_uploads.append(
                    (blocks[i], handoff.scale_pages[i])
                )
            staged.append(blocks[i])
        # replicate the donor's release state BEFORE binding so the slot's
        # block table starts with the released entries pinned to pad block 0
        # and free_sequence keeps the truncated chain out of the radix
        if handoff.window_front > 0:
            engine.manager.seed_window_front(seq_id, handoff.window_front)

        _bind_migrated(
            engine, slot, request=req, seq_id=seq_id,
            prompt_len=handoff.prompt_len, generated=handoff.generated,
            cached_tokens=cached_tokens, start_time=handoff.start_time,
            first_token_time=handoff.first_token_time,
            kv_len=handoff.kv_len, pending_token=handoff.pending_token,
            slot_key=handoff.slot_key, finish_reason=handoff.finish_reason,
        )
    except Exception:
        engine.slots[slot] = None
        engine._kv_lens[slot] = 0
        # drop OUR staged uploads: after free_sequence those block ids return
        # to the free list and a later _apply_pending would write donor pages
        # over blocks that may belong to another live sequence
        if staged:
            drop = set(staged)
            engine.manager.pending.uploads = [
                (bid, page) for bid, page in engine.manager.pending.uploads
                if bid not in drop
            ]
            engine.manager.pending.scale_uploads = [
                (bid, page)
                for bid, page in engine.manager.pending.scale_uploads
                if bid not in drop
            ]
        engine.manager.free_sequence(seq_id, cache=False)
        raise
    return slot


# ---------------------------------------------------------------------------
# Wire format (DCN / cross-host handoff)
# ---------------------------------------------------------------------------


def _frame_blobs(*blobs: bytes) -> bytes:
    """THE 8-byte-little-endian length-prefixed multi-blob framing, shared
    by every handoff encoder (one-shot + streamed piece) so encoders and
    decoders cannot drift on offset arithmetic."""
    out = io.BytesIO()
    for b in blobs:
        out.write(len(b).to_bytes(8, "little"))
        out.write(b)
    return out.getvalue()


def _read_blobs(data: bytes, count: int) -> List[bytes]:
    view = memoryview(data)
    off, out = 0, []
    for _ in range(count):
        if off + 8 > len(view):
            raise ValueError(
                f"malformed handoff frame: truncated length prefix at "
                f"offset {off} (frame is {len(view)} bytes)"
            )
        n = int.from_bytes(view[off : off + 8], "little")
        if off + 8 + n > len(view):
            raise ValueError(
                f"malformed handoff frame: blob of {n} bytes at offset "
                f"{off} overruns the {len(view)}-byte frame"
            )
        out.append(bytes(view[off + 8 : off + 8 + n]))
        off += 8 + n
    return out


def serialize_handoff(h: KVHandoff, compress: bool = True) -> bytes:
    """Frame a handoff for a DCN hop: pickled metadata + framed pages.

    Pages use the shared tensor wire format (header + optional zstd), and the
    metadata rides the same msgpack header codec — the wire stays
    pickle-free so a peer can never smuggle executable payloads
    (reference keeps lz4/zstd for WAN only — SURVEY §2.3; same stance here).
    """
    meta = {
        "request": {
            "request_id": h.request.request_id,
            "model": h.request.model,
            "prompt_token_ids": h.request.prompt_token_ids,
            "sampling": h.request.sampling.to_dict(),
            "priority": h.request.priority,
            "session_id": h.request.session_id,
            # deadline crosses the PD boundary as an ABSOLUTE time (the
            # checkpoint-wire convention, runtime/engine.py): relative
            # deadline_s would silently re-anchor to the receiver's
            # arrival_time and hand a migrated job fresh slack. Omitted
            # (not null) when unset, so deadline-less wires are
            # byte-identical to the pre-deadline format.
            **({"deadline_at": h.request.deadline_at}
               if h.request.deadline_s is not None else {}),
        },
        "model_name": h.model_name,
        "block_size": h.block_size,
        "token_ids": h.token_ids,
        "kv_len": h.kv_len,
        "pending_token": h.pending_token,
        "prompt_len": h.prompt_len,
        "generated": h.generated,
        "start_time": h.start_time,
        "first_token_time": h.first_token_time,
        "slot_key": h.slot_key,
        "window_front": h.window_front,
        "finish_reason": h.finish_reason,
        "has_scales": h.scale_pages is not None,
    }
    ser = TensorSerializer(compress=compress)
    blobs = [_pack_header(meta), ser.serialize(h.pages)]
    if h.scale_pages is not None:
        blobs.append(ser.serialize(h.scale_pages))
    return _frame_blobs(*blobs)


# ---------------------------------------------------------------------------
# Device-path handoff: same-chip / same-slice engine pairs never touch host
# ---------------------------------------------------------------------------


def migrate_kv_device(src: "TPUEngine", dst: "TPUEngine", slot: int,
                      dst_slot: Optional[int] = None) -> int:
    """Move a live sequence between two engines whose KV pools share devices
    — pages move pool→pool in ONE jitted gather-scatter on the accelerator;
    only slot metadata (a few hundred bytes) rides the host.

    This is the intra-slice PD migration path: a DistServe-style deployment
    on one TPU slice runs prefill and decode pools in ONE process (BASELINE
    config 5 — prefill on 16 chips, decode on 48 of a v5e-64), so the
    handoff is an HBM/ICI copy, not a serialize→DCN→deserialize hop. On the
    tunneled bench chip the host path measures ~4 MB/s (the tunnel's D2H
    rate), i.e. ~12 s for a 512-token 3B sequence; this path is one device
    dispatch. The reference has no equivalent — its migration body is a
    50 ms sleep (``/root/reference/server/app/services/pd_scheduler.py:462``).

    The donor slot stays live (caller decides ``finish_slot`` semantics,
    matching :func:`export_slot_kv`).
    """
    import jax.numpy as jnp

    s = src.slots[slot]
    if s is None:
        raise ValueError(f"slot {slot} empty")
    if src.model_cfg.name != dst.model_cfg.name:
        raise ValueError("model mismatch between engines")
    if src.cfg.block_size != dst.cfg.block_size:
        raise ValueError("block_size mismatch between engines")
    if src.kv_dtype != dst.kv_dtype:
        raise ValueError("kv_cache_dtype mismatch between engines")
    # int8-KV pools migrate on every path: the jitted copy here moves scale
    # pools by key; the wire paths (one-shot + streamed) frame scale pages
    # alongside data pages. kv_dtype equality above guarantees both sides
    # agree on whether scales exist.
    src_devs = {d for leaf in (src.kv["k"],) for d in leaf.devices()}
    dst_devs = {d for leaf in (dst.kv["k"],) for d in leaf.devices()}
    if src_devs != dst_devs:
        raise ValueError(
            "migrate_kv_device needs engines sharing devices; use the "
            "host/wire path (export_slot_kv / StreamedExport) across hosts"
        )
    window_front = src.manager.seq_window_front.get(s.seq_id, 0)
    token_ids = list(src.manager.seq_tokens[s.seq_id])
    src_blocks = list(src.manager.seq_blocks[s.seq_id])

    if dst_slot is None:
        free = dst.free_slots()
        if not free:
            raise RuntimeError("no free slots")
        dst_slot = free[0]
    if dst.slots[dst_slot] is not None:
        raise RuntimeError(f"slot {dst_slot} busy")
    req = s.request
    kv_len = int(src._kv_lens[slot])
    # validate BEFORE the allocator and the device copy run; a finished
    # donor (first-token stop) needs no decode headroom
    _validate_capacity(
        dst, len(token_ids), kv_len,
        0 if s.finish_reason is not None else
        req.sampling.max_new_tokens - len(s.generated),
    )
    seq_id = f"{req.request_id}-pd"
    dst_blocks, cached_tokens = dst.manager.allocate_sequence(seq_id, token_ids)
    try:
        cached_blocks = cached_tokens // dst.cfg.block_size
        src_ids, dst_ids = [], []
        for i in range(len(dst_blocks)):
            if i < cached_blocks or i < window_front:
                continue    # resident via prefix cache / window-released
            if i < len(src_blocks):
                src_ids.append(src_blocks[i])
                dst_ids.append(dst_blocks[i])
        if src_ids:
            # recipient's own pending ops (CoW from allocate) must land
            # before we overwrite pages
            dst._apply_pending()
            dst.kv = _device_copy_pages(
                src.kv, dst.kv,
                jnp.asarray(np.asarray(src_ids, np.int32)),
                jnp.asarray(np.asarray(dst_ids, np.int32)),
            )
        if window_front > 0:
            dst.manager.seed_window_front(seq_id, window_front)
        _bind_migrated(
            dst, dst_slot, request=req, seq_id=seq_id,
            prompt_len=s.prompt_len, generated=s.generated,
            cached_tokens=cached_tokens, start_time=s.start_time,
            first_token_time=s.first_token_time, kv_len=kv_len,
            pending_token=int(src._last_tokens[slot]),
            slot_key=src._slot_keys[slot],
            finish_reason=s.finish_reason,
        )
    except Exception:
        dst.slots[dst_slot] = None
        dst._kv_lens[dst_slot] = 0
        dst.manager.free_sequence(seq_id, cache=False)
        raise
    return dst_slot


@functools.lru_cache(maxsize=8)
def _device_copy_fn(keys: Tuple[str, ...]):
    import jax

    def copy(src_kv, dst_kv, src_ids, dst_ids):
        return {
            k: dst_kv[k].at[:, dst_ids].set(src_kv[k][:, src_ids])
            for k in keys
        }

    # donate the destination pools: the copy mutates them in place
    return jax.jit(copy, donate_argnums=(1,))


def _device_copy_pages(src_kv, dst_kv, src_ids, dst_ids):
    # every pool entry with a block axis migrates — incl. int8 scale pools
    keys = tuple(sorted(src_kv.keys()))
    return _device_copy_fn(keys)(
        {k: src_kv[k] for k in keys}, {k: dst_kv[k] for k in keys},
        src_ids, dst_ids,
    )


# ---------------------------------------------------------------------------
# Streamed handoff (VERDICT r3 #3): chunk the export per page range and
# overlap the push with remaining prefill compute
# ---------------------------------------------------------------------------
#
# The round-3 handoff was whole-sequence, post-prefill, blocking: the donor
# finished the prompt, gathered EVERY page, pulled ~67 MB (512-token 8B bf16)
# to the host, and POSTed one blob — migration_ms landed entirely on the
# decode stage's start. The streamed protocol splits the handoff into three
# message kinds on the same ``/kv/transfer`` socket (magic-discriminated, so
# legacy one-shot blobs keep working):
#
# - ``begin``  — sent at prefill START: prompt tokens + sampling + framing.
#   The receiver allocates the block chain (prefix-cache aware) while the
#   donor is still computing.
# - ``piece``  — a block range's pages, sent as soon as those positions'
#   KV is final. During CHUNKED prefill, chunk i's pages cross the wire
#   while chunk i+1 computes: the page gather is dispatched right after
#   chunk i+1's prefill dispatch, so in-order device execution completes it
#   at ~chunk i's end while the host is free to pull/serialize/POST
#   (the same async-dispatch pattern as sub-wave admission staggering).
# - ``commit`` — after the first token samples: kv_len, pending token,
#   PRNG key, timing. The receiver binds the slot; the next decode_step
#   continues the generation bit-for-bit (same invariant + test as the
#   one-shot path).
#
# Sliding-window models fall back to the one-shot path (window release
# during admission would stream pages the commit then discards).
#
# Ref parity anchor: the per-layer KV messages the reference defines but
# never wires (/root/reference/proto/inference.proto:121-127) — here the
# streamed contract is page-range-framed and actually drives serving.

_STREAM_MAGIC = b"TPUS"
_KIND_BEGIN, _KIND_PIECE, _KIND_COMMIT, _KIND_ABORT = 0, 1, 2, 3


def is_stream_message(data: bytes) -> bool:
    return data[:4] == _STREAM_MAGIC


def message_kind(data: bytes) -> str:
    """Human name of a handoff wire message's kind — fault-rule ``match``
    context for the sender's push seam (rules can target, say, only
    ``commit`` frames) and log labelling. One-shot blobs are ``blob``."""
    if len(data) < 6 or not is_stream_message(data):
        return "blob"
    return {_KIND_BEGIN: "begin", _KIND_PIECE: "piece",
            _KIND_COMMIT: "commit", _KIND_ABORT: "abort"}.get(
                data[5], "unknown")


def _pack_stream(kind: int, meta: Dict[str, Any],
                 payload: bytes = b"") -> bytes:
    mb = _pack_header(meta)
    return b"".join([
        _STREAM_MAGIC, bytes([1, kind]), len(mb).to_bytes(4, "little"), mb,
        payload,
    ])


def _unpack_stream(data: bytes) -> Tuple[int, Dict[str, Any], bytes]:
    if data[:4] != _STREAM_MAGIC:
        raise ValueError("not a streamed handoff message")
    if len(data) < 10:
        raise ValueError(
            f"malformed handoff frame: {len(data)}-byte message is shorter "
            "than the 10-byte stream header"
        )
    if data[4] != 1:
        raise ValueError(f"unsupported stream version {data[4]}")
    kind = data[5]
    n = int.from_bytes(data[6:10], "little")
    if n == 0 or 10 + n > len(data):
        raise ValueError(
            f"malformed handoff frame: {n}-byte stream header overruns "
            f"the {len(data)}-byte message"
        )
    meta = _unpack_header(bytes(data[10:10 + n]))
    return kind, meta, bytes(data[10 + n:])


class StreamedExport:
    """Donor-side driver: runs a request's (chunked) prefill on ``engine``
    and generates the streamed handoff messages.

    Usage::

        exp = StreamedExport(engine, request, key)
        for msg in exp.messages():
            send(msg)                  # POST to the receiver, in order
        exp.first_token, exp.ttft_ms   # set once messages() is exhausted

    ``messages()`` interleaves page export with prefill compute: each loop
    iteration dispatches the next prefill chunk, dispatches the page gather
    for the blocks the PREVIOUS chunk completed, and only then yields the
    previous piece (whose device work already finished) — the host
    serialize/POST happens while the device runs the current chunk. The
    donor slot is freed when the generator completes (or aborts).
    """

    def __init__(self, engine: "TPUEngine", request: InferenceRequest,
                 key: str, piece_blocks: int = 4,
                 compress: bool = False) -> None:
        if engine.model_cfg.sliding_window is not None:
            raise ValueError(
                "streamed handoff does not support sliding-window models "
                "(use the one-shot path)"
            )
        # kv_seq_sharded donors stream fine since round 4: chunked prefill
        # composes with sharded pools, and the page gather collects shards
        # through GSPMD before the host pull. int8-KV donors stream their
        # scale pages inside each piece (receiver must be int8 too).
        self._quant = "k_scale" in engine.kv
        self.engine = engine
        self.request = request
        self.key = key
        self.piece_blocks = max(1, piece_blocks)
        self.compress = compress
        # results (set when messages() completes)
        self.first_token: Optional[int] = None
        self.ttft_ms: Optional[float] = None
        self.prompt_tokens: int = 0
        self.bytes_sent: int = 0
        self.pieces_sent: int = 0
        # bytes that crossed the wire BEFORE prefill finished (overlap proof)
        self.bytes_before_first_token: int = 0

    # -- message builders ----------------------------------------------------

    def _begin_msg(self) -> bytes:
        req = self.request
        return _pack_stream(_KIND_BEGIN, {
            "key": self.key,
            "model_name": self.engine.model_cfg.name,
            "block_size": self.engine.cfg.block_size,
            "int8_kv": self._quant,
            "request": {
                "request_id": req.request_id,
                "model": req.model,
                "prompt_token_ids": req.prompt_token_ids,
                "sampling": req.sampling.to_dict(),
                "priority": req.priority,
                "session_id": req.session_id,
                # same absolute-deadline convention as serialize_handoff
                **({"deadline_at": req.deadline_at}
                   if req.deadline_s is not None else {}),
            },
        })

    def _piece_msg(self, block_lo: int, k, v, ks=None, vs=None) -> bytes:
        # k/v: device gathers [L, n, Hkv, Bk, D]; pull + relayout host-side
        # to the adopt upload layout [n, L, 2, Hkv, Bk, D]
        pages = np.stack([np.asarray(k), np.asarray(v)], axis=0)
        pages = pages.transpose(2, 1, 0, 3, 4, 5)
        ser = TensorSerializer(compress=self.compress)
        pb = ser.serialize(pages)
        if ks is None:
            return _pack_stream(
                _KIND_PIECE, {"key": self.key, "block_lo": block_lo}, pb
            )
        scales = np.stack([np.asarray(ks), np.asarray(vs)], axis=0)
        scales = scales.transpose(2, 1, 0, 3, 4)     # [n, L, 2, Bk, D]
        payload = _frame_blobs(pb, ser.serialize(scales))
        return _pack_stream(
            _KIND_PIECE,
            {"key": self.key, "block_lo": block_lo, "has_scales": True},
            payload,
        )

    def _gather(self, blocks: List[int]):
        import jax.numpy as jnp

        ids = jnp.asarray(np.asarray(blocks, np.int32))
        out = (self.engine.kv["k"][:, ids], self.engine.kv["v"][:, ids])
        if self._quant:
            out += (self.engine.kv["k_scale"][:, ids],
                    self.engine.kv["v_scale"][:, ids])
        return out

    # -- the driver ----------------------------------------------------------

    def messages(self):
        eng = self.engine
        bs = eng.cfg.block_size
        adm = eng.submit_chunked_start(self.request)
        slot = adm.slot
        try:
            yield self._begin_msg()
            chain = eng.manager.seq_blocks[adm.seq_id]
            sent = 0                    # blocks exported so far
            pending: Optional[Tuple] = None  # (block_lo, *gathers)
            # donor-side prefix-cache hits are final before any chunk runs
            while not adm.done:
                eng.submit_chunked_step(adm)    # dispatch chunk (async
                # unless last — the final chunk samples + syncs in-graph)
                full = adm.off // bs
                if pending is not None:
                    msg = self._piece_msg(pending[0], *pending[1:])
                    if self.first_token is None:
                        self.bytes_before_first_token += len(msg)
                    self.bytes_sent += len(msg)
                    self.pieces_sent += 1
                    yield msg
                    pending = None
                if full > sent:
                    hi = min(full, sent + self.piece_blocks)
                    pending = (sent, *self._gather(chain[sent:hi]))
                    sent = hi
            # prefill finished: record results, then flush the tail —
            # everything left is pure export latency (the part streaming
            # exists to shrink)
            s = eng.slots[slot]
            self.first_token = int(eng._last_tokens[slot])
            self.prompt_tokens = s.prompt_len
            self.ttft_ms = (
                (s.first_token_time - s.start_time) * 1000.0
                if s.first_token_time else None
            )
            if pending is not None:
                msg = self._piece_msg(pending[0], *pending[1:])
                self.bytes_sent += len(msg)
                self.pieces_sent += 1
                yield msg
                pending = None
            # the pending token's append may have grown the chain by one
            # block (its page is uncommitted garbage the receiver never
            # reads: kv_len marks validity — same as the one-shot path)
            chain = eng.manager.seq_blocks[adm.seq_id]
            while sent < len(chain):
                hi = min(len(chain), sent + self.piece_blocks)
                msg = self._piece_msg(sent, *self._gather(chain[sent:hi]))
                self.bytes_sent += len(msg)
                self.pieces_sent += 1
                yield msg
                sent = hi
            commit = _pack_stream(_KIND_COMMIT, {
                "key": self.key,
                "token_ids": list(eng.manager.seq_tokens[adm.seq_id]),
                "kv_len": int(eng._kv_lens[slot]),
                "pending_token": int(eng._last_tokens[slot]),
                "prompt_len": s.prompt_len,
                "generated": list(s.generated),
                "start_time": s.start_time,
                "first_token_time": s.first_token_time,
                "slot_key": [int(x) for x in eng._slot_keys[slot]],
                "finish_reason": s.finish_reason,
            })
            self.bytes_sent += len(commit)
            yield commit
        except BaseException:
            # free the donor slot on ANY exit — including the consumer
            # closing the generator early (failed POST). The transport layer
            # owns telling the receiver (abort_message(key)); a generator
            # cannot yield during GeneratorExit.
            if not adm.done:
                eng.abort_chunked(adm)
            elif eng.slots[slot] is not None:
                eng.finish_slot(slot, cache=False)
            raise
        else:
            eng.finish_slot(slot, cache=False)


def abort_message(key: str) -> bytes:
    """Tell a receiver to drop a streamed-handoff session (donor failed)."""
    return _pack_stream(_KIND_ABORT, {"key": key})


# ---------------------------------------------------------------------------
# Cluster-wide KV migration (round 13): prefix-only transfers over the SAME
# begin/piece/commit protocol.
#
# A cold worker that was routed a request whose prefix is hot on a peer can
# PULL the peer's cached KV instead of re-prefilling: it POSTs an export
# request to the peer's ``/kv/export`` data-plane endpoint, and the peer
# answers with a framed sequence of the chaos-hardened streamed-handoff
# messages — one ``begin`` (``prefix_only`` marked, carrying the exact
# prefix token ids), the ``piece`` frames, and one ``commit``. The puller
# feeds each frame through its own :class:`HandoffReceiver`, so duplicate
# tolerance, corrupt-piece session aborts, staged-coverage commit checks,
# and the TTL/progress purge machinery all apply unchanged. A prefix-only
# commit binds NO slot: it releases the staged sequence with
# ``free_sequence(cache=True)``, landing the pulled blocks in the radix
# prefix index — the very next admission of the real request hits L1 and
# skips the re-prefill.
#
# The export side sources blocks from EVERY tier: device-resident radix
# blocks come out in one pool gather (the ``export_slot_kv`` pattern), and
# blocks past the L1 run are probed out of the spill tiers
# (``_probe_spill`` — host RAM, then the remote store), which is what
# promotes the per-worker spill tiers into a cluster-servable cache.
# ---------------------------------------------------------------------------

EXPORT_REQUEST_VERSION = 1


def pack_export_request(*, key: str, token_ids: Sequence[int], model_name: str,
                        block_size: int, int8_kv: bool,
                        max_blocks: int = 64,
                        start_block: int = 0,
                        fp: Optional[str] = None) -> bytes:
    """Wire form of a ``/kv/export`` pull request (msgpack header codec —
    the same pickle-free framing as every other handoff message).
    ``start_block``: leading full blocks the puller ALREADY holds — the
    exporter ships pieces from there, so a partially-warm puller never
    re-transfers (and the peer never re-gathers) the overlap.
    ``fp`` (round 20, proactive replication): a text-space prefix
    fingerprint in place of token ids — a plane-hinted puller has never
    seen the prompt, so the WARM exporter resolves the fingerprint back
    to the token ids its radix is keyed by (miss → empty response, an
    honest "nothing cached"). ``token_ids`` may be empty when ``fp`` is
    given; the version stays 1 because old exporters simply see an
    empty-token request and answer with an empty body."""
    return _pack_header({
        "v": EXPORT_REQUEST_VERSION,
        "key": key,
        "token_ids": [int(t) for t in token_ids],
        "model_name": model_name,
        "block_size": int(block_size),
        "int8_kv": bool(int8_kv),
        "max_blocks": int(max_blocks),
        "start_block": max(0, int(start_block)),
        **({"fp": str(fp)} if fp else {}),
    })


def unpack_export_request(raw: bytes) -> Dict[str, Any]:
    req = _unpack_header(raw)
    if int(req.get("v") or 0) != EXPORT_REQUEST_VERSION:
        raise ValueError(
            f"unsupported kv export request version {req.get('v')!r}"
        )
    return req


def split_frames(data: bytes) -> List[bytes]:
    """Split a ``/kv/export`` response body back into its stream messages
    (the body is ``_frame_blobs(*frames)``; an empty body = no match).
    Raises on truncation — a peer dying mid-response must surface as a
    failed pull, never as a silently shorter prefix."""
    view = memoryview(data)
    off, out = 0, []
    while off < len(view):
        if off + 8 > len(view):
            raise ValueError(
                f"truncated kv export response: length prefix cut at "
                f"offset {off} of {len(view)} bytes"
            )
        n = int.from_bytes(view[off:off + 8], "little")
        if off + 8 + n > len(view):
            raise ValueError(
                f"truncated kv export response: {n}-byte frame at offset "
                f"{off} overruns the {len(view)}-byte body"
            )
        out.append(bytes(view[off + 8:off + 8 + n]))
        off += 8 + n
    return out


def export_prefix_frames(engine: "TPUEngine", token_ids: Sequence[int],
                         key: str, *, piece_blocks: int = 4,
                         max_blocks: int = 64, start_block: int = 0,
                         compress: bool = False) -> Tuple[List[bytes], Dict[str, int]]:
    """Build the prefix-only begin/piece/commit frames for the longest
    locally-cached full-block prefix of ``token_ids``.

    ``start_block``: leading full blocks the PULLER already holds — only
    blocks ``[start_block, n)`` are gathered and shipped (the receiver's
    own cached blocks satisfy the commit coverage check for the rest), so
    a partially-warm puller costs transfer proportional to what it is
    actually missing.

    Returns ``(frames, info)`` where ``info`` counts the shipped blocks by
    tier (``dev_blocks`` from the device radix, ``spill_blocks`` restored
    from the host/remote spill tiers). ``frames`` is empty when the peer
    has nothing beyond ``start_block`` — the caller answers "no match" and
    the puller recomputes.

    Must run serialized with the engine (the caller holds the engine lock /
    executor): the gather reads live pool pages and the spill probe mutates
    LRU state.
    """
    import jax.numpy as jnp

    from distributed_gpu_inference_tpu.utils.data_structures import (
        compute_prefix_hash,
    )

    mgr = engine.manager
    bs = engine.cfg.block_size
    empty = {"dev_blocks": 0, "spill_blocks": 0}
    token_ids = [int(t) for t in token_ids]
    start = max(0, int(start_block))
    n_full = min(len(token_ids) // bs, max(0, int(max_blocks)))
    if n_full <= start or not mgr.enable_prefix_cache:
        return [], empty
    prefix = token_ids[: n_full * bs]
    cached = mgr.radix.match_prefix(prefix)[:n_full]
    quant = "k_scale" in engine.kv

    ship_dev = cached[start:]       # device blocks actually shipped
    dev_pages = dev_scales = None
    if ship_dev:
        # pad the gather to a bucketed width (block 0 is the reserved pad
        # block) so XLA compiles O(max_blocks / bucket) gather shapes, not
        # one per distinct prefix depth — export latency must not eat a
        # fresh compile on every new depth
        bucket = 4
        padded = list(ship_dev) + [0] * (-len(ship_dev) % bucket)
        ids = jnp.asarray(np.asarray(padded, np.int32))
        k = np.asarray(engine.kv["k"][:, ids])[:, : len(ship_dev)]
        v = np.asarray(engine.kv["v"][:, ids])[:, : len(ship_dev)]
        # → [n, L, 2, Hkv, Bk, D]: the adopt/spill upload layout
        dev_pages = np.stack([k, v], axis=0).transpose(2, 1, 0, 3, 4, 5)
        if quant:
            ks = np.asarray(
                engine.kv["k_scale"][:, ids]
            )[:, : len(ship_dev)]
            vs = np.asarray(
                engine.kv["v_scale"][:, ids]
            )[:, : len(ship_dev)]
            dev_scales = np.stack([ks, vs], axis=0).transpose(2, 1, 0, 3, 4)

    # past the device-resident run: the spill tiers are part of the
    # cluster cache — a block evicted to host RAM or the remote store is
    # still servable to a peer (validated for dtype/scale by the probe).
    # Probe hits are NOT the exporter's own serving traffic: restore the
    # l2/l3 hit counters so peer demand never skews this worker's cache
    # panels (promote-on-hit is kept — repeated pulls of the same remote-
    # tier prefix should get cheaper, and the L2 is a bounded LRU).
    spill: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
    spill_lo = max(len(cached), start)
    idx = spill_lo
    st = mgr.stats
    l2_before, l3_before = st.l2_hits, st.l3_hits
    try:
        while idx < n_full:
            hit = mgr._probe_spill(
                compute_prefix_hash(prefix, (idx + 1) * bs)
            )
            if hit is None:
                break
            spill.append(hit)
            idx += 1
    finally:
        st.l2_hits, st.l3_hits = l2_before, l3_before
    n = idx
    if n <= start:
        return [], empty

    def _block(i: int) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        if i < len(cached):
            j = i - start
            return dev_pages[j], (dev_scales[j] if quant else None)
        page, scale = spill[i - spill_lo]
        return page, scale

    frames = [_pack_stream(_KIND_BEGIN, {
        "key": key,
        "model_name": engine.model_cfg.name,
        "block_size": bs,
        "int8_kv": quant,
        "prefix_only": True,
        "token_ids": prefix[: n * bs],
    })]
    ser = TensorSerializer(compress=compress)
    pb_step = max(1, int(piece_blocks))
    for lo in range(start, n, pb_step):
        hi = min(n, lo + pb_step)
        pages = np.stack([_block(i)[0] for i in range(lo, hi)], axis=0)
        pb = ser.serialize(pages)
        if quant:
            scales = np.stack(
                [_block(i)[1] for i in range(lo, hi)], axis=0
            )
            frames.append(_pack_stream(
                _KIND_PIECE,
                {"key": key, "block_lo": lo, "has_scales": True},
                _frame_blobs(pb, ser.serialize(scales)),
            ))
        else:
            frames.append(_pack_stream(
                _KIND_PIECE, {"key": key, "block_lo": lo}, pb
            ))
    frames.append(_pack_stream(_KIND_COMMIT, {
        "key": key, "prefix_only": True, "kv_len": n * bs,
    }))
    return frames, {"dev_blocks": len(ship_dev),
                    "spill_blocks": len(spill)}


@dataclass
class _AdoptSession:
    seq_id: str
    request: InferenceRequest
    block_size: int
    blocks: List[int]
    cached_tokens: int
    prompt_len: int
    # cluster-KV migration: a prefix-only session transfers CACHED prefix
    # blocks with no live generation attached — its commit releases the
    # chain into the radix prefix index instead of binding a slot
    prefix_only: bool = False
    staged: List[int] = field(default_factory=list)
    # last-activity time, refreshed on every piece: a long streamed
    # migration (multi-GB KV at the documented ~4 MB/s tunnel D2H rate)
    # must not be purged mid-stream by its own later messages — only
    # sessions with no traffic for SESSION_TTL_S are stale.
    last_activity: float = field(default_factory=time.monotonic)
    # refreshed only when a piece stages a NOT-previously-staged block:
    # legitimate migrations of any size keep making block progress (total
    # refreshes bounded by the block count), while a trickler re-sending
    # the same block forever stalls this clock and hits the backstop
    last_progress: float = field(default_factory=time.monotonic)


class HandoffReceiver:
    """Recipient-side session machine for streamed handoffs.

    One instance per engine; ``handle(raw)`` dispatches begin/piece/commit/
    abort messages AND legacy one-shot blobs (``adopt_kv`` path), so a data
    plane needs exactly one receiver callable. The caller provides the
    engine lock (the worker's job path and the data-plane thread share it).
    """

    SESSION_TTL_S = 180.0
    # no-progress backstop: a donor that keeps the session warm (pieces
    # every <TTL) without ever staging a new block must not pin its
    # allocated KV blocks forever. Progress-based, not a hard lifetime cap:
    # a legitimate migration of ANY size stages new blocks as it goes (at
    # the documented ~4 MB/s tunnel rate even a 2 MB block lands well
    # inside this window), so only stalled/adversarial streams hit it.
    SESSION_MAX_NO_PROGRESS_S = 10 * 180.0
    # adopt-session count cap, enforced at ``_begin``: a flood of begins
    # (crashed donors that never send their abort, or a buggy peer
    # re-opening sessions) must not pin unbounded KV blocks while each
    # waits out its TTL — past the cap the stalest session is evicted to
    # make room. Sized well above any sane concurrent-migration fan-in.
    MAX_SESSIONS = 32

    # commit-replay memo size: a retried commit whose first delivery's ACK
    # was lost must answer idempotently (the slot is already bound — a
    # "no session" error would fail a handoff that actually LANDED), so
    # recent commits are remembered by key
    MAX_COMMIT_MEMO = 32

    def __init__(self, engine: "TPUEngine") -> None:
        self.engine = engine
        self._sessions: Dict[str, _AdoptSession] = {}
        # recently committed keys → the result dict their commit returned
        # (insertion-ordered; oldest evicted past MAX_COMMIT_MEMO)
        self._recent_commits: Dict[str, Dict[str, Any]] = {}
        # sessions_purged: abandoned migrations reclaimed (TTL, no-progress
        # backstop, or count-cap eviction) — exported via worker heartbeats
        # as kv_handoff_sessions_purged_total so they are VISIBLE, not just
        # silently garbage-collected. The per-reason counters break the
        # total down (chaos suites assert each recovery path is COUNTED,
        # not silently absorbed); "rx_aborts" counts sender-requested
        # aborts, "commits" successful bindings.
        self.stats: Dict[str, int] = {
            "sessions_purged": 0,
            "purged_ttl": 0,
            "purged_no_progress": 0,
            "purged_cap": 0,
            "rx_aborts": 0,
            "commits": 0,
            "prefix_commits": 0,
            "begin_duplicates": 0,
            "commit_replays": 0,
        }
        # flight recorder: receiver-side begin/commit/abort instants keyed
        # by session key. The receiver knows only the kv_cache_key — the
        # decode stage that later claims the adoption pops these into the
        # request's Timeline (``pop_flight``). Bounded: oldest keys evict
        # past the cap, duplicate begins/commit replays don't double-note.
        self._flight: Dict[str, List[Tuple[str, float]]] = {}
        self.FLIGHT_KEY_CAP = 64

    def _flight_note(self, key: str, name: str) -> None:
        if not key:
            return
        evs = self._flight.get(key)
        if evs is None:
            while len(self._flight) >= self.FLIGHT_KEY_CAP:
                self._flight.pop(next(iter(self._flight)))
            evs = self._flight[key] = []
        if len(evs) < 8:
            evs.append((name, time.time()))

    def pop_flight(self, key: str) -> List[Tuple[str, float]]:
        """Drain the receiver-side flight events for one session key —
        ``[(event, wall_ts), ...]`` — for adoption into the claiming
        request's Timeline. Empty when nothing was recorded."""
        return self._flight.pop(key, [])

    def handle(self, raw: bytes) -> Dict[str, Any]:
        # chaos seam: an installed FaultPlan can truncate or lose this
        # message in transit (no-op passthrough otherwise)
        raw = _faults.mutate_bytes("kv.receiver.message", raw)
        self._purge_stale()
        if not is_stream_message(raw):
            handoff = deserialize_handoff(raw)
            key = handoff.request.session_id or handoff.request.request_id
            slot = adopt_kv(self.engine, handoff)
            return {"slot": slot, "bytes_received": len(raw),
                    "kv_cache_key": key, "streamed": False}
        kind, meta, payload = _unpack_stream(raw)
        if kind == _KIND_BEGIN:
            return self._begin(meta)
        if kind == _KIND_PIECE:
            try:
                return self._piece(meta, payload, len(raw))
            except Exception:
                # a malformed/truncated piece poisons the whole stream (its
                # block range can never be staged, so the commit could only
                # bind garbage): abort the session NOW so its blocks free
                # immediately instead of pinning KV until the TTL purge
                self._drop(str(meta.get("key", "")))
                raise
        if kind == _KIND_COMMIT:
            return self._commit(meta)
        if kind == _KIND_ABORT:
            return self._abort(meta)
        raise ValueError(f"unknown stream message kind {kind}")

    # -- session steps -------------------------------------------------------

    def _begin(self, meta: Dict[str, Any]) -> Dict[str, Any]:
        eng = self.engine
        if eng.model_cfg.name != meta["model_name"]:
            raise ValueError(
                f"model mismatch: engine={eng.model_cfg.name} "
                f"handoff={meta['model_name']}"
            )
        if eng.cfg.block_size != meta["block_size"]:
            raise ValueError("block_size mismatch between engines")
        if bool(meta.get("int8_kv")) != ("k_scale" in eng.kv):
            raise ValueError(
                "kv_cache_dtype mismatch: int8-KV donors stream raw int8 "
                "pages + scales and can only land in int8-KV engines "
                "(and vice versa)"
            )
        key = meta["key"]
        prefix_only = bool(meta.get("prefix_only"))
        existing = self._sessions.get(key)
        if existing is not None:
            rid = (meta.get("request") or {}).get("request_id")
            if prefix_only:
                # prefix-only sessions carry no request; the key itself is
                # the idempotency token (pullers mint a fresh key per pull)
                rid = f"kvmig-{key}"
            if existing.request.request_id == rid:
                # duplicate delivery (sender retried a begin whose ACK was
                # lost): the session is already open for the SAME request —
                # answer idempotently so the retry ladder composes with the
                # streamed protocol instead of poisoning it
                self.stats["begin_duplicates"] = (
                    self.stats.get("begin_duplicates", 0) + 1
                )
                return {"kv_cache_key": key, "state": "begun",
                        "cached_tokens": existing.cached_tokens,
                        "duplicate": True}
            raise ValueError(
                f"streamed handoff {key!r} already begun by another request"
            )
        # purge on ADOPT-SESSION pressure too, not only on message arrival:
        # age out stale sessions first, then — if a begin flood still has
        # the table at the cap — evict the stalest session so abandoned
        # migrations can never pin the pool against live ones
        self._purge_stale()
        while len(self._sessions) >= self.MAX_SESSIONS:
            stalest = min(self._sessions,
                          key=lambda k: self._sessions[k].last_activity)
            self._drop(stalest)
            self.stats["sessions_purged"] = (
                self.stats.get("sessions_purged", 0) + 1
            )
            self.stats["purged_cap"] = self.stats.get("purged_cap", 0) + 1
        if prefix_only:
            toks = [int(t) for t in (meta.get("token_ids") or [])]
            bs = int(meta["block_size"])
            if not toks or len(toks) % bs != 0:
                raise ValueError(
                    "prefix-only handoff needs a whole-block token_ids "
                    f"prefix (got {len(toks)} tokens, block size {bs})"
                )
            if len(toks) // bs > eng.cfg.max_blocks_per_seq or \
                    len(toks) > eng.cfg.max_seq_len:
                raise ValueError(
                    "prefix-only handoff exceeds engine sequence bounds"
                )
            request = InferenceRequest(
                request_id=f"kvmig-{key}",
                prompt_token_ids=toks,
                sampling=SamplingParams(max_new_tokens=1),
            )
            seq_id = f"{key}-kvmig"
            # the transfer is NOT a serving request: allocate_sequence
            # would book the pulled prefix as one giant cache miss and
            # skew every hit-rate panel/bench — restore the query stats
            # (block/eviction accounting stays; kv_migrate counters own
            # the transfer's own observability)
            st = eng.manager.stats
            before = (st.prefix_queries, st.prefix_hit_tokens,
                      st.prefix_total_tokens, st.misses, st.l1_hits)
            try:
                blocks, cached_tokens = eng.manager.allocate_sequence(
                    seq_id, toks
                )
            finally:
                # restore on the failure path too (pool pressure raises
                # AFTER the query stats were bumped)
                (st.prefix_queries, st.prefix_hit_tokens,
                 st.prefix_total_tokens, st.misses, st.l1_hits) = before
            self._sessions[key] = _AdoptSession(
                seq_id=seq_id, request=request, block_size=bs,
                blocks=list(blocks), cached_tokens=cached_tokens,
                prompt_len=len(toks), prefix_only=True,
            )
            return {"kv_cache_key": key, "state": "begun",
                    "cached_tokens": cached_tokens, "prefix_only": True}
        r = meta["request"]
        request = InferenceRequest(
            request_id=r["request_id"],
            model=r.get("model"),
            prompt_token_ids=r.get("prompt_token_ids"),
            sampling=SamplingParams.from_dict(r["sampling"]),
            priority=r.get("priority", 0),
            session_id=r.get("session_id"),
        )
        if r.get("deadline_at") is not None:
            # re-derive the RELATIVE deadline against this engine's fresh
            # arrival_time so deadline_at lands on the original absolute
            # instant — elapsed handoff time stays spent, EDF order
            # survives the migration (clamped: already-missed deadlines
            # must not go negative)
            request.deadline_s = max(
                0.0, float(r["deadline_at"]) - request.arrival_time
            )
        prompt = list(request.prompt_token_ids or [])
        if not prompt:
            raise ValueError("streamed handoff with empty prompt")
        # full capacity check at BEGIN time — before any piece crosses the
        # wire. The commit-time state is prompt + 1 pending (first) token,
        # so remaining = max_new - 1: identical bound to the commit check.
        _validate_capacity(
            eng, len(prompt) + 1, len(prompt),
            max(request.sampling.max_new_tokens - 1, 0),
        )
        seq_id = f"{request.request_id}-pd"
        blocks, cached_tokens = eng.manager.allocate_sequence(seq_id, prompt)
        self._sessions[key] = _AdoptSession(
            seq_id=seq_id, request=request,
            block_size=meta["block_size"], blocks=list(blocks),
            cached_tokens=cached_tokens, prompt_len=len(prompt),
        )
        self._flight_note(key, "handoff.rx_begin")
        return {"kv_cache_key": key, "state": "begun",
                "cached_tokens": cached_tokens}

    def _piece(self, meta: Dict[str, Any], payload: bytes,
               raw_len: int) -> Dict[str, Any]:
        # io chaos seam (round 19): receiver-side STAGING faults — a torn
        # or corrupted staging buffer (io_bytes mutates payload, error
        # kinds raise) rides the existing corrupt-piece contract above:
        # handle() aborts the session and the sender's retry ladder runs
        payload = _faults.io_bytes(
            "io.handoff.stage", payload, key=str(meta.get("key", ""))
        )
        sess = self._require(meta["key"])
        sess.last_activity = time.monotonic()
        if meta.get("has_scales"):
            pb, sb = _read_blobs(payload, 2)
            pages = TensorSerializer().deserialize(pb)
            scales = TensorSerializer().deserialize(sb)
        else:
            pages = TensorSerializer().deserialize(payload)
            scales = None
        lo = int(meta["block_lo"])
        eng = self.engine
        cached_blocks = sess.cached_tokens // sess.block_size
        uploaded = 0
        already = set(sess.staged)
        for j in range(pages.shape[0]):
            i = lo + j
            if i >= len(sess.blocks):
                # the donor's chain can grow one block past the prompt
                # allocation (pending-token block) — extend lazily at
                # commit; an uncommitted page here is never read, skip it
                continue
            if i < cached_blocks:
                continue    # receiver-side prefix hit: page already resident
            eng.manager.pending.uploads.append((sess.blocks[i], pages[j]))
            if scales is not None:
                eng.manager.pending.scale_uploads.append(
                    (sess.blocks[i], scales[j])
                )
            if sess.blocks[i] not in already:
                sess.last_progress = time.monotonic()
            sess.staged.append(sess.blocks[i])
            uploaded += 1
        eng._apply_pending()
        return {"kv_cache_key": meta["key"], "state": "staged",
                "blocks": uploaded, "bytes_received": raw_len}

    def _commit(self, meta: Dict[str, Any]) -> Dict[str, Any]:
        key = meta["key"]
        if key not in self._sessions and key in self._recent_commits:
            # retried commit after a lost ACK: the slot is already bound —
            # answer the original result instead of failing a handoff that
            # landed (the sender's retry ladder depends on this)
            self.stats["commit_replays"] = (
                self.stats.get("commit_replays", 0) + 1
            )
            return {**self._recent_commits[key], "replay": True}
        sess = self._require(key)
        eng = self.engine
        if sess.prefix_only:
            # prefix-only commit: no slot to bind — verify coverage, then
            # release the chain into the radix prefix index so the next
            # admission of the real request hits L1 instead of re-prefilling
            cached_blocks = sess.cached_tokens // sess.block_size
            kv_len = int(meta.get("kv_len") or sess.prompt_len)
            needed = -(-kv_len // sess.block_size)
            staged = set(sess.staged)
            missing = [
                i for i in range(cached_blocks,
                                 min(needed, len(sess.blocks)))
                if sess.blocks[i] not in staged
            ]
            if missing:
                self._drop(key)
                raise ValueError(
                    f"prefix handoff {key!r}: commit with unstaged blocks "
                    f"{missing[:8]}{'...' if len(missing) > 8 else ''} "
                    f"(piece lost in transit?) — session aborted"
                )
            eng.manager.free_sequence(sess.seq_id, cache=True)
            del self._sessions[key]
            self.stats["prefix_commits"] = (
                self.stats.get("prefix_commits", 0) + 1
            )
            result = {"kv_cache_key": key, "state": "committed",
                      "prefix_only": True, "blocks": len(sess.blocks),
                      "cached_tokens": sess.cached_tokens, "streamed": True}
            self._recent_commits[key] = result
            while len(self._recent_commits) > self.MAX_COMMIT_MEMO:
                self._recent_commits.pop(next(iter(self._recent_commits)))
            return result
        req = sess.request
        token_ids = list(meta["token_ids"])
        # every block covering the committed KV range must have been staged
        # (or be resident via the receiver's prefix cache): committing over
        # a lost piece would bind a slot to unwritten pages and the resumed
        # decode would silently diverge — abort instead, so the control
        # plane retries the stage cleanly
        cached_blocks = sess.cached_tokens // sess.block_size
        needed = -(-int(meta["kv_len"]) // sess.block_size)
        staged = set(sess.staged)
        missing = [
            i for i in range(cached_blocks, min(needed, len(sess.blocks)))
            if sess.blocks[i] not in staged
        ]
        if missing:
            self._drop(key)
            raise ValueError(
                f"streamed handoff {key!r}: commit with unstaged blocks "
                f"{missing[:8]}{'...' if len(missing) > 8 else ''} "
                f"(piece lost in transit?) — session aborted"
            )
        try:
            _validate_capacity(
                eng, len(token_ids), int(meta["kv_len"]),
                0 if meta.get("finish_reason") is not None else
                req.sampling.max_new_tokens - len(meta["generated"]),
            )
        except ValueError:
            self._drop(key)
            raise
        free = eng.free_slots()
        if not free:
            self._drop(key)
            raise RuntimeError("no free slots")
        slot = free[0]
        try:
            # mirror the donor's pending-token append (may grow the chain)
            for tok in token_ids[len(eng.manager.seq_tokens[sess.seq_id]):]:
                eng.manager.append_token(sess.seq_id, tok)
            _bind_migrated(
                eng, slot, request=req, seq_id=sess.seq_id,
                prompt_len=sess.prompt_len, generated=meta["generated"],
                cached_tokens=sess.cached_tokens,
                start_time=meta["start_time"],
                first_token_time=meta["first_token_time"],
                kv_len=int(meta["kv_len"]),
                pending_token=int(meta["pending_token"]),
                slot_key=meta.get("slot_key"),
                finish_reason=meta.get("finish_reason"),
            )
        except Exception:
            eng.slots[slot] = None
            eng._kv_lens[slot] = 0
            self._drop(key)
            raise
        del self._sessions[key]
        self.stats["commits"] = self.stats.get("commits", 0) + 1
        self._flight_note(key, "handoff.rx_commit")
        result = {"slot": slot, "kv_cache_key": key, "state": "committed",
                  "streamed": True}
        self._recent_commits[key] = result
        while len(self._recent_commits) > self.MAX_COMMIT_MEMO:
            self._recent_commits.pop(next(iter(self._recent_commits)))
        return result

    def _abort(self, meta: Dict[str, Any]) -> Dict[str, Any]:
        if str(meta.get("key", "")) in self._sessions:
            self.stats["rx_aborts"] = self.stats.get("rx_aborts", 0) + 1
            self._flight_note(str(meta.get("key", "")), "handoff.rx_abort")
        self._drop(meta.get("key", ""))
        return {"kv_cache_key": meta.get("key"), "state": "aborted"}

    # -- bookkeeping ---------------------------------------------------------

    def _require(self, key: str) -> _AdoptSession:
        sess = self._sessions.get(key)
        if sess is None:
            raise ValueError(f"no streamed handoff session {key!r}")
        return sess

    def _drop(self, key: str) -> None:
        sess = self._sessions.pop(key, None)
        if sess is None:
            return
        eng = self.engine
        if sess.staged:
            staged = set(sess.staged)
            eng.manager.pending.uploads = [
                (bid, page) for bid, page in eng.manager.pending.uploads
                if bid not in staged
            ]
            eng.manager.pending.scale_uploads = [
                (bid, page)
                for bid, page in eng.manager.pending.scale_uploads
                if bid not in staged
            ]
        if sess.seq_id in eng.manager.seq_blocks:
            eng.manager.free_sequence(sess.seq_id, cache=False)

    def _purge_stale(self) -> None:
        now = time.monotonic()
        for key, sess in list(self._sessions.items()):
            if now - sess.last_activity > self.SESSION_TTL_S:
                reason = "purged_ttl"
            elif now - sess.last_progress > self.SESSION_MAX_NO_PROGRESS_S:
                reason = "purged_no_progress"
            else:
                continue
            self._drop(key)
            self.stats["sessions_purged"] = (
                self.stats.get("sessions_purged", 0) + 1
            )
            self.stats[reason] = self.stats.get(reason, 0) + 1


def deserialize_handoff(data: bytes) -> KVHandoff:
    mb = _read_blobs(data, 1)[0]
    meta: Dict[str, Any] = _unpack_header(mb)
    count = 3 if meta.get("has_scales") else 2
    blobs = _read_blobs(data, count)
    pages = TensorSerializer().deserialize(blobs[1])
    scale_pages = (
        TensorSerializer().deserialize(blobs[2])
        if meta.get("has_scales") else None
    )
    r = meta["request"]
    request = InferenceRequest(
        request_id=r["request_id"],
        model=r.get("model"),
        prompt_token_ids=r.get("prompt_token_ids"),
        sampling=SamplingParams.from_dict(r["sampling"]),
        priority=r.get("priority", 0),
        session_id=r.get("session_id"),
    )
    if r.get("deadline_at") is not None:
        # absolute → relative against the fresh arrival_time (same
        # re-derivation as the streamed _begin path): EDF ordering
        # survives the handoff, elapsed transfer time stays spent
        request.deadline_s = max(
            0.0, float(r["deadline_at"]) - request.arrival_time
        )
    return KVHandoff(
        request=request,
        model_name=meta["model_name"],
        block_size=meta["block_size"],
        token_ids=meta["token_ids"],
        kv_len=meta["kv_len"],
        pending_token=meta["pending_token"],
        prompt_len=meta["prompt_len"],
        generated=meta["generated"],
        start_time=meta["start_time"],
        first_token_time=meta["first_token_time"],
        slot_key=meta.get("slot_key"),
        window_front=meta.get("window_front", 0),
        finish_reason=meta.get("finish_reason"),
        pages=pages,
        scale_pages=scale_pages,
    )
