"""Control plane: REST API, store, schedulers, fleet services.

TPU-native re-design of the reference's ``server/app`` layer
(FastAPI + async SQLAlchemy + Postgres → aiohttp + stdlib sqlite/WAL here;
behavioral parity, not a translation). The control plane never touches
tensors — it moves JSON params/results only (reference ``SURVEY`` §3.2);
tensor traffic rides the ICI/DCN data plane in ``comm/`` and ``parallel/``.
"""
