"""Proactive prefix replication (round 20): the plane watches prefix
hit-VELOCITY at discovery time and, when a prefix is heating up, rides
``kv_replicate`` hints down the heartbeat response to workers that do
not hold it — each hint is one budget/backoff-bounded ``/kv/export``
pull on the worker (``engines/llm.kv_replicate``), so the PR 13
storm-workload hit-rate win arrives BEFORE the burst instead of during
it.

Stance (same as every routing signal here):

- **Advisory.** A hint the worker drops (budget full, peer dead, fp
  churned out of the exporter's map) costs nothing — the plane re-hints
  after a cooldown, and the reactive migrate path still exists. A wrong
  prediction costs one prefetch worth of bandwidth, never correctness.
- **Bounded.** Heat state is a bounded LRU of fingerprint chains;
  hints are capped per heartbeat; each (worker, prefix) pair is
  re-hinted at most once per ``replicate_cooldown_s``.
- **Off by default.** ``RoutingConfig.replicate`` gates both the heat
  accounting and the hint fan-out; off means the heartbeat response is
  byte-identical to the pre-round build.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, List, Optional, Sequence

from .prefix_routing import PrefixRegistry, RoutingConfig


class _Heat:
    __slots__ = ("fps", "hits")

    def __init__(self, fps: List[str]) -> None:
        self.fps = fps              # full boundary chain, depth order
        self.hits: Deque[float] = deque()


class ReplicationPlanner:
    """Discovery-time heat tracker + per-heartbeat hint planner."""

    # bounded heat state: prefixes beyond this evict coldest-first
    _MAX_PREFIXES = 1024
    # bounded cooldown map: (worker, fp) pairs beyond this evict oldest
    _MAX_COOLDOWNS = 8192

    def __init__(self, cfg: RoutingConfig,
                 registry: PrefixRegistry) -> None:
        self.cfg = cfg
        self.registry = registry
        self._lock = threading.Lock()
        # deepest-fp -> _Heat; insertion/touch order = LRU
        self._heat: "OrderedDict[str, _Heat]" = OrderedDict()
        # (worker_id, deepest-fp) -> last hint time
        self._cooldown: "OrderedDict[tuple, float]" = OrderedDict()
        self.stats = {"queries": 0, "hot": 0, "hints": 0}

    # -- discovery-time accounting ------------------------------------------

    def note_query(self, fps: Sequence[str],
                   now: Optional[float] = None) -> None:
        """One discovery query carried this fingerprint chain: record a
        hit on EVERY boundary it traverses, not just the deepest — a
        chat turn extends its conversation's chain with a fresh deepest
        fp each time, but the shared head (system prompt, earlier turns)
        recurs, and that shared part is what is worth replicating.
        Boundaries are content-addressed (cumulative hashes), so one
        key always maps to one chain. Gated on the flag by the CALLER
        (the discovery handler) so the off path costs nothing."""
        if not fps:
            return
        now = time.time() if now is None else now
        window = max(0.1, self.cfg.replicate_window_s)
        with self._lock:
            chain = [str(f) for f in fps]
            for i, key in enumerate(chain):
                h = self._heat.get(key)
                if h is None:
                    h = self._heat[key] = _Heat(chain[:i + 1])
                else:
                    self._heat.move_to_end(key)
                h.hits.append(now)
                while h.hits and h.hits[0] < now - window:
                    h.hits.popleft()
            while len(self._heat) > self._MAX_PREFIXES:
                self._heat.popitem(last=False)
            self.stats["queries"] += 1

    # -- heartbeat-time planning --------------------------------------------

    def hints_for(self, worker_id: str,
                  sources: Sequence[Dict[str, Any]],
                  now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Hints for the worker that just heartbeated: hot prefixes it
        does NOT advertise that some OTHER worker with a live data plane
        does. ``sources`` are candidate exporter rows (id +
        data_plane_url — the caller lists them only while the flag is
        on, so the off path costs no store query). At most
        ``replicate_max_hints`` per beat; each (worker, prefix) pair
        respects ``replicate_cooldown_s``."""
        now = time.time() if now is None else now
        window = max(0.1, self.cfg.replicate_window_s)
        threshold = max(1, self.cfg.replicate_hot_threshold)
        exporters = {
            str(s["id"]): s for s in sources
            if s.get("data_plane_url") and str(s.get("id")) != worker_id
        }
        if not exporters:
            return []
        with self._lock:
            hot = []
            for key, h in self._heat.items():
                while h.hits and h.hits[0] < now - window:
                    h.hits.popleft()
                if len(h.hits) >= threshold:
                    hot.append((len(h.hits), key, list(h.fps)))
            # one hint per lineage, at the DEEPEST still-hot boundary: an
            # ancestor is heated by every query that traverses it, so a
            # hot entry that is a strict prefix of another hot entry says
            # nothing the deeper one doesn't — replicating the deeper
            # chain covers it
            covered = set()
            for _c, _key, fps in hot:
                covered.update(fps[:-1])
            hot = [t for t in hot if t[1] not in covered]
            # hottest first: the hint budget goes to the biggest storms
            hot.sort(key=lambda t: -t[0])
        out: List[Dict[str, Any]] = []
        for _hits, key, fps in hot:
            if len(out) >= max(1, self.cfg.replicate_max_hints):
                break
            # the heartbeating worker already holds ANY of it → skip: the
            # reactive path (or a prior hint) is mid-landing, and a
            # partial-overlap prefetch would re-ship what it has
            n, _tw = self.registry.match_blocks(worker_id, fps, now=now)
            if n > 0:
                continue
            ck = (worker_id, key)
            with self._lock:
                last = self._cooldown.get(ck)
                if last is not None and \
                        now - last < self.cfg.replicate_cooldown_s:
                    continue
            src_id, src_blocks, src_tier = self.registry.best_match(
                list(exporters), fps, now=now,
            )
            if src_id is None or src_blocks <= 0:
                continue   # nobody exportable advertises it (anymore)
            with self._lock:
                self._cooldown[ck] = now
                while len(self._cooldown) > self._MAX_COOLDOWNS:
                    self._cooldown.popitem(last=False)
                self.stats["hints"] += 1
            out.append({
                "fps": fps,
                "worker_id": src_id,
                "data_plane_url": exporters[src_id]["data_plane_url"],
                "tier": src_tier,
            })
        if out:
            self.stats["hot"] += 1
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "tracked_prefixes": len(self._heat),
                **self.stats,
            }
