"""Async persistence layer for the control plane.

The reference uses async SQLAlchemy + Postgres with a *missing* models module
(``server/app/db/database.py:25-28``; schema reconstructed in SURVEY §2.1 from
field usage in ``server/app/api/workers.py:199-218``, ``jobs.py:88-97``,
``services/reliability.py:45-127``, ``services/usage.py:171-186``). This store
implements that reconstructed contract on stdlib sqlite3:

- WAL-mode sqlite, one writer at a time, reads concurrent.
- All blocking calls pushed to a thread executor behind an asyncio lock, so
  the aiohttp control plane stays non-blocking.
- ``claim_next_job`` provides the atomic pull the reference gets from
  ``SELECT … FOR UPDATE SKIP LOCKED`` (``scheduler.py:194-234``) — sqlite has
  a single writer, so ``BEGIN IMMEDIATE`` + conditional UPDATE is equivalent.
- **Versioned in-place migrations** via ``PRAGMA user_version`` (the role
  alembic plays for the reference, ``server/alembic/env.py``): ``_SCHEMA``
  is the frozen v1 baseline, every later change is an entry in
  ``_MIGRATIONS``, and ``Store.__init__`` upgrades any older database file
  atomically per version. Fresh databases replay the full migration list,
  so the upgrade path is exercised on every open, not just on legacy files.

Rows are returned as plain dicts; JSON-typed columns are transparently
encoded/decoded.
"""

from __future__ import annotations

import asyncio
import json
import os
import sqlite3
import time
import uuid
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

from ..testing import faults as _faults
from ..utils.data_structures import JobStatus, WorkerState

# Multi-writer contention knobs (replicated control planes share one
# database file; see docs/ENV_CONFIG.md). busy_timeout makes sqlite block
# up to N ms for the other plane's write transaction; the retry loop
# handles the SQLITE_BUSY that still escapes (deadlock-avoidance returns
# busy immediately when a deferred reader upgrades against a writer).
_BUSY_TIMEOUT_MS = int(os.environ.get("DGI_STORE_BUSY_TIMEOUT_MS", "5000"))
_LOCK_RETRIES = int(os.environ.get("DGI_STORE_LOCK_RETRIES", "6"))
_LOCK_RETRY_BASE_S = 0.02


def _is_locked(exc: BaseException) -> bool:
    if not isinstance(exc, sqlite3.OperationalError):
        return False
    msg = str(exc)
    return "locked" in msg or "busy" in msg

# Columns stored as JSON text.
_WORKER_JSON = {
    "supported_types",
    "loaded_models",
    "online_pattern",
    "config_override",
    "topology",
    "mesh_shape",
    "load_stats",
}
_JOB_JSON = {"params", "result", "checkpoint", "prefix_fps", "timeline"}

_SCHEMA = """
CREATE TABLE IF NOT EXISTS workers (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL DEFAULT '',
    region TEXT NOT NULL DEFAULT 'unknown',
    country TEXT, city TEXT, timezone TEXT,
    -- TPU capability surface (reference stores gpu_model/gpu_memory_gb etc.)
    accelerator TEXT NOT NULL DEFAULT 'tpu',
    chip_generation TEXT, num_chips INTEGER NOT NULL DEFAULT 1,
    hbm_gb_per_chip REAL NOT NULL DEFAULT 16.0,
    hbm_used_gb REAL NOT NULL DEFAULT 0.0,
    topology TEXT, mesh_shape TEXT,
    cpu_cores INTEGER, ram_gb REAL,
    supported_types TEXT NOT NULL DEFAULT '[]',
    loaded_models TEXT NOT NULL DEFAULT '[]',
    status TEXT NOT NULL DEFAULT 'idle',
    role TEXT NOT NULL DEFAULT 'hybrid',
    current_job_id TEXT,
    last_heartbeat REAL,
    registered_at REAL NOT NULL,
    supports_direct INTEGER NOT NULL DEFAULT 0,
    direct_url TEXT,
    -- auth (hashes only at rest: reference workers.py:199-235)
    auth_token_hash TEXT, refresh_token_hash TEXT, signing_secret TEXT,
    token_expires_at REAL,
    failed_auth_attempts INTEGER NOT NULL DEFAULT 0,
    last_failed_auth REAL, locked_until REAL,
    -- reliability (reference reliability.py:45-127)
    reliability_score REAL NOT NULL DEFAULT 0.5,
    success_rate REAL NOT NULL DEFAULT 1.0,
    total_jobs INTEGER NOT NULL DEFAULT 0,
    completed_jobs INTEGER NOT NULL DEFAULT 0,
    failed_jobs INTEGER NOT NULL DEFAULT 0,
    avg_latency_ms REAL NOT NULL DEFAULT 0.0,
    unexpected_offline_count INTEGER NOT NULL DEFAULT 0,
    total_online_seconds REAL NOT NULL DEFAULT 0.0,
    total_sessions INTEGER NOT NULL DEFAULT 0,
    avg_session_minutes REAL NOT NULL DEFAULT 0.0,
    current_session_start REAL,
    online_pattern TEXT NOT NULL DEFAULT '{}',
    -- remote config (reference workers.py:491-546)
    config_version INTEGER NOT NULL DEFAULT 0,
    config_override TEXT,
    last_config_sync REAL
);

CREATE TABLE IF NOT EXISTS jobs (
    id TEXT PRIMARY KEY,
    type TEXT NOT NULL,
    params TEXT NOT NULL DEFAULT '{}',
    priority INTEGER NOT NULL DEFAULT 0,
    status TEXT NOT NULL DEFAULT 'queued',
    preferred_region TEXT,
    allow_cross_region INTEGER NOT NULL DEFAULT 1,
    actual_region TEXT,
    client_ip TEXT, client_region TEXT,
    worker_id TEXT,
    result TEXT, error TEXT,
    timeout_seconds REAL NOT NULL DEFAULT 300.0,
    retry_count INTEGER NOT NULL DEFAULT 0,
    max_retries INTEGER NOT NULL DEFAULT 3,
    created_at REAL NOT NULL,
    started_at REAL, completed_at REAL,
    actual_duration_ms REAL
);
CREATE INDEX IF NOT EXISTS idx_jobs_status_priority
    ON jobs (status, priority DESC, created_at);
CREATE INDEX IF NOT EXISTS idx_jobs_worker ON jobs (worker_id);

CREATE TABLE IF NOT EXISTS enterprises (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL,
    contact_email TEXT,
    custom_pricing TEXT,            -- JSON {job_type: price-per-unit}
    price_plan_id TEXT,
    allow_logging INTEGER NOT NULL DEFAULT 1,
    retention_days INTEGER NOT NULL DEFAULT 30,
    anonymize_data INTEGER NOT NULL DEFAULT 0,
    encrypt_fields INTEGER NOT NULL DEFAULT 0,
    created_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS price_plans (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL,
    prices TEXT NOT NULL DEFAULT '{}',   -- JSON {job_type: price-per-unit}
    created_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS api_keys (
    id TEXT PRIMARY KEY,
    enterprise_id TEXT NOT NULL,
    key_hash TEXT NOT NULL,
    name TEXT,
    active INTEGER NOT NULL DEFAULT 1,
    created_at REAL NOT NULL,
    last_used_at REAL
);
CREATE INDEX IF NOT EXISTS idx_api_keys_hash ON api_keys (key_hash);

CREATE TABLE IF NOT EXISTS usage_records (
    id TEXT PRIMARY KEY,
    enterprise_id TEXT,
    job_id TEXT NOT NULL,
    job_type TEXT NOT NULL,
    worker_id TEXT,
    units REAL NOT NULL DEFAULT 0.0,     -- tokens / pixels / seconds
    unit_kind TEXT NOT NULL DEFAULT 'tokens',
    cost REAL NOT NULL DEFAULT 0.0,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_usage_ent_time
    ON usage_records (enterprise_id, created_at);

CREATE TABLE IF NOT EXISTS bills (
    id TEXT PRIMARY KEY,
    enterprise_id TEXT NOT NULL,
    period_start REAL NOT NULL,
    period_end REAL NOT NULL,
    total_cost REAL NOT NULL DEFAULT 0.0,
    line_items TEXT NOT NULL DEFAULT '[]',
    status TEXT NOT NULL DEFAULT 'open',
    created_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS audit_log (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    ts REAL NOT NULL,
    event TEXT NOT NULL,
    actor TEXT,
    detail TEXT
);
"""

_BASELINE_VERSION = 1

# Ordered (version, statement) pairs. All statements of one version apply in
# one transaction and ``PRAGMA user_version`` advances with it — a crash
# mid-version leaves the file at the previous version, to be retried. The
# baseline ``_SCHEMA`` is FROZEN at v1: schema evolution happens here.
_MIGRATIONS = [
    # v2: jobs carry the enterprise that submitted them, so usage/billing can
    # attribute work without joining through api_keys at query time
    (2, "ALTER TABLE jobs ADD COLUMN enterprise_id TEXT"),
    (2, "CREATE INDEX IF NOT EXISTS idx_jobs_enterprise "
        "ON jobs (enterprise_id)"),
    # v3: PD disaggregation — decode-capable workers advertise the data-plane
    # URL prefill peers push KV handoffs to (server/pd_flow.py)
    (3, "ALTER TABLE workers ADD COLUMN data_plane_url TEXT"),
    # v4: registration idempotency — a register retried after a lost
    # response (server flap) must land on the SAME worker row, keyed by the
    # machine fingerprint the worker already sends (worker/machine_id.py)
    (4, "ALTER TABLE workers ADD COLUMN machine_fingerprint TEXT"),
    (4, "CREATE INDEX IF NOT EXISTS idx_workers_fingerprint "
        "ON workers (machine_fingerprint)"),
    # v5: crash-safe generation — every claim bumps the job's
    # assignment_epoch (the fence a zombie worker's late complete_job or
    # stale checkpoint is rejected against), and workers piggyback a
    # portable PreemptedSequence checkpoint on heartbeats so a requeued
    # job resumes instead of regenerating. Direct (queue-less) SSE streams
    # checkpoint into their own table keyed by stream_id.
    (5, "ALTER TABLE jobs ADD COLUMN assignment_epoch INTEGER "
        "NOT NULL DEFAULT 0"),
    (5, "ALTER TABLE jobs ADD COLUMN checkpoint TEXT"),
    (5, "CREATE TABLE IF NOT EXISTS stream_checkpoints ("
        " stream_id TEXT PRIMARY KEY,"
        " worker_id TEXT,"
        " epoch INTEGER NOT NULL DEFAULT 0,"
        " state TEXT,"
        " updated_at REAL)"),
    # v6: cache-aware routing — jobs carry the request's prefix boundary
    # fingerprints (computed client- or server-side, utils/prefixes.py) so
    # claim/scoring can prefer the worker already holding the prefix;
    # workers persist their advertised radix summary (a control-plane
    # restart warm-starts routing instead of going blind) and a graded
    # load snapshot from the batcher heartbeat stats (the binary
    # current_job_id load signal lies for batcher-backed workers running
    # many jobs concurrently).
    (6, "ALTER TABLE jobs ADD COLUMN prefix_fps TEXT"),
    (6, "ALTER TABLE workers ADD COLUMN load_stats TEXT"),
    (6, "CREATE TABLE IF NOT EXISTS worker_prefix_summaries ("
        " worker_id TEXT PRIMARY KEY,"
        " seq INTEGER NOT NULL DEFAULT 0,"
        " block_chars INTEGER NOT NULL DEFAULT 64,"
        " entries TEXT,"
        " updated_at REAL)"),
    # v7: fast-restart fencing — each worker PROCESS mints a boot_id at
    # startup and sends it with registration. A re-registration on the
    # same fingerprint with a DIFFERENT boot_id proves the previous
    # incarnation is dead even when the restart beat the heartbeat
    # timeout (fast supervisor): its RUNNING jobs requeue immediately
    # instead of stranding until the job timeout. A credential-blip
    # re-register from the SAME process keeps its boot_id and its work.
    (7, "ALTER TABLE workers ADD COLUMN boot_id TEXT"),
    # v8: SLO-native overload control — usage records carry the tenant
    # and tier the plane admitted the job under, so per-tenant accounting
    # (and the fairness story behind the admission budgets) is auditable
    # from the same table billing reads.
    (8, "ALTER TABLE usage_records ADD COLUMN tenant TEXT"),
    (8, "ALTER TABLE usage_records ADD COLUMN tier TEXT"),
    (8, "CREATE INDEX IF NOT EXISTS idx_usage_tenant "
        "ON usage_records (tenant, created_at)"),
    # v9: request flight recorder — the merged per-request timeline is
    # stored with the job at completion (bounded by the recorder's
    # per-job event cap), so GET /debug/requests/{id}/timeline survives a
    # control-plane restart and post-mortems read from the same row the
    # result lives on. Advisory: a write failure is swallowed — the
    # recorder can never fail a request.
    (9, "ALTER TABLE jobs ADD COLUMN timeline TEXT"),
    # v10: replicated control planes — every claim stamps the plane that
    # brokered it. The assignment_epoch remains THE fence (a stale plane's
    # late complete/checkpoint 409s exactly like a stale worker's); the
    # plane_id column makes the broker auditable per epoch, so chaos tests
    # and post-mortems can prove WHICH plane's write was fenced out.
    (10, "ALTER TABLE jobs ADD COLUMN plane_id TEXT"),
]

SCHEMA_VERSION = max(
    [v for v, _ in _MIGRATIONS], default=_BASELINE_VERSION
)


def _encode(table_json: set, row: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in row.items():
        if k in table_json and v is not None and not isinstance(v, str):
            v = json.dumps(v)
        elif isinstance(v, bool):
            v = int(v)
        out[k] = v
    return out


def _decode(table_json: set, row: sqlite3.Row) -> Dict[str, Any]:
    d = dict(row)
    for k in table_json:
        if k in d and isinstance(d[k], str):
            try:
                d[k] = json.loads(d[k])
            except (ValueError, TypeError):
                pass
    return d


class Store:
    """Async facade over a WAL sqlite database (control-plane state)."""

    def __init__(self, path: str = ":memory:") -> None:
        self._path = path
        # one connection PER STORE, serialized writes within a plane;
        # check_same_thread off because we hop through the default executor.
        # Replicated planes each open their own Store on the same file:
        # WAL + busy_timeout + the locked-retry loop make cross-plane
        # writes safe (sqlite serializes writers; fenced conditional
        # UPDATEs decide races).
        self._conn = sqlite3.connect(
            path, check_same_thread=False, isolation_level=None
        )
        self._conn.row_factory = sqlite3.Row
        if path != ":memory:":
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(f"PRAGMA busy_timeout={_BUSY_TIMEOUT_MS}")
        self._conn.execute("PRAGMA foreign_keys=ON")
        try:
            self._locked_retry(self._migrate)
        except BaseException:
            self._conn.close()
            raise
        self._lock = asyncio.Lock()

    def _rollback(self) -> None:
        """Best-effort ROLLBACK: when BEGIN itself lost a lock race there
        is no transaction to roll back, and that secondary error must not
        mask the original one."""
        try:
            self._conn.execute("ROLLBACK")
        except sqlite3.OperationalError:
            pass

    def _locked_retry(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` (a whole transaction), retrying on SQLITE_BUSY with
        capped exponential backoff. With a single plane this never fires;
        with replicated planes it absorbs the write-lock collisions
        busy_timeout lets through. The transaction either fully commits or
        fully rolls back per attempt, so a retry re-reads fresh state —
        fenced UPDATEs (claim, transition) stay correct across planes."""
        for attempt in range(_LOCK_RETRIES + 1):
            try:
                return fn()
            except sqlite3.OperationalError as exc:
                if not _is_locked(exc) or attempt >= _LOCK_RETRIES:
                    raise
                self._rollback()
                time.sleep(min(0.25, _LOCK_RETRY_BASE_S * (2 ** attempt)))

    def _migrate(self) -> None:
        """Bring the database to ``SCHEMA_VERSION`` in place.

        version 0 means either a fresh file or a legacy pre-versioning
        database; both get the v1 baseline (``IF NOT EXISTS`` makes it a
        no-op on legacy files, whose tables ARE the v1 shape) and then
        replay every migration beyond their version.
        """
        (ver,) = self._conn.execute("PRAGMA user_version").fetchone()
        if ver == 0:
            # executescript issues an implicit COMMIT, so the baseline runs
            # in autocommit (IF NOT EXISTS makes it a no-op against a peer's
            # concurrent bootstrap). The version stamp must re-check under
            # the write lock: a racer that also read 0 must not clobber a
            # peer that already advanced past the baseline, or it would
            # re-apply ALTERs against the migrated schema.
            self._conn.executescript(_SCHEMA)
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                (cur,) = self._conn.execute(
                    "PRAGMA user_version"
                ).fetchone()
                if cur == 0:
                    self._conn.execute(
                        f"PRAGMA user_version={_BASELINE_VERSION}"
                    )
                self._conn.execute("COMMIT")
            except BaseException:
                self._rollback()
                raise
            ver = cur if cur > 0 else _BASELINE_VERSION
        if ver > SCHEMA_VERSION:
            raise RuntimeError(
                f"database {self._path!r} is at schema version {ver}, newer "
                f"than this build's {SCHEMA_VERSION} — refusing to open"
            )
        pending = sorted(
            {v for v, _ in _MIGRATIONS if v > ver}
        )
        for v in pending:
            # IMMEDIATE + re-check: two planes opening the same fresh file
            # concurrently must not both apply a version (the second ALTER
            # TABLE would fail on a duplicate column)
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                (cur_ver,) = self._conn.execute(
                    "PRAGMA user_version"
                ).fetchone()
                if cur_ver >= v:
                    self._conn.execute("COMMIT")
                    continue
                for mv, sql in _MIGRATIONS:
                    if mv == v:
                        self._conn.execute(sql)
                self._conn.execute(f"PRAGMA user_version={v}")
                self._conn.execute("COMMIT")
            except BaseException:
                self._rollback()
                raise

    async def _run(self, fn, *args):
        async with self._lock:
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, fn, *args)

    def close(self) -> None:
        self._conn.close()

    # -- generic helpers ---------------------------------------------------

    def _exec(self, sql: str, params: Sequence[Any] = ()) -> None:
        self._locked_retry(lambda: self._conn.execute(sql, params))

    def _query(self, sql: str, params: Sequence[Any] = ()) -> List[sqlite3.Row]:
        return self._conn.execute(sql, params).fetchall()

    async def execute(self, sql: str, params: Sequence[Any] = ()) -> None:
        # chaos seam: an installed FaultPlan can lose this mutation (drop)
        # or fail it like a wedged backend (error) — no-op passthrough
        # otherwise (testing/faults.py)
        if _faults.store_fault("server.store.execute", sql=sql):
            return
        await self._run(self._exec, sql, params)

    async def query(
        self, sql: str, params: Sequence[Any] = ()
    ) -> List[Dict[str, Any]]:
        rows = await self._run(self._query, sql, params)
        return [dict(r) for r in rows]

    # -- workers -----------------------------------------------------------

    async def upsert_worker(self, worker: Dict[str, Any]) -> None:
        row = _encode(_WORKER_JSON, dict(worker))
        row.setdefault("registered_at", time.time())
        cols = ", ".join(row)
        ph = ", ".join("?" for _ in row)
        upd = ", ".join(f"{c}=excluded.{c}" for c in row if c != "id")
        await self.execute(
            f"INSERT INTO workers ({cols}) VALUES ({ph}) "
            f"ON CONFLICT(id) DO UPDATE SET {upd}",
            list(row.values()),
        )

    async def get_worker(self, worker_id: str) -> Optional[Dict[str, Any]]:
        rows = await self._run(
            self._query, "SELECT * FROM workers WHERE id=?", (worker_id,)
        )
        return _decode(_WORKER_JSON, rows[0]) if rows else None

    async def update_worker(self, worker_id: str, **fields: Any) -> None:
        if not fields:
            return
        row = _encode(_WORKER_JSON, fields)
        sets = ", ".join(f"{k}=?" for k in row)
        await self.execute(
            f"UPDATE workers SET {sets} WHERE id=?",
            [*row.values(), worker_id],
        )

    async def list_workers(
        self,
        status: Optional[Iterable[str]] = None,
        region: Optional[str] = None,
        supports_type: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        sql, params = "SELECT * FROM workers", []
        clauses = []
        if status is not None:
            vals = [s.value if isinstance(s, WorkerState) else s for s in status]
            clauses.append(f"status IN ({','.join('?' * len(vals))})")
            params += vals
        if region is not None:
            clauses.append("region=?")
            params.append(region)
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        rows = await self._run(self._query, sql, params)
        out = [_decode(_WORKER_JSON, r) for r in rows]
        if supports_type is not None:
            out = [w for w in out if supports_type in (w.get("supported_types") or [])]
        return out

    async def delete_worker(self, worker_id: str) -> None:
        await self.execute("DELETE FROM workers WHERE id=?", (worker_id,))

    async def reserve_worker_id_for_fingerprint(
        self, fingerprint: str, candidate_id: str
    ) -> str:
        """Atomic lookup-or-reserve of the worker row for a machine
        fingerprint (registration idempotency). A plain SELECT-then-INSERT
        in the handler is check-then-act: two concurrent registers (a
        client retry racing its own slow original) would both see no row
        and mint duplicate workers. ``BEGIN IMMEDIATE`` + conditional
        insert makes the reservation atomic — the same pattern
        ``claim_next_job`` uses."""
        # chaos seam: a dropped reservation write models a lost insert —
        # the candidate id is still returned, and the follow-up upsert
        # creates the row (the retry path the scenario exercises)
        if _faults.store_fault(
            "server.store.execute", sql="INSERT INTO workers (reserve)"
        ):
            return candidate_id

        def txn() -> str:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                row = self._conn.execute(
                    "SELECT id FROM workers WHERE machine_fingerprint=?",
                    (fingerprint,),
                ).fetchone()
                if row is not None:
                    self._conn.execute("COMMIT")
                    return row["id"]
                self._conn.execute(
                    "INSERT INTO workers (id, machine_fingerprint, "
                    "registered_at) VALUES (?, ?, ?)",
                    (candidate_id, fingerprint, time.time()),
                )
                self._conn.execute("COMMIT")
                return candidate_id
            except BaseException:
                self._rollback()
                raise

        return await self._run(self._locked_retry, txn)

    async def try_transition_job(self, job_id: str, from_status: str,
                                 owned_by: Optional[str] = None,
                                 **fields: Any) -> bool:
        """Conditionally update a job only if it is still in
        ``from_status`` (and, when ``owned_by`` is given, still assigned to
        that worker); returns True when this caller won the transition.
        The single UPDATE is atomic, so two concurrent duplicate
        completions cannot both apply terminal effects."""
        # chaos seam: a dropped transition is a lost write — the job stays
        # in from_status and the caller takes its lost-the-race path
        if _faults.store_fault(
            "server.store.execute", sql=f"UPDATE jobs (transition {from_status})"
        ):
            return False
        row = _encode(_JOB_JSON, fields)
        sets = ", ".join(f"{k}=?" for k in row)
        sql = f"UPDATE jobs SET {sets} WHERE id=? AND status=?"
        params: List[Any] = [*row.values(), job_id, from_status]
        if owned_by is not None:
            sql += " AND worker_id=?"
            params.append(owned_by)

        def txn() -> bool:
            cur = self._conn.execute(sql, params)
            return cur.rowcount == 1

        return await self._run(self._locked_retry, txn)

    # -- jobs --------------------------------------------------------------

    async def create_job(self, job: Dict[str, Any]) -> str:
        row = _encode(_JOB_JSON, dict(job))
        row.setdefault("id", str(uuid.uuid4()))
        row.setdefault("created_at", time.time())
        row.setdefault("status", JobStatus.QUEUED.value)
        cols = ", ".join(row)
        ph = ", ".join("?" for _ in row)
        await self.execute(
            f"INSERT INTO jobs ({cols}) VALUES ({ph})", list(row.values())
        )
        return row["id"]

    async def get_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        rows = await self._run(
            self._query, "SELECT * FROM jobs WHERE id=?", (job_id,)
        )
        return _decode(_JOB_JSON, rows[0]) if rows else None

    async def update_job(self, job_id: str, **fields: Any) -> None:
        if not fields:
            return
        row = _encode(_JOB_JSON, fields)
        sets = ", ".join(f"{k}=?" for k in row)
        await self.execute(
            f"UPDATE jobs SET {sets} WHERE id=?", [*row.values(), job_id]
        )

    async def list_jobs(
        self,
        status: Optional[Iterable[str]] = None,
        worker_id: Optional[str] = None,
        limit: int = 100,
    ) -> List[Dict[str, Any]]:
        sql, params = "SELECT * FROM jobs", []
        clauses = []
        if status is not None:
            vals = [s.value if isinstance(s, JobStatus) else s for s in status]
            clauses.append(f"status IN ({','.join('?' * len(vals))})")
            params += vals
        if worker_id is not None:
            clauses.append("worker_id=?")
            params.append(worker_id)
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        sql += " ORDER BY priority DESC, created_at LIMIT ?"
        params.append(limit)
        rows = await self._run(self._query, sql, params)
        return [_decode(_JOB_JSON, r) for r in rows]

    async def claim_next_job(
        self,
        worker_id: str,
        supported_types: Sequence[str],
        region: Optional[str] = None,
        prefer: Optional[Any] = None,
        prefer_window: int = 32,
        plane_id: Optional[str] = None,
    ) -> Optional[Dict[str, Any]]:
        """Atomically claim the best queued job for this worker.

        Equivalent of the reference's ``SELECT … FOR UPDATE SKIP LOCKED``
        claim (``scheduler.py:194-234``): priority DESC then FIFO, filtered to
        the worker's supported types, region-preferring jobs honored.

        ``prefer``: optional sync callable ``row_dict -> float`` (cache-aware
        routing affinity, ``server/prefix_routing.py``). Within the HEAD
        priority band only — and at most ``prefer_window`` eligible rows —
        the highest-preference job wins, FIFO breaking ties. Priority
        ordering is never violated and a job can be deferred by at most
        ``prefer_window - 1`` positions, so affinity is a bounded
        reordering, not a starvation risk. The callable runs inside the
        claim transaction: it must be pure and in-memory (no store access).

        ``plane_id``: the control-plane replica brokering this claim,
        stamped on the row alongside the epoch bump. With replicated
        planes sharing this file, two planes CAN race the same queued row:
        the conditional UPDATE's ``status=QUEUED`` guard decides the
        winner and the loser re-scans (returns None this poll).
        """

        def txn() -> Optional[sqlite3.Row]:
            if not supported_types:
                return None
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                ph = ",".join("?" * len(supported_types))
                # scan deep enough that a run of region-restricted jobs at the
                # head of the queue cannot starve claimable work behind them
                rows = self._conn.execute(
                    f"SELECT * FROM jobs WHERE status=? AND type IN ({ph}) "
                    "ORDER BY priority DESC, created_at LIMIT 1000",
                    [JobStatus.QUEUED.value, *supported_types],
                ).fetchall()
                pick = None
                cands: List[sqlite3.Row] = []
                for r in rows:
                    pref = r["preferred_region"]
                    if (
                        pref
                        and region
                        and pref != region
                        and not r["allow_cross_region"]
                    ):
                        continue
                    # PD stage jobs are pinned to the worker holding (or
                    # receiving) the KV — nobody else may claim them
                    # (server/pd_flow.py sets target_worker). Substring
                    # pre-check keeps the hot claim path from JSON-parsing
                    # every candidate's (possibly multi-MB prompt-bearing)
                    # params inside the write transaction.
                    raw_params = r["params"] or "{}"
                    if '"target_worker"' in raw_params:
                        try:
                            target = json.loads(raw_params).get(
                                "target_worker"
                            )
                        except ValueError:
                            target = None
                        if target and target != worker_id:
                            continue
                    if prefer is None:
                        pick = r
                        break
                    if cands and r["priority"] != cands[0]["priority"]:
                        break   # never cross a priority band for affinity
                    cands.append(r)
                    if len(cands) >= max(1, prefer_window):
                        break
                if prefer is not None and cands:
                    best, best_score = cands[0], None
                    for r in cands:
                        try:
                            s = float(prefer(dict(r)))
                        except Exception:  # noqa: BLE001 — advisory only
                            s = 0.0
                        if best_score is None or s > best_score:
                            best, best_score = r, s
                    pick = best
                if pick is None:
                    self._conn.execute("COMMIT")
                    return None
                now = time.time()
                # every claim is a fresh assignment epoch: a zombie still
                # working the previous assignment fails the epoch fence on
                # complete/checkpoint even if THIS worker reclaims the job
                cur = self._conn.execute(
                    "UPDATE jobs SET status=?, worker_id=?, started_at=?, "
                    "actual_region=?, plane_id=?, "
                    "assignment_epoch=assignment_epoch+1 "
                    "WHERE id=? AND status=?",
                    (
                        JobStatus.RUNNING.value,
                        worker_id,
                        now,
                        region,
                        plane_id,
                        pick["id"],
                        JobStatus.QUEUED.value,
                    ),
                )
                if cur.rowcount != 1:
                    # raced: a peer plane claimed (or a sweep moved) this
                    # row between our scan and the UPDATE. Single-writer
                    # deployments never hit this; with replicated planes
                    # the loser simply reports no job this poll.
                    self._rollback()
                    return None
                self._conn.execute("COMMIT")
                return self._conn.execute(
                    "SELECT * FROM jobs WHERE id=?", (pick["id"],)
                ).fetchone()
            except BaseException:
                self._rollback()
                raise

        row = await self._run(self._locked_retry, txn)
        return _decode(_JOB_JSON, row) if row is not None else None

    # -- prefix summaries (cache-aware routing) ----------------------------

    async def save_prefix_summary(self, worker_id: str, seq: int,
                                  block_chars: int, entries_json: str,
                                  updated_at: float) -> None:
        """Write-through persistence of a worker's advertised radix
        summary (``server/prefix_routing.py`` keeps the hot in-memory
        copy; this row exists so a restarted control plane warm-starts
        routing instead of going locality-blind)."""
        await self.execute(
            "INSERT INTO worker_prefix_summaries "
            "(worker_id, seq, block_chars, entries, updated_at) "
            "VALUES (?,?,?,?,?) ON CONFLICT(worker_id) DO UPDATE SET "
            "seq=excluded.seq, block_chars=excluded.block_chars, "
            "entries=excluded.entries, updated_at=excluded.updated_at",
            (worker_id, int(seq), int(block_chars), entries_json,
             float(updated_at)),
        )

    async def delete_prefix_summary(self, worker_id: str) -> None:
        await self.execute(
            "DELETE FROM worker_prefix_summaries WHERE worker_id=?",
            (worker_id,),
        )

    # -- stream checkpoints (direct-mode failover) -------------------------

    async def save_stream_checkpoint(self, stream_id: str, worker_id: str,
                                     epoch: int, state: Any) -> bool:
        """Fenced upsert of a direct stream's generation checkpoint.

        Accepts when the stream is unknown, when ``epoch`` advances past the
        stored one, or when the SAME owner re-checkpoints at its current
        epoch. A zombie worker (whose stream was adopted by a failover peer,
        bumping the epoch) is rejected — its stale state must never clobber
        the live continuation. Returns True when the write landed."""
        # chaos seam (round 19): a checkpoint write through a dark/slow
        # store raises OperationalError or is silently lost — the pushers
        # upstream already tolerate both (staleness, not failure)
        if _faults.store_fault("server.store.checkpoint",
                               stream_id=stream_id):
            return False

        def txn() -> bool:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                row = self._conn.execute(
                    "SELECT epoch, worker_id FROM stream_checkpoints "
                    "WHERE stream_id=?", (stream_id,),
                ).fetchone()
                if row is not None:
                    stored = int(row["epoch"] or 0)
                    if epoch < stored or (
                        epoch == stored
                        and row["worker_id"] not in (None, worker_id)
                    ):
                        self._conn.execute("COMMIT")
                        return False
                self._conn.execute(
                    "INSERT INTO stream_checkpoints "
                    "(stream_id, worker_id, epoch, state, updated_at) "
                    "VALUES (?,?,?,?,?) ON CONFLICT(stream_id) DO UPDATE "
                    "SET worker_id=excluded.worker_id, "
                    "epoch=excluded.epoch, state=excluded.state, "
                    "updated_at=excluded.updated_at",
                    (stream_id, worker_id, int(epoch),
                     json.dumps(state), time.time()),
                )
                self._conn.execute("COMMIT")
                return True
            except BaseException:
                self._rollback()
                raise

        return await self._run(self._locked_retry, txn)

    async def adopt_stream_checkpoint(
        self, stream_id: str, worker_id: str
    ) -> Optional[Dict[str, Any]]:
        """Atomically hand a stream's latest checkpoint to a failover
        worker: bumps the epoch (fencing out the previous owner's late
        writes) and records the adopter as the new owner. Returns
        ``{"state", "epoch"}`` or None when no checkpoint exists."""

        def txn() -> Optional[Dict[str, Any]]:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                row = self._conn.execute(
                    "SELECT epoch, state FROM stream_checkpoints "
                    "WHERE stream_id=?", (stream_id,),
                ).fetchone()
                if row is None or row["state"] is None:
                    self._conn.execute("COMMIT")
                    return None
                new_epoch = int(row["epoch"] or 0) + 1
                self._conn.execute(
                    "UPDATE stream_checkpoints SET worker_id=?, epoch=?, "
                    "updated_at=? WHERE stream_id=?",
                    (worker_id, new_epoch, time.time(), stream_id),
                )
                self._conn.execute("COMMIT")
                try:
                    state = json.loads(row["state"])
                except (ValueError, TypeError):
                    state = None
                if state is None:
                    return None
                return {"state": state, "epoch": new_epoch}
            except BaseException:
                self._rollback()
                raise

        return await self._run(self._locked_retry, txn)

    async def delete_stream_checkpoint(self, stream_id: str, worker_id: str,
                                       epoch: int) -> bool:
        """Fenced cleanup when a stream finishes normally: only the current
        owner at the current (or newer) epoch may delete — a zombie's late
        "done" must not erase the checkpoint its replacement still needs."""

        def txn() -> bool:
            cur = self._conn.execute(
                "DELETE FROM stream_checkpoints WHERE stream_id=? "
                "AND (worker_id IS NULL OR worker_id=?) AND epoch<=?",
                (stream_id, worker_id, int(epoch)),
            )
            return cur.rowcount == 1

        return await self._run(self._locked_retry, txn)

    async def get_stream_checkpoint(
        self, stream_id: str
    ) -> Optional[Dict[str, Any]]:
        rows = await self._run(
            self._query,
            "SELECT * FROM stream_checkpoints WHERE stream_id=?",
            (stream_id,),
        )
        if not rows:
            return None
        d = dict(rows[0])
        if isinstance(d.get("state"), str):
            try:
                d["state"] = json.loads(d["state"])
            except (ValueError, TypeError):
                pass
        return d

    # -- queue stats -------------------------------------------------------

    async def queue_stats(self) -> Dict[str, Any]:
        rows = await self._run(
            self._query,
            "SELECT status, COUNT(*) AS n FROM jobs GROUP BY status",
        )
        by_status = {r["status"]: r["n"] for r in rows}
        workers = await self._run(
            self._query,
            "SELECT status, COUNT(*) AS n FROM workers GROUP BY status",
        )
        w_by_status = {r["status"]: r["n"] for r in workers}
        return {
            "jobs": by_status,
            "queued": by_status.get(JobStatus.QUEUED.value, 0),
            "running": by_status.get(JobStatus.RUNNING.value, 0),
            "workers": w_by_status,
            "idle_workers": w_by_status.get(WorkerState.IDLE.value, 0),
        }

    # -- enterprise / billing ---------------------------------------------

    async def insert(self, table: str, row: Dict[str, Any],
                     json_cols: Optional[set] = None) -> str:
        jc = json_cols if json_cols is not None else _detect_json_cols(table)
        row = _encode(jc, dict(row))
        row.setdefault("id", str(uuid.uuid4()))
        row.setdefault("created_at", time.time())
        cols = ", ".join(row)
        ph = ", ".join("?" for _ in row)
        await self.execute(
            f"INSERT INTO {table} ({cols}) VALUES ({ph})", list(row.values())
        )
        return row["id"]

    async def get(self, table: str, row_id: str) -> Optional[Dict[str, Any]]:
        rows = await self._run(
            self._query, f"SELECT * FROM {table} WHERE id=?", (row_id,)
        )
        return _decode(_detect_json_cols(table), rows[0]) if rows else None

    async def audit(self, event: str, actor: Optional[str] = None,
                    detail: Optional[Dict[str, Any]] = None) -> None:
        await self.execute(
            "INSERT INTO audit_log (ts, event, actor, detail) VALUES (?,?,?,?)",
            (time.time(), event, actor, json.dumps(detail or {})),
        )


_TABLE_JSON = {
    "workers": _WORKER_JSON,
    "jobs": _JOB_JSON,
    "enterprises": {"custom_pricing"},
    "price_plans": {"prices"},
    "bills": {"line_items"},
    "usage_records": set(),
    "api_keys": set(),
    "audit_log": {"detail"},
    "stream_checkpoints": {"state"},
}


def _detect_json_cols(table: str) -> set:
    return _TABLE_JSON.get(table, set())
