"""Replicated control planes: identity, peer membership, job forwarding.

One control plane is a SPOF and a throughput ceiling (ROADMAP item 2). This
module makes the plane a *cohort*: N ``server/app.py`` replicas share one
job store (``server/store.py`` is multi-writer hardened — WAL, busy-timeout,
locked-retry, fenced conditional UPDATEs) and each replica carries a
``plane_id`` stamped on every claim it brokers. Workers and SDK clients hold
the full endpoint list and fail over; the store's assignment-epoch fence
rejects a stale plane's late writes exactly like a stale worker's.

Plane-to-plane job forwarding closes the reference platform's scaffold TODO
(PAPER.md §0: server-to-server dispatch was left unimplemented): a
submission landing on a plane that cannot accept it locally (queue
saturated, no live workers) is forwarded to a peer instead of bounced to
the client. Forwarding is bounded and loop-fenced by an explicit hop chain
(``X-DGI-Plane-Hops``): a plane whose id is already in the chain never
re-forwards, and the chain length is capped (``DGI_PLANE_FORWARD_MAX_HOPS``).

Everything here is OFF by default: a ``ServerState`` constructed without
plane arguments behaves byte-identically to the single-plane build (no new
response fields, no forwarding, claims stamp a NULL plane_id).
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import aiohttp

# hop-chain header: comma-separated plane_ids the submission already visited
HOPS_HEADER = "X-DGI-Plane-Hops"

_DEF_MAX_HOPS = int(os.environ.get("DGI_PLANE_FORWARD_MAX_HOPS", "2"))
_FORWARD_TIMEOUT_S = float(
    os.environ.get("DGI_PLANE_FORWARD_TIMEOUT_S", "5.0")
)


def _parse_chain(raw: Optional[str]) -> List[str]:
    if not raw:
        return []
    return [p.strip() for p in raw.split(",") if p.strip()][:16]


class PlaneCluster:
    """This replica's identity + its view of the plane cohort.

    ``enabled`` is True only when the deployment opted into multi-plane
    (a plane_id or peer list was configured); every caller gates its
    behavior change on it, which is what keeps the single-plane
    configuration byte-identical to the pre-cohort build.
    """

    def __init__(self, plane_id: Optional[str] = None,
                 peers: Optional[Sequence[str]] = None,
                 forward_max_hops: Optional[int] = None,
                 api_key: Optional[str] = None) -> None:
        self.enabled = bool(plane_id) or bool(peers)
        self.plane_id = plane_id or (
            f"plane-{uuid.uuid4().hex[:8]}" if self.enabled else None
        )
        self.peers: List[str] = [
            str(u).rstrip("/") for u in (peers or []) if u
        ]
        self.forward_max_hops = (
            _DEF_MAX_HOPS if forward_max_hops is None
            else max(0, int(forward_max_hops))
        )
        self._api_key = api_key
        self._session: Optional[aiohttp.ClientSession] = None
        # counters surfaced through /metrics (record_request) and /health
        self.stats: Dict[str, int] = {
            "forwarded": 0, "forward_failed": 0,
            "received_forwarded": 0, "loop_fenced": 0,
        }

    # -- claim stamping -----------------------------------------------------

    @property
    def claim_stamp(self) -> Optional[str]:
        """plane_id written on claims this replica brokers (None when the
        cohort is disabled — the column stays NULL, as single-writer)."""
        return self.plane_id if self.enabled else None

    # -- forwarding ---------------------------------------------------------

    def may_forward(self, chain: Sequence[str]) -> bool:
        """Loop fence + hop bound: forward only when the cohort is enabled,
        a peer exists, our own id is not already in the chain (loop), and
        the chain has hops left."""
        if not (self.enabled and self.peers):
            return False
        if self.plane_id in chain:
            self.stats["loop_fenced"] += 1
            return False
        if len(chain) >= self.forward_max_hops:
            return False
        return True

    def note_received(self, chain: Sequence[str]) -> None:
        if chain:
            self.stats["received_forwarded"] += 1

    async def _ensure_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=_FORWARD_TIMEOUT_S)
            )
        return self._session

    async def forward_job(
        self, body: Dict[str, Any], chain: Sequence[str],
        sync: bool = False,
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        """POST the submission to the first peer that accepts it.

        Returns ``(status, payload)`` from the accepting peer — any
        definitive answer (2xx, or a 4xx the client caused) is relayed
        verbatim. Peers that are down (transport error) or themselves
        capacity-rejecting (429/503) are skipped; None means every peer
        declined and the caller should return its own local rejection.
        """
        if not self.may_forward(chain):
            return None
        new_chain = ",".join([*chain, str(self.plane_id)])
        headers = {HOPS_HEADER: new_chain}
        if self._api_key:
            headers["X-API-Key"] = self._api_key
        path = "/api/v1/jobs/sync" if sync else "/api/v1/jobs"
        session = await self._ensure_session()
        for peer in self.peers:
            try:
                async with session.post(
                    peer + path, json=body, headers=headers
                ) as resp:
                    if resp.status in (429, 503):
                        continue     # peer has no capacity either
                    payload = await resp.json(content_type=None)
                    self.stats["forwarded"] += 1
                    if isinstance(payload, dict):
                        payload.setdefault("forwarded_via", self.plane_id)
                    return resp.status, payload
            except (aiohttp.ClientError, OSError, ValueError):
                continue             # dead/unreachable peer: try the next
        self.stats["forward_failed"] += 1
        return None

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
        self._session = None

    # -- introspection ------------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        return {
            "id": self.plane_id,
            "peers": list(self.peers),
            "forward_max_hops": self.forward_max_hops,
            "stats": dict(self.stats),
        }
