"""Control-plane REST API (aiohttp).

Endpoint parity with the reference's FastAPI surface:
- Jobs API     (``server/app/api/jobs.py``): create async/sync, get, cancel,
  direct-mode discovery, queue stats.
- Workers API  (``server/app/api/workers.py``): register (token issuance),
  heartbeat (config_changed flag), atomic next-job, complete, going-offline /
  offline, verify, refresh-token, remote config GET/PUT, list/detail with
  online-probability predictions.
- Admin API    (``server/app/api/admin.py``): dashboard/realtime stats,
  enterprise CRUD + API keys, usage summaries, bills, privacy/compliance.
- ``/health``, ``/regions`` (``server/app/main.py:99-121``), ``/metrics``
  (Prometheus text).

Auth model mirrors the reference (``workers.py:55-94``): Bearer token
verified against a salted hash with a 5-strike / 15-min lockout; optional
HMAC request signing; ``X-API-Key`` for the jobs/admin surface.
"""

from __future__ import annotations

import asyncio
import json
import os
import sqlite3
import time
import uuid
from typing import Any, Dict, Optional

from aiohttp import web

from ..utils.data_structures import JobStatus, WorkerState
from ..utils.prefixes import fingerprints_for_params, sanitize_fingerprints
from .admission import (
    TIER_PRIORITY_BOOST,
    AdmissionController,
    estimate_cost_tokens,
    tenant_of,
)
from .calibration import CostCalibration, MigrateHintTracker
from .flight_recorder import FlightRecorder
from .geo import GeoService
from .health import HealthService
from .observability import MetricsCollector, StructuredLogger, TracingManager
from .prefix_routing import (
    PrefixRegistry,
    RoutingConfig,
    decide_kv_route,
    route_flight_attrs,
)
from .replication import ReplicationPlanner
from .reliability import ReliabilityService
from .scheduler import (
    _MAX_DISTANCE,
    REGIONS,
    WEIGHTS,
    SmartScheduler,
    estimate_job_duration_s,
    graded_load_score,
    region_distance,
)
from .security import LockoutState, SecurityService
from .store import Store
from .plane_cluster import HOPS_HEADER, PlaneCluster, _parse_chain
from .pd_flow import PDFlowError, PDFlowService
from .task_guarantee import TaskGuaranteeBackgroundWorker, TaskGuaranteeService
from .usage import UsageService
from .privacy import EnterprisePrivacyService
from .worker_config import WorkerConfigService

API = "/api/v1"

# serialized heartbeat ``engine_stats`` beyond this is dropped (counted:
# heartbeat_payload_rejected_total{reason="engine_stats_oversize"}) — one
# misbehaving worker must not bloat the heartbeat path for the fleet
_ENGINE_STATS_MAX_BYTES = 128 * 1024


class ServerState:
    """Bundles the store + every fleet service; attached to the aiohttp app."""

    def __init__(self, db_path: str = ":memory:",
                 api_key: Optional[str] = None,
                 admin_key: Optional[str] = None,
                 require_signing: bool = False,
                 heartbeat_timeout_s: float = 90.0,
                 submit_queue_limit: int = 0,
                 plane_id: Optional[str] = None,
                 plane_peers: Optional[list] = None,
                 plane_forward_max_hops: Optional[int] = None) -> None:
        self.store = Store(db_path)
        # replicated control planes (round 15): this replica's identity +
        # peer membership. OFF unless plane_id/plane_peers are configured —
        # the default single-plane build is byte-identical (no new response
        # fields, NULL plane stamps, no forwarding).
        self.plane = PlaneCluster(
            plane_id=plane_id, peers=plane_peers,
            forward_max_hops=plane_forward_max_hops, api_key=api_key,
        )
        self.security = SecurityService()
        self.reliability = ReliabilityService(self.store)
        self.metrics = MetricsCollector()
        # cache-aware routing: per-worker radix summaries (heartbeat
        # engine_stats channel) + the live-pushable routing knobs the
        # scheduler/direct-discovery affinity terms read
        self.routing = RoutingConfig()
        self.prefix_registry = PrefixRegistry(self.routing)
        # cost-model self-calibration (round 20): per-worker online
        # estimators fed from flight-trace phase durations and
        # kv_migrate counter deltas. Accumulates always (cheap, bounded);
        # decide_kv_route only READS measured values while
        # routing.calibrate is on — off keeps the static priors verbatim.
        self.calibration = CostCalibration(self.routing)
        # in-flight migrate-pull pressure per cold worker: fixes the
        # blind spot where a target already running its full pull budget
        # was priced as idle (hints expire after migrate_hint_window_s)
        self.migrate_hints = MigrateHintTracker(self.routing)
        # proactive prefix replication (round 20): discovery-time heat
        # tracking + heartbeat-response hints. Gated on routing.replicate
        # at every call site, so off costs nothing.
        self.replication = ReplicationPlanner(self.routing,
                                              self.prefix_registry)
        self.scheduler = SmartScheduler(
            self.store, self.reliability,
            prefix_registry=self.prefix_registry, metrics=self.metrics,
        )
        self.scheduler.attach_calibration(self.calibration,
                                          self.migrate_hints)
        # claims brokered by this replica carry its plane_id (NULL when the
        # cohort is disabled) — the audit trail behind the epoch fence
        self.scheduler.plane_id = self.plane.claim_stamp
        self.pd_flow = PDFlowService(self.store, metrics=self.metrics)
        self.guarantee = TaskGuaranteeService(
            self.store, self.reliability, heartbeat_timeout_s,
            # sweeps that permanently fail a PD stage child must fail its
            # container promptly (and cancel orphaned siblings) instead of
            # stranding the parent until its own timeout
            on_permanent_failure=self.pd_flow.on_job_permanently_failed,
            # partition staleness: the moment a worker is marked offline
            # (self-reported, admin, or heartbeat sweep) its advertised
            # prefix summary is zeroed — affinity must never keep routing
            # at a dead warm worker while its staleness TTL runs down
            on_worker_offline=self._invalidate_prefix_summary,
        )
        self.background = TaskGuaranteeBackgroundWorker(self.guarantee)
        self.geo = GeoService()
        self.worker_config = WorkerConfigService(self.store)
        if submit_queue_limit:
            # end-to-end backpressure: POST /jobs beyond this queue depth
            # answers 429 + Retry-After instead of growing the queue
            # silently (threshold lives on the fleet-default LoadControl —
            # the same policy object the claim-side admission enforces)
            self.worker_config.set_submit_queue_limit(submit_queue_limit)
        self.usage = UsageService(self.store)
        # SLO-native overload control (round 12): per-tenant token-bucket
        # budgets + the degrade-before-reject ladder. Disabled by default
        # (untiered fleets keep the blanket backpressure path verbatim);
        # flipped/retuned live via GET/PUT /api/v1/admin/admission.
        self.admission = AdmissionController(metrics=self.metrics)
        self.privacy = EnterprisePrivacyService(self.store)
        # console export is env-driven (DGI_OTEL_CONSOLE) — the knob was
        # previously unreachable (no caller could ever enable it)
        self.tracing = TracingManager()
        # request flight recorder (round 14): merged per-request timelines
        # — server admission/route/claim/complete events plus worker-side
        # events shipped through results and heartbeats. Always-on and
        # advisory: every recorder call is wrapped so it can never fail or
        # reorder a request.
        self.flight = FlightRecorder(metrics=self.metrics,
                                     tracing=self.tracing,
                                     calibration=self.calibration)
        self.scheduler.attach_flight(self.flight)
        # gray-failure defense (round 18): windowed per-worker health
        # scores + the healthy→suspect→quarantined→probation machine.
        # Disabled by default (discovery/claim stay byte-identical);
        # flipped/retuned live via GET/PUT /api/v1/admin/health.
        self.health = HealthService(
            on_transition=lambda wid, frm, to:
                self.metrics.record_health_transition(frm, to)
        )
        self.scheduler.attach_health(self.health)
        self.log = StructuredLogger("dgi-tpu.server")
        self.api_key = api_key
        self.admin_key = admin_key or api_key
        self.require_signing = require_signing
        # serializes reserve→issue→upsert in register_worker: a retry racing
        # its own slow original must not interleave, or the store could end
        # up holding the ORIGINAL's token hashes while the client keeps the
        # retry's tokens (instant lockout spiral)
        self.register_lock = asyncio.Lock()
        # short-TTL queue-stats cache for the backpressure check: a 429
        # FLOOD (the case backpressure exists for) must not pay two
        # GROUP BY table scans per rejected request. Accepted submissions
        # invalidate it, so admission decisions always see fresh depth.
        self._bp_cache: Optional[tuple] = None   # (expires_at, stats)
        self.started_at = time.time()

    async def _invalidate_prefix_summary(self, worker_id: str,
                                         reason: str) -> None:
        """Offline-worker hook: drop the in-memory summary (counted) and
        its persisted warm-start row, so neither live scoring nor a
        control-plane restart resurrects a dead worker's affinity."""
        if self.prefix_registry.invalidate_worker(
            worker_id, reason=reason, metrics=self.metrics
        ):
            try:
                await self.store.delete_prefix_summary(worker_id)
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass

    def bp_cache_clear(self) -> None:
        """Invalidate the backpressure queue-stats cache — called after any
        accepted job creation so the next admission check reads the real
        queue depth (rejections leave the depth unchanged, so the cache
        stays valid through a rejection storm)."""
        self._bp_cache = None


def _state(request: web.Request) -> ServerState:
    return request.app["state"]


def _stamp_trace(body: Dict[str, Any]) -> str:
    """Ensure the submission carries a ``trace_id`` (client-supplied on
    the body or params, minted otherwise) and stamp it into params so it
    rides the job to workers — PD stage children inherit parent params,
    so one trace spans the whole disaggregated flow. Returns the id."""
    params = body.get("params")
    if not isinstance(params, dict):
        params = {}
        body["params"] = params
    tid = body.get("trace_id") or params.get("trace_id")
    if not isinstance(tid, str) or not tid:
        tid = uuid.uuid4().hex[:16]
    params["trace_id"] = str(tid)[:64]
    return params["trace_id"]


def _log_submission(st: ServerState, trace_id: str,
                    body: Dict[str, Any], **extra: Any) -> None:
    """One-request-one-id greppability: server logs for this submission
    (and everything later code logs through a bound child) carry the
    trace id + admitted tenant/tier."""
    params = body.get("params") or {}
    st.log.bind(
        trace_id=trace_id,
        **({"tenant": params["tenant"]} if params.get("tenant") else {}),
        **({"tier": params["tier"]} if params.get("tier") else {}),
    ).info("job_submitted", job_type=body.get("type") or "llm", **extra)


def _flight_note(st: ServerState, trace_id: Optional[str], event: str,
                 job_id: Optional[str] = None, **attrs: Any) -> None:
    """Advisory server-side flight event: the recorder can NEVER fail or
    reorder a request, so every call is fenced here."""
    try:
        st.flight.note(trace_id, event, job_id=job_id, **attrs)
    except Exception:  # noqa: BLE001 — recorder is advisory by contract
        pass


def _json_error(status: int, detail: str,
                retry_after_s: Optional[float] = None,
                error_code: Optional[str] = None) -> web.Response:
    """JSON error body; capacity-style rejections (429/503) carry a
    machine-readable ``retry_after_s`` in the body AND the standard
    ``Retry-After`` header, so the SDK has ONE retry contract for both.
    ``error_code`` names the degradation class (``store_unavailable``)
    so clients can distinguish a browned-out durable tier from plain
    capacity without parsing the human-readable detail."""
    body: Dict[str, Any] = {"detail": detail}
    headers = None
    if error_code is not None:
        body["error_code"] = error_code
    if retry_after_s is not None:
        body["retry_after_s"] = round(float(retry_after_s), 3)
        headers = {"Retry-After": str(max(1, int(-(-retry_after_s // 1))))}
    return web.json_response(body, status=status, headers=headers)


def _store_unavailable(st: "ServerState", exc: Exception) -> web.Response:
    """Typed degraded-mode rejection for a failed store WRITE (round 19):
    a wedged/full backing store must bounce submissions with a retryable
    503 + ``error_code="store_unavailable"`` — not an opaque 500 — while
    read paths keep serving from the intact database. Flags the
    ``store_degraded`` gauge; the next successful write clears it."""
    st.metrics.record_store_degraded(True)
    return _json_error(
        503, f"job store unavailable: {exc}",
        retry_after_s=2.0, error_code="store_unavailable",
    )


async def _submit_backpressure(st: ServerState) -> Optional[web.Response]:
    """Queue-depth admission control for job submission: when the queue is
    saturated (fleet-default ``LoadControl.max_queue_depth``), reject with
    429 + Retry-After derived from current queue stats — real backpressure
    instead of silent queue growth. Returns None when the job may enter."""
    if st.worker_config.submit_queue_limit <= 0:
        return None    # backpressure disabled: skip the queue-stats scans
    queued, active = await _queue_snapshot(st)
    ok, retry_after = st.worker_config.should_accept_submission(
        queued, active
    )
    if ok:
        return None
    st.metrics.record_request("backpressure", "rejected")
    return _json_error(
        429,
        f"queue saturated ({queued} jobs queued); retry after "
        f"{retry_after:.1f}s",
        retry_after_s=retry_after,
    )


async def _queue_snapshot(st: ServerState) -> tuple:
    """(queued, active_workers) through the short-TTL backpressure cache —
    admission decisions under a rejection flood must not pay two GROUP BY
    scans per rejected request (same contract as _submit_backpressure)."""
    now = time.time()
    if st._bp_cache is not None and st._bp_cache[0] > now:
        stats = st._bp_cache[1]
    else:
        stats = await st.store.queue_stats()
        st._bp_cache = (now + 0.25, stats)
    queued = int(stats.get("queued") or 0)
    workers = stats.get("workers") or {}
    active = int(workers.get("idle") or 0) + int(workers.get("busy") or 0)
    return queued, active


async def _admit_submission(st: ServerState, body: Dict[str, Any]
                            ) -> Optional[web.Response]:
    """Overload control for job submission with the admission controller
    ENABLED (callers keep the legacy ``_submit_backpressure`` — which
    runs BEFORE body parsing, so a rejection flood never pays a JSON
    parse — on the disabled path): the submission runs down the
    per-tenant degrade/shed ladder. A shed answers 429 + Retry-After
    (same machine-readable contract); a degrade MUTATES the body in
    place (``max_tokens`` clamp, ``speculative`` off) and stamps
    tenant/tier/priority-boost so workers and usage metering see the
    tier the plane admitted."""
    tenant, tier = tenant_of(body)
    params = body.get("params")
    if not isinstance(params, dict):
        params = {}
        body["params"] = params
    queued, active = await _queue_snapshot(st)
    decode = int(params.get("max_new_tokens") or params.get("max_tokens")
                 or 256)
    decision = st.admission.decide(
        tenant, tier, estimate_cost_tokens(params),
        queued, active, st.worker_config, decode_tokens=decode,
    )
    # admission decision on the request's timeline (shed included — the
    # trace then records WHY nothing else ever happened to it)
    _flight_note(st, params.get("trace_id"), "server.admission",
                 **decision.flight_attrs())
    if not decision.admitted:
        st.metrics.record_request("backpressure", "rejected")
        return _json_error(
            429,
            f"overloaded: {decision.reason}; retry after "
            f"{decision.retry_after_s:.1f}s",
            retry_after_s=decision.retry_after_s,
        )
    if decision.max_tokens is not None:
        # graceful degradation rung 1: clamp the decode ask (reported
        # back to the client via the result's finish_reason/usage — the
        # request still completes, just shorter)
        for key in ("max_new_tokens", "max_tokens"):
            if params.get(key) is not None:
                params[key] = min(int(params[key]), decision.max_tokens)
        params.setdefault("max_new_tokens", decision.max_tokens)
        params["degraded_max_tokens"] = decision.max_tokens
    if decision.disable_spec:
        # rung 2: vanilla decode — drafting spends compute the fleet no
        # longer has at this saturation
        params["speculative"] = False
    # the tier the plane admitted rides the job: workers place it in the
    # batcher's priority/EDF heap, usage metering bills the right bucket
    params.setdefault("tenant", tenant)
    params["tier"] = decision.tier
    body["priority"] = int(body.get("priority") or 0) \
        + TIER_PRIORITY_BOOST.get(decision.tier, 0)
    return None


# ---------------------------------------------------------------------------
# auth helpers
# ---------------------------------------------------------------------------


def _check_api_key(request: web.Request) -> Optional[web.Response]:
    st = _state(request)
    if st.api_key and request.headers.get("X-API-Key") != st.api_key:
        return _json_error(401, "invalid API key")
    return None


def _check_admin_key(request: web.Request) -> Optional[web.Response]:
    st = _state(request)
    if st.admin_key and request.headers.get("X-Admin-Key") != st.admin_key:
        return _json_error(401, "invalid admin key")
    return None


async def _auth_worker(request: web.Request, worker_id: str
                       ) -> tuple[Optional[Dict[str, Any]], Optional[web.Response]]:
    """Bearer-token auth with lockout; returns (worker_row, error_response).

    Callers MUST test the error with ``is not None`` — ``web.Response``
    subclasses Mapping, so an empty 401/423 response is FALSY and a
    truthiness check silently waves the request through unauthenticated.
    """
    st = _state(request)
    w = await st.store.get_worker(worker_id)
    if w is None:
        return None, _json_error(404, "worker not found")
    lock = LockoutState(
        failed_attempts=int(w.get("failed_auth_attempts") or 0),
        last_failed=w.get("last_failed_auth"),
        locked_until=w.get("locked_until"),
    )
    if st.security.lockout.is_locked(lock):
        return None, _json_error(423, "worker locked out")
    auth = request.headers.get("Authorization", "")
    token = auth[7:] if auth.startswith("Bearer ") else ""
    ok = st.security.tokens.verify(
        token, w.get("auth_token_hash"), w.get("token_expires_at")
    )
    if not ok:
        lock = st.security.lockout.record_failure(lock)
        await st.store.update_worker(
            worker_id,
            failed_auth_attempts=lock.failed_attempts,
            last_failed_auth=lock.last_failed,
            locked_until=lock.locked_until,
        )
        st.security.audit.log("auth_failed", actor=worker_id)
        return None, _json_error(401, "invalid token")
    if st.require_signing and w.get("signing_secret"):
        body = await request.read()
        sig_ok = st.security.signer.verify(
            w["signing_secret"], request.method, request.path, body,
            request.headers.get("X-Timestamp", ""),
            request.headers.get("X-Signature", ""),
        )
        if not sig_ok:
            return None, _json_error(401, "invalid signature")
    if w.get("failed_auth_attempts"):
        await st.store.update_worker(
            worker_id, failed_auth_attempts=0, locked_until=None
        )
    return w, None


# ---------------------------------------------------------------------------
# workers API
# ---------------------------------------------------------------------------


async def register_worker(request: web.Request) -> web.Response:
    st = _state(request)
    body = await request.json()
    # the whole resolve→issue→upsert sequence runs under register_lock: a
    # retry racing its own slow original must not interleave, or the last
    # upsert could store the ORIGINAL's token hashes while the client keeps
    # the retry's tokens — every later call 401s into lockout
    async with st.register_lock:
        return await _register_worker_locked(st, body)


async def _register_worker_locked(st: ServerState,
                                  body: Dict[str, Any]) -> web.Response:
    worker_id = body.get("worker_id")
    fingerprint = body.get("machine_fingerprint")
    if not worker_id and fingerprint:
        # registration idempotency under a flapping server: a register whose
        # response was lost gets retried by the client — the retry must land
        # on the SAME row (keyed by machine fingerprint), not mint a
        # duplicate worker that would double fleet counts and strand the
        # first row's credentials. The reservation is atomic in the store,
        # so even a retry racing its own still-in-flight original resolves
        # to one row.
        worker_id = await st.store.reserve_worker_id_for_fingerprint(
            fingerprint, str(uuid.uuid4())
        )
    worker_id = worker_id or str(uuid.uuid4())
    # restart-with-reregistration: landing on a row that already completed
    # a registration (it holds issued credentials) AND looks dead (swept
    # offline, or heartbeat-silent past the timeout) means the previous
    # incarnation of this machine is gone — whatever it was RUNNING will
    # never complete. Requeue those jobs NOW (epoch bumps on the next
    # claim, fencing any zombie remnant) instead of stranding them until
    # the stale-job sweep's per-job timeout, and count the rejoin. A row
    # with a RECENT heartbeat is NOT treated as dead: a live worker
    # re-registers to recover from a credential blip (401 + failed
    # refresh), and destructively requeueing the work it is actively
    # generating would turn that blip into duplicate compute — its jobs
    # stay put, and the sweep covers the case where it really is dying.
    prior = await st.store.get_worker(worker_id)
    boot_id = body.get("boot_id")
    rejoined = False
    if prior is not None and prior.get("auth_token_hash") is not None:
        hb = prior.get("last_heartbeat")
        rejoined = (
            prior.get("status") == WorkerState.OFFLINE.value
            or hb is None
            or time.time() - float(hb) > st.guarantee._heartbeat_timeout_s
            # fast-restart fence: a NEW process (different boot_id) on the
            # same fingerprint proves the old incarnation is dead even when
            # the restart beat the heartbeat timeout — without this, its
            # RUNNING jobs strand until the job timeout (the fresh process
            # heartbeats happily, so no sweep ever fires)
            or (bool(boot_id) and bool(prior.get("boot_id"))
                and boot_id != prior.get("boot_id"))
        )
    bundle, stored = st.security.tokens.issue()
    row: Dict[str, Any] = {
        "id": worker_id,
        "name": body.get("name") or worker_id[:8],
        "region": body.get("region") or "unknown",
        "country": body.get("country"),
        "city": body.get("city"),
        "timezone": body.get("timezone"),
        "accelerator": body.get("accelerator") or "tpu",
        "chip_generation": body.get("chip_generation"),
        "num_chips": int(body.get("num_chips") or 1),
        "hbm_gb_per_chip": float(body.get("hbm_gb_per_chip") or 16.0),
        "topology": body.get("topology"),
        "mesh_shape": body.get("mesh_shape"),
        "cpu_cores": body.get("cpu_cores"),
        "ram_gb": body.get("ram_gb"),
        "supported_types": body.get("supported_types") or ["llm"],
        "loaded_models": body.get("loaded_models") or [],
        "status": WorkerState.IDLE.value,
        # validated: an unknown role string would poison PD placement later
        "role": body.get("role") if body.get("role") in (
            "prefill", "decode", "hybrid", "pipeline_stage"
        ) else "hybrid",
        "last_heartbeat": time.time(),
        "supports_direct": bool(body.get("supports_direct")),
        "direct_url": body.get("direct_url"),
        "data_plane_url": body.get("data_plane_url"),
        "machine_fingerprint": fingerprint,
        "boot_id": boot_id,
        **stored,
    }
    await st.store.upsert_worker(row)
    if rejoined:
        st.metrics.record_worker_rejoin(worker_id)
        for job in await st.store.list_jobs(
            status=[JobStatus.RUNNING.value], worker_id=worker_id
        ):
            # conditional requeue via the guarantee layer: a completion
            # racing this re-registration keeps its terminal status
            await st.guarantee.requeue_job(job, reason="worker_reregistered")
        # the fresh process starts with a COLD cache: its pre-restart
        # summary must not keep earning affinity until the TTL expires
        await st._invalidate_prefix_summary(worker_id, "worker_reregistered")
    await st.reliability.start_session(worker_id)
    cfg = await st.worker_config.get_config(worker_id)
    st.security.audit.log("worker_registered", actor=worker_id)
    return web.json_response(
        {
            "worker_id": worker_id,
            **bundle.to_dict(),
            "config": cfg.to_dict(),
            "heartbeat_interval_s": 30,
        }
    )


async def _ingest_checkpoint(st: ServerState, worker_id: str,
                             cp: Dict[str, Any]) -> None:
    """Store one piggybacked generation checkpoint, fenced.

    ``kind=job`` entries land on the job row only while the job is still
    RUNNING on this worker at this assignment epoch — a zombie whose job
    was requeued (epoch bumped on the next claim) or taken over cannot
    poison the live assignment's resume state. ``kind=stream`` entries go
    to the stream_checkpoints table with the same epoch fence (the adopt
    path bumps it)."""
    kind = cp.get("kind")
    key = cp.get("key")
    epoch = int(cp.get("epoch") or 0)
    state = cp.get("state")
    if not key:
        st.metrics.record_checkpoint_rejected("malformed")
        return
    if kind == "job":
        job = await st.store.get_job(str(key))
        if job is None or job.get("worker_id") != worker_id:
            st.metrics.record_checkpoint_rejected("not_owner")
            return
        if int(job.get("assignment_epoch") or 0) != epoch:
            st.metrics.record_checkpoint_rejected("stale_epoch")
            return
        if job["status"] != JobStatus.RUNNING.value:
            st.metrics.record_checkpoint_rejected("not_running")
            return
        if state is not None:
            await st.store.update_job(str(key), checkpoint=state)
            st.metrics.record_checkpoint(worker_id)
        return
    if kind == "stream":
        if cp.get("done"):
            await st.store.delete_stream_checkpoint(
                str(key), worker_id, epoch
            )
            return
        ok = await st.store.save_stream_checkpoint(
            str(key), worker_id, epoch, state
        )
        if ok:
            st.metrics.record_checkpoint(worker_id)
        else:
            st.metrics.record_checkpoint_rejected("stale_epoch")
        return
    st.metrics.record_checkpoint_rejected("malformed")


async def heartbeat(request: web.Request) -> web.Response:
    worker_id = request.match_info["worker_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    body = await request.json() if request.can_read_body else {}
    fields: Dict[str, Any] = {"last_heartbeat": time.time()}
    for key in ("status", "hbm_used_gb", "loaded_models", "current_job_id"):
        if key in body:
            fields[key] = body[key]
    stale_job = False
    claimed = fields.get("current_job_id")
    if claimed:
        # a delayed/duplicate heartbeat can carry a claim the sweeps already
        # requeued (or another worker already finished): accepting it would
        # resurrect a phantom BUSY worker shadowing the real assignment
        job = await st.store.get_job(claimed)
        if job is None or job.get("worker_id") != worker_id:
            # requeued (worker_id cleared) or taken over: a true zombie
            stale_job = True
            fields["current_job_id"] = None
            if fields.get("status") == WorkerState.BUSY.value:
                fields["status"] = WorkerState.IDLE.value
        elif job["status"] != JobStatus.RUNNING.value:
            # terminal but still ours: the heartbeat thread raced our own
            # just-reported completion — drop the claim quietly, this is
            # NOT zombie work and must not trip the worker's stale alarm
            fields["current_job_id"] = None
            if fields.get("status") == WorkerState.BUSY.value:
                fields["status"] = WorkerState.IDLE.value
    stale_jobs: list = []
    extra_claims = body.get("active_job_ids")
    if isinstance(extra_claims, list):
        # a batcher-backed worker runs several jobs concurrently;
        # current_job_id carries only one of them — fence the REST of its
        # claims too, so a requeued/taken-over concurrent job is flagged
        # back instead of silently finishing as undetected zombie work
        jids = [jid for jid in extra_claims[:32]
                if isinstance(jid, str) and jid != claimed]
        jobs = await asyncio.gather(*(st.store.get_job(j) for j in jids))
        for jid, job in zip(jids, jobs):
            if job is None or job.get("worker_id") != worker_id:
                stale_jobs.append(jid)
    if w.get("status") == WorkerState.OFFLINE.value:
        # swept offline but evidently alive: revive (a heartbeat IS proof of
        # life) and open a fresh reliability session so online-time
        # accounting resumes. Counted as a fleet rejoin — the degradation
        # panel reads recovery from this counter.
        fields.setdefault("status", WorkerState.IDLE.value)
        await st.reliability.start_session(worker_id)
        st.metrics.record_worker_rejoin(worker_id)
    es = body.get("engine_stats")
    if isinstance(es, dict):
        # payload hygiene: the engine_stats side channel is worker-supplied
        # and unauthenticated in shape — cap its serialized size so one
        # misbehaving worker cannot bloat the heartbeat path (the summary
        # channel has its own per-entry cap on top of this)
        try:
            oversized = len(json.dumps(es)) > _ENGINE_STATS_MAX_BYTES
        except (TypeError, ValueError):
            oversized = True
        if oversized:
            st.metrics.record_heartbeat_payload_rejected(
                "engine_stats_oversize"
            )
            es = None
    else:
        es = None
    if es is not None:
        batcher = es.get("batcher")
        if isinstance(batcher, dict) and batcher.get("capacity"):
            # graded load for the scheduler: a batcher-backed worker runs
            # many jobs concurrently, so the binary BUSY signal lies —
            # persist the occupancy snapshot the scoring path grades from
            fields["load_stats"] = {
                "active_slots": batcher.get("active_slots"),
                "queue_depth": batcher.get("queue_depth"),
                "capacity": batcher.get("capacity"),
                "avg_occupancy": batcher.get("avg_occupancy"),
                "ts": time.time(),
            }
    await st.store.update_worker(worker_id, **fields)
    await st.reliability.update_online_pattern(worker_id, online=True)
    cps = body.get("checkpoints")
    if isinstance(cps, list):
        # crash-safe generation: workers piggyback portable generation
        # checkpoints on heartbeats. Each entry is fenced (assignment
        # epoch + ownership) and a malformed entry degrades to a skipped
        # sample — a failing checkpoint must never 500 the heartbeat (that
        # would get a LIVE worker swept offline).
        for cp in cps[:32]:
            if not isinstance(cp, dict):
                continue
            try:
                await _ingest_checkpoint(st, worker_id, cp)
            except Exception:  # noqa: BLE001
                st.metrics.record_checkpoint_rejected("malformed")
    summary_resync = None
    summary_rejected = False
    if es is not None:
        # speculation-efficiency counters ride the heartbeat (worker
        # main._spec_engine_stats) → /metrics surfaces accept-rate and
        # tokens-per-step per worker
        st.metrics.record_spec_engine(worker_id, es)
        # KV-pressure counters (preemptions / resumes / pressure events)
        # ride the same payload → per-worker preemption panels in /metrics
        st.metrics.record_pressure_engine(worker_id, es)
        # batcher serving stats (occupancy, queue depth, chunked
        # admissions, drain migrations) → per-worker batch-health panels
        batcher = es.get("batcher")
        if isinstance(batcher, dict):
            st.metrics.record_batcher_engine(worker_id, batcher)
        # PD handoff lifecycle counters (sender outcomes, piece retries,
        # receiver abort/purge reasons) → pd_handoffs_total{outcome} /
        # pd_handoff_bytes_total per worker
        pd = es.get("pd")
        if isinstance(pd, dict):
            st.metrics.record_pd_engine(worker_id, pd)
        # cluster-KV migration counters (pull outcomes, export service,
        # bytes) → kv_migrations_total{outcome} / kv_migration_bytes_total
        kvmig = es.get("kv_migrate")
        if isinstance(kvmig, dict):
            st.metrics.record_kv_migrate_engine(worker_id, kvmig)
            # self-calibration: per-tier pull_bytes/pull_ms deltas feed
            # the worker's measured handoff bandwidth (accumulates even
            # with calibrate off — flipping the flag uses warm estimates)
            try:
                st.calibration.ingest_kv_migrate(worker_id, kvmig)
            except Exception:  # noqa: BLE001 — advisory, never 500 a beat
                pass
        # spill-tier IO health (round 19): put/get errors, corrupt-entry
        # quarantines, breaker states → kv_spill_errors_total{tier} /
        # spill_quarantined_total{tier,reason} / io_breaker_state{tier}
        kvspill = es.get("kv_spill")
        if isinstance(kvspill, dict):
            st.metrics.record_kv_spill_engine(worker_id, kvspill)
        # direct-serving channel (round 18): cancelled hedge losers →
        # hedges_total{outcome=cancelled}; the latency samples riding
        # the same payload feed the HealthService below
        direct = es.get("direct")
        if isinstance(direct, dict):
            st.metrics.record_direct_engine(worker_id, direct)
        # flight-recorder channel: cumulative counters (delta-anchored,
        # restart re-anchors like every other engine payload) plus a
        # bounded ring of recently-completed stream timelines — direct
        # streams never pass complete_job, so their worker-side events
        # ship here. Ingest UNIONS events per (trace, source) keyed by
        # name+timestamp and returns False when nothing changed, so the
        # ring re-shipping on every beat (duplicate delivery) is a no-op
        # that cannot re-finalize a trace.
        fl = es.get("flight")
        if isinstance(fl, dict):
            st.metrics.record_flight_engine(worker_id, fl)
            recent = fl.get("recent")
            if isinstance(recent, list):
                for wire in recent[:16]:
                    try:
                        if st.flight.ingest_wire(worker_id, wire) and \
                                isinstance(wire, dict) and wire.get("done"):
                            st.flight.finalize(wire.get("trace_id"))
                    except Exception:  # noqa: BLE001 — never 500 a beat
                        pass
        ps = es.get("prefix_summary")
        if ps is not None:
            # cache-aware routing: the worker's advertised radix summary
            # (full snapshot or delta — runtime/prefix_summary.py wire
            # format). Validation/caps live in the registry; rejections
            # are counted and answered, never 500d.
            await st.prefix_registry.ensure_loaded(st.store)
            res = st.prefix_registry.ingest(worker_id, ps)
            summary_resync = res.resync
            # statically un-ingestable (wire version / fingerprint basis
            # skew): tell the worker explicitly, so it stops shipping
            # payloads this plane can never apply instead of ping-ponging
            # full snapshots forever
            summary_rejected = (not res.applied and not res.resync)
            if res.reason and res.reason != "summary_resync":
                # "summary_resync" is the PROTOCOL-NORMAL recovery path
                # (plane restart, lost heartbeat) — counting it here would
                # make the misbehaving-worker counter fire on every
                # restart; real rejections/truncations only
                st.metrics.record_heartbeat_payload_rejected(res.reason)
            if res.applied:
                try:
                    await st.prefix_registry.persist(worker_id, st.store)
                except Exception:  # noqa: BLE001 — persistence is warm-
                    pass           # start comfort, never heartbeat-fatal
    # gray-failure defense: every beat feeds the health score — direct
    # serving latencies/errors (es["direct"]) + the worker-measured
    # heartbeat round-trip (body["hb_rtt_ms"]) — and advances the
    # quarantine state machine. No-op (not even accumulation) while the
    # service is disabled.
    st.health.ingest(worker_id, es, body)
    if es is not None and es.get("prefix_summary_live"):
        # the worker declares its summary channel alive this beat (wire()
        # returns None while in sync, so no payload ≠ no summary): keep
        # its advertised state fresh — staleness means "stopped
        # heartbeating / restarted / channel disabled", not "stopped
        # serving new prefixes". A restarted worker that no longer ships
        # summaries omits the marker and ages out within one TTL.
        st.prefix_registry.touch(worker_id)
    replicate_hints = None
    if st.routing.enabled and st.routing.replicate:
        # proactive prefix replication: hot prefixes this worker does not
        # hold ride the response as pull hints. The store query runs only
        # while the flag is on; off keeps the beat byte-identical.
        try:
            srcs = await st.store.list_workers(
                status=[WorkerState.IDLE.value, WorkerState.BUSY.value]
            )
            hints = st.replication.hints_for(worker_id, srcs)
            if hints:
                replicate_hints = hints
                st.metrics.record_kv_replicate_hints(len(hints))
        except Exception:  # noqa: BLE001 — advisory, never 500 a beat
            pass
    client_version = int(body.get("config_version") or 0)
    changed = await st.worker_config.config_changed_since(
        worker_id, client_version
    )
    return web.json_response({
        "ok": True, "config_changed": changed, "stale_job": stale_job,
        **({"stale_jobs": stale_jobs} if stale_jobs else {}),
        **({"prefix_summary_resync": summary_resync}
           if summary_resync is not None else {}),
        **({"prefix_summary_applied": False} if summary_rejected else {}),
        # plane cohort (round 15): the replica answering this beat. The
        # worker watches for a CHANGE (its plane died, it failed over) and
        # resyncs a full prefix-summary snapshot — the new plane has no
        # ACKed delta base. Omitted single-plane: the response stays
        # byte-identical to the pre-cohort build.
        **({"plane_id": st.plane.plane_id} if st.plane.enabled else {}),
        # proactive replication (round 20): pull-ahead hints for prefixes
        # heating up that this worker does not advertise. Omitted unless
        # routing.replicate is on AND the planner found work — the beat
        # stays byte-identical otherwise.
        **({"kv_replicate": replicate_hints} if replicate_hints else {}),
    })


async def next_job(request: web.Request) -> web.Response:
    worker_id = request.match_info["worker_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    job = await st.scheduler.atomic_assign_job(worker_id)
    if job is None:
        return web.Response(status=204)  # no job (reference api_client.py:161)
    # server-side admission policy (reference worker_config.py:195): release
    # the claim without burning a retry if load control declines it
    import random as _random

    if not await st.worker_config.should_accept_job(
        worker_id, job["type"], rand=_random.random(),
        ignore_job_id=job["id"],
    ):
        # conditional release: between our claim and this decline a sweep
        # (or admin cancel) may have moved the job — an unconditional
        # overwrite would clobber another worker's fresh claim or revert a
        # terminal status back to QUEUED (stale-claim race under
        # concurrent failover)
        await st.store.try_transition_job(
            job["id"], JobStatus.RUNNING.value, owned_by=worker_id,
            status=JobStatus.QUEUED.value, worker_id=None,
            started_at=None,
        )
        await st.store.update_worker(
            worker_id, current_job_id=None, status=WorkerState.IDLE.value
        )
        return web.Response(status=204)
    # the claim lands on the request's timeline (+ an OTel span): queue
    # wait on the queued path is submitted → claimed
    trace_id = (job.get("params") or {}).get("trace_id") \
        if isinstance(job.get("params"), dict) else None
    if trace_id:
        with st.tracing.span("job.claim", trace_id=trace_id,
                             worker=worker_id):
            _flight_note(st, trace_id, "server.claimed",
                         job_id=job["id"], worker=worker_id)
    st.metrics.record_queue("queued", (await st.store.queue_stats())["queued"])
    return web.json_response({"job": job})


async def release_job(request: web.Request) -> web.Response:
    """Worker declines a claimed job (client-side load control): requeue it
    without burning a retry or recording a failure — any other worker can run
    it. Mirrors the server-side admission release in ``next_job``."""
    worker_id = request.match_info["worker_id"]
    job_id = request.match_info["job_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    job = await st.store.get_job(job_id)
    if job is None or job.get("worker_id") != worker_id:
        return _json_error(404, "job not assigned to this worker")
    if job["status"] == JobStatus.RUNNING.value:
        # conditional: a sweep requeue + another worker's re-claim can land
        # between our read and this write — releasing unconditionally
        # would yank the job out from under the NEW owner (stale-claim
        # race the fleet chaos suite drives via requeue storms)
        await st.store.try_transition_job(
            job_id, JobStatus.RUNNING.value, owned_by=worker_id,
            status=JobStatus.QUEUED.value, worker_id=None,
            started_at=None,
        )
    await st.store.update_worker(
        worker_id, current_job_id=None, status=WorkerState.IDLE.value
    )
    return web.json_response({"status": "released"})


async def complete_job(request: web.Request) -> web.Response:
    worker_id = request.match_info["worker_id"]
    job_id = request.match_info["job_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    job = await st.store.get_job(job_id)
    if job is None or job.get("worker_id") != worker_id:
        return _json_error(404, "job not assigned to this worker")
    body = await request.json()
    success = bool(body.get("success", True))
    # flight recorder: the worker's per-request timeline rides the result
    # payload — lift it off before the result is stored (the merged
    # timeline lands on the job row separately at finalize)
    flight_wire = None
    if isinstance(body.get("result"), dict):
        flight_wire = body["result"].pop("timeline", None)
    claimed_epoch = body.get("assignment_epoch")
    if claimed_epoch is not None and \
            int(claimed_epoch) != int(job.get("assignment_epoch") or 0):
        # zombie fence: the job was requeued/reclaimed since this worker's
        # assignment (every claim bumps assignment_epoch — even a reclaim
        # by the SAME worker, which the worker_id check above cannot see).
        # The late result is discarded; release this worker's capacity
        # claim so it doesn't sit phantom-BUSY.
        w2 = await st.store.get_worker(worker_id)
        if w2 is not None and w2.get("current_job_id") == job_id:
            fields: Dict[str, Any] = {"current_job_id": None}
            if w2.get("status") == WorkerState.BUSY.value:
                # only BUSY→IDLE: a DRAINING worker must stay draining or
                # the scheduler would hand fresh work to a process that is
                # seconds from exiting
                fields["status"] = WorkerState.IDLE.value
            await st.store.update_worker(worker_id, **fields)
        st.metrics.record_checkpoint_rejected("stale_epoch")
        return _json_error(
            409, f"stale assignment epoch {claimed_epoch} "
                 f"(job is at {job.get('assignment_epoch') or 0})"
        )

    async def _already_terminal(status: str) -> web.Response:
        # always release this worker's capacity claim on the job
        w2 = await st.store.get_worker(worker_id)
        if w2 is not None and w2.get("current_job_id") == job_id:
            await st.store.update_worker(
                worker_id, current_job_id=None, status=WorkerState.IDLE.value
            )
        expected = (
            JobStatus.COMPLETED.value if success else JobStatus.FAILED.value
        )
        if status == expected:
            # duplicate delivery (response lost → client retried, or the
            # request was replayed in flight): the first delivery already
            # applied the status change, reliability delta, and usage —
            # acknowledge idempotently, never double-apply
            return web.json_response({"ok": True, "duplicate": True})
        # late completion of a cancelled/requeued job: never overwrite the
        # terminal status or bill usage for it
        return _json_error(409, f"job is {status}, not running")

    if job["status"] != JobStatus.RUNNING.value:
        return await _already_terminal(job["status"])
    now = time.time()
    dur_ms = (
        (now - float(job["started_at"])) * 1000.0 if job.get("started_at") else None
    )
    # atomic RUNNING→terminal claim: of N concurrent duplicate deliveries
    # exactly ONE wins and applies the reliability/usage/PD effects below;
    # losers re-read the row and take the duplicate/conflict path above
    won = await st.store.try_transition_job(
        job_id, JobStatus.RUNNING.value, owned_by=worker_id,
        status=JobStatus.COMPLETED.value if success else JobStatus.FAILED.value,
        result=body.get("result"),
        error=body.get("error"),
        completed_at=now,
        actual_duration_ms=dur_ms,
    )
    if not won:
        job2 = await st.store.get_job(job_id)
        return await _already_terminal(
            job2["status"] if job2 is not None else "gone"
        )
    await st.store.update_worker(
        worker_id, current_job_id=None, status=WorkerState.IDLE.value
    )
    await st.reliability.record_event(
        worker_id,
        "job_completed" if success else "job_failed",
        latency_ms=dur_ms,
    )
    st.metrics.record_request(
        job["type"], "completed" if success else "failed",
        latency_s=(dur_ms or 0) / 1000.0,
    )
    job2 = await st.store.get_job(job_id)
    if success:
        await st.usage.record_job_usage(job2, enterprise_id=None)
    if job2 is not None and st.pd_flow.is_pd_child(job2):
        # advance the PD flow (prefill done → enqueue pinned decode child;
        # decode done → merge results into the parent container job)
        await st.pd_flow.on_child_complete(job2)
    await _flight_complete(st, job2 or job, job_id, worker_id, success,
                           flight_wire)
    return web.json_response({"ok": True})


async def _flight_complete(st: ServerState, job: Dict[str, Any],
                           job_id: str, worker_id: str, success: bool,
                           flight_wire: Any) -> None:
    """Completion-time flight-recorder fan-in: ingest the worker's
    result-borne events, stamp the completion, derive + observe phases
    (observe-once per phase — PD children compose: the prefill child's
    completion lands prefill/ttft, the decode child's lands decode/e2e),
    and persist the merged timeline with the job (the PD parent's row for
    stage children). Advisory end to end — any failure is swallowed."""
    try:
        params = job.get("params")
        trace_id = params.get("trace_id") \
            if isinstance(params, dict) else None
        if not trace_id:
            return
        if flight_wire is not None:
            st.flight.ingest_wire(worker_id, flight_wire)
        with st.tracing.span("job.complete", trace_id=trace_id,
                             worker=worker_id, success=success):
            _flight_note(st, trace_id, "server.completed", job_id=job_id,
                         worker=worker_id, success=success)
        # a PD prefill child's completion is NOT the end of the request:
        # defer e2e/decode/handoff observation to the decode child's
        # finalize (observe-once would otherwise lock in a prefill-only
        # e2e and permanently exclude decode time from the histograms)
        st.flight.finalize(trace_id, partial=(
            st.pd_flow.is_pd_child(job)
            and (params or {}).get("pd_stage") == "prefill"
        ))
        tl = st.flight.timeline(trace_id)
        if tl is None:
            return
        target = job_id
        if st.pd_flow.is_pd_child(job):
            target = str((params or {}).get("pd_parent") or job_id)
        await st.store.update_job(target, timeline={
            "trace_id": trace_id,
            "events": tl["events"],
            "phases": tl["phases"],
        })
    except Exception:  # noqa: BLE001 — the recorder can never fail a request
        pass


async def checkpoint_job(request: web.Request) -> web.Response:
    """Worker-pushed generation checkpoint for a RUNNING job — the
    graceful-drain migration path (``migrate=true`` additionally requeues
    the job WITHOUT burning a retry, so the next claimant resumes from the
    checkpoint instead of regenerating). Fenced by assignment epoch like
    every other checkpoint write."""
    worker_id = request.match_info["worker_id"]
    job_id = request.match_info["job_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    job = await st.store.get_job(job_id)
    if job is None or job.get("worker_id") != worker_id:
        return _json_error(404, "job not assigned to this worker")
    body = await request.json()
    epoch = int(body.get("assignment_epoch") or 0)
    if epoch != int(job.get("assignment_epoch") or 0):
        st.metrics.record_checkpoint_rejected("stale_epoch")
        return _json_error(
            409, f"stale assignment epoch {epoch} "
                 f"(job is at {job.get('assignment_epoch') or 0})"
        )
    if job["status"] != JobStatus.RUNNING.value:
        st.metrics.record_checkpoint_rejected("not_running")
        return _json_error(409, f"job is {job['status']}, not running")
    state = body.get("state")
    if state is not None:
        await st.store.update_job(job_id, checkpoint=state)
        st.metrics.record_checkpoint(worker_id)
    requeued = False
    if body.get("migrate"):
        # graceful migration: conditional RUNNING→QUEUED (a racing
        # completion keeps its terminal status), retry_count untouched —
        # a drain is not a failure. The checkpoint stays on the row; the
        # next claim bumps the epoch and resumes from it.
        requeued = await st.store.try_transition_job(
            job_id, JobStatus.RUNNING.value, owned_by=worker_id,
            status=JobStatus.QUEUED.value,
            worker_id=None,
            started_at=None,
        )
        w2 = await st.store.get_worker(worker_id)
        if w2 is not None and w2.get("current_job_id") == job_id:
            fields: Dict[str, Any] = {"current_job_id": None}
            if w2.get("status") == WorkerState.BUSY.value:
                fields["status"] = WorkerState.IDLE.value
            await st.store.update_worker(worker_id, **fields)
    return web.json_response({"ok": True, "requeued": requeued})


async def checkpoint_stream(request: web.Request) -> web.Response:
    """Worker-pushed checkpoint for a direct (queue-less) SSE stream —
    the per-token/periodic cadence between heartbeats. ``done=true``
    deletes the row when the stream finishes normally (fenced: a zombie's
    late "done" cannot erase the state its replacement resumes from)."""
    worker_id = request.match_info["worker_id"]
    stream_id = request.match_info["stream_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    body = await request.json()
    epoch = int(body.get("epoch") or 0)
    try:
        if body.get("done"):
            await st.store.delete_stream_checkpoint(
                stream_id, worker_id, epoch
            )
            return web.json_response({"ok": True, "deleted": True})
        ok = await st.store.save_stream_checkpoint(
            stream_id, worker_id, epoch, body.get("state")
        )
    except sqlite3.OperationalError as exc:
        # a dark store costs checkpoint STALENESS, never an opaque 500:
        # the worker's pusher treats any failure as a skipped push and
        # the next cadence retries (typed so it shows up in SDK traces)
        return _store_unavailable(st, exc)
    st.metrics.record_store_degraded(False)
    if not ok:
        st.metrics.record_checkpoint_rejected("stale_epoch")
        return _json_error(
            409, f"stale stream epoch {epoch} for {stream_id}"
        )
    st.metrics.record_checkpoint(worker_id)
    return web.json_response({"ok": True})


async def adopt_stream(request: web.Request) -> web.Response:
    """Failover worker adopts a dropped stream's checkpoint: atomically
    bumps the epoch (fencing the previous owner's late writes out) and
    returns the latest state so the adopter resumes via
    ``TPUEngine.resume()`` and splices the continuation at the client's
    offset."""
    worker_id = request.match_info["worker_id"]
    stream_id = request.match_info["stream_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    row = await st.store.adopt_stream_checkpoint(stream_id, worker_id)
    if row is None:
        return _json_error(404, f"no checkpoint for stream {stream_id}")
    st.metrics.record_stream_failover()
    return web.json_response({
        "stream_id": stream_id,
        "checkpoint": row["state"],
        "epoch": row["epoch"],
    })


async def going_offline(request: web.Request) -> web.Response:
    worker_id = request.match_info["worker_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    await st.store.update_worker(worker_id, status=WorkerState.DRAINING.value)
    return web.json_response({"ok": True, "drain": True})


async def offline(request: web.Request) -> web.Response:
    worker_id = request.match_info["worker_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    requeued = await st.guarantee.handle_worker_offline(worker_id, graceful=True)
    return web.json_response({"ok": True, "requeued_jobs": requeued})


async def verify_worker(request: web.Request) -> web.Response:
    worker_id = request.match_info["worker_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    return web.json_response({"ok": True, "worker_id": worker_id})


async def refresh_token(request: web.Request) -> web.Response:
    worker_id = request.match_info["worker_id"]
    st = _state(request)
    w = await st.store.get_worker(worker_id)
    if w is None:
        return _json_error(404, "worker not found")
    body = await request.json()
    if not st.security.tokens.verify(
        body.get("refresh_token", ""), w.get("refresh_token_hash")
    ):
        return _json_error(401, "invalid refresh token")
    bundle, stored = st.security.tokens.issue()
    await st.store.update_worker(worker_id, **stored)
    st.security.audit.log("token_refreshed", actor=worker_id)
    return web.json_response({"worker_id": worker_id, **bundle.to_dict()})


async def get_worker_config(request: web.Request) -> web.Response:
    worker_id = request.match_info["worker_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    cfg = await st.worker_config.get_config(worker_id)
    await st.store.update_worker(worker_id, last_config_sync=time.time())
    return web.json_response(cfg.to_dict())


async def put_worker_config(request: web.Request) -> web.Response:
    worker_id = request.match_info["worker_id"]
    w, err = await _auth_worker(request, worker_id)
    if err is not None:
        return err
    st = _state(request)
    updates = await request.json()
    cfg = await st.worker_config.update_config(worker_id, updates)
    return web.json_response(cfg.to_dict())


async def list_workers(request: web.Request) -> web.Response:
    if (err := _check_api_key(request)) is not None:
        return err
    st = _state(request)
    workers = await st.store.list_workers()
    out = []
    for w in workers:
        out.append(
            {
                "id": w["id"], "name": w["name"], "region": w["region"],
                "status": w["status"], "role": w.get("role"),
                "accelerator": w.get("accelerator"),
                "chip_generation": w.get("chip_generation"),
                "num_chips": w.get("num_chips"),
                "reliability_score": w.get("reliability_score"),
                "online_probability": st.reliability.predict_online_probability(w),
                "supported_types": w.get("supported_types"),
                "loaded_models": w.get("loaded_models"),
                "last_heartbeat": w.get("last_heartbeat"),
            }
        )
    return web.json_response({"workers": out, "total": len(out)})


async def worker_detail(request: web.Request) -> web.Response:
    if (err := _check_api_key(request)) is not None:
        return err
    st = _state(request)
    w = await st.store.get_worker(request.match_info["worker_id"])
    if w is None:
        return _json_error(404, "worker not found")
    for secret in ("auth_token_hash", "refresh_token_hash", "signing_secret"):
        w.pop(secret, None)
    w["online_probability"] = st.reliability.predict_online_probability(w)
    w["predicted_remaining_minutes"] = st.reliability.predict_remaining_online_time(w)
    return web.json_response(w)


# ---------------------------------------------------------------------------
# jobs API
# ---------------------------------------------------------------------------


async def _make_job_row(request: web.Request, body: Dict[str, Any]
                        ) -> Dict[str, Any]:
    st = _state(request)
    client_ip = request.headers.get("X-Forwarded-For", request.remote or "")
    client_ip = client_ip.split(",")[0].strip()
    client_region = await st.geo.detect_client_region(client_ip)
    # cache-aware routing: the job row carries the request's prefix
    # boundary fingerprints — client-supplied (SDK prefix_hint / auto)
    # wins, server-side computation from the prompt/messages is the
    # fallback. Advisory: an empty list just means locality-blind.
    fps: list = []
    if st.routing.enabled and (body.get("type") or "llm") == "llm":
        fps = sanitize_fingerprints(
            body.get("prefix_fps"), st.routing.max_fps_per_request
        )
        if not fps:
            fps = fingerprints_for_params(
                body.get("params"), st.routing.block_chars,
                st.routing.max_fps_per_request,
            )
    return {
        "type": body.get("type") or "llm",
        "params": body.get("params") or {},
        **({"prefix_fps": fps} if fps else {}),
        "priority": int(body.get("priority") or 0),
        "preferred_region": body.get("preferred_region") or client_region,
        "allow_cross_region": bool(body.get("allow_cross_region", True)),
        "client_ip": client_ip or None,
        "client_region": client_region,
        "timeout_seconds": float(body.get("timeout_seconds") or 300.0),
        "max_retries": int(body.get("max_retries") or 3),
    }


async def _forward_or(st: ServerState, request: web.Request,
                      body: Dict[str, Any], local: web.Response,
                      sync: bool = False) -> web.Response:
    """Capacity rejection path with plane forwarding: before bouncing the
    client, offer the submission to a peer plane (bounded hops, loop
    fence — server/plane_cluster.py). A peer's definitive answer is
    relayed; when every peer declines too, the LOCAL rejection stands, so
    single-plane behavior (and the retry contract) is unchanged."""
    chain = _parse_chain(request.headers.get(HOPS_HEADER))
    fwd = await st.plane.forward_job(body, chain, sync=sync)
    if fwd is None:
        return local
    status, payload = fwd
    st.metrics.record_request("plane_forward", "sent")
    return web.json_response(payload, status=status)


async def create_job(request: web.Request) -> web.Response:
    if (err := _check_api_key(request)) is not None:
        return err
    st = _state(request)
    st.plane.note_received(_parse_chain(request.headers.get(HOPS_HEADER)))
    if not st.admission.cfg.enabled:
        # ladder OFF: the pre-round-12 blanket backpressure, still run
        # BEFORE body parsing so a 429 flood stays parse-free
        if (bp := await _submit_backpressure(st)) is not None:
            if not st.plane.enabled:
                return bp
            return await _forward_or(st, request, await request.json(), bp)
    body = await request.json()
    trace_id = _stamp_trace(body)
    if st.admission.cfg.enabled and \
            (bp := await _admit_submission(st, body)) is not None:
        return bp
    _log_submission(st, trace_id, body)
    row = await _make_job_row(request, body)
    if (row.get("params") or {}).get("pd_disaggregated"):
        # PD container job: created RUNNING (never claimable); the flow
        # service places prefill/decode and enqueues the pinned stage jobs
        row["status"] = JobStatus.RUNNING.value
        row["started_at"] = time.time()
        try:
            with st.tracing.span("job.submit", trace_id=trace_id, pd=True):
                job_id = await st.store.create_job(row)
        except sqlite3.OperationalError as exc:
            return _store_unavailable(st, exc)
        st.metrics.record_store_degraded(False)
        st.bp_cache_clear()
        _flight_note(st, trace_id, "server.submitted", job_id=job_id,
                     pd=True)
        job = await st.store.get_job(job_id)
        try:
            await st.pd_flow.submit(job)
        except PDFlowError as exc:
            await st.store.update_job(
                job_id, status=JobStatus.FAILED.value, error=str(exc),
                completed_at=time.time(),
            )
            # machine-readable retry hint: PD placement failures are
            # capacity problems (no prefill/decode pair free) — same retry
            # contract as the 429 backpressure path
            return _json_error(503, str(exc), retry_after_s=5.0)
        except Exception as exc:  # noqa: BLE001 — parent must not strand
            await st.store.update_job(
                job_id, status=JobStatus.FAILED.value,
                error=f"pd placement error: {exc}",
                completed_at=time.time(),
            )
            return _json_error(500, f"pd placement error: {exc}")
        st.metrics.record_request(row["type"], "queued")
        return web.json_response(
            {"job_id": job_id, "status": "running", "pd": True}, status=201
        )
    try:
        with st.tracing.span("job.submit", trace_id=trace_id):
            job_id = await st.store.create_job(row)
    except sqlite3.OperationalError as exc:
        return _store_unavailable(st, exc)
    st.metrics.record_store_degraded(False)
    st.bp_cache_clear()
    _flight_note(st, trace_id, "server.submitted", job_id=job_id)
    st.metrics.record_request(row["type"], "queued")
    return web.json_response({"job_id": job_id, "status": "queued"}, status=201)


async def create_job_sync(request: web.Request) -> web.Response:
    """503 with no capacity; priority boost +10; long-poll for the result
    (reference jobs.py:116-181)."""
    if (err := _check_api_key(request)) is not None:
        return err
    st = _state(request)
    st.plane.note_received(_parse_chain(request.headers.get(HOPS_HEADER)))
    if not st.admission.cfg.enabled:
        if (bp := await _submit_backpressure(st)) is not None:
            if not st.plane.enabled:
                return bp
            return await _forward_or(
                st, request, await request.json(), bp, sync=True
            )
    body = await request.json()
    trace_id = _stamp_trace(body)
    if st.admission.cfg.enabled and \
            (bp := await _admit_submission(st, body)) is not None:
        return bp
    stats = await st.scheduler.get_queue_stats()
    if stats["active_workers"] == 0:
        # a fleet with zero live workers drains nothing: tell clients to
        # come back on the heartbeat-revival timescale, not instantly —
        # unless a peer plane can take the job right now
        return await _forward_or(
            st, request, body,
            _json_error(503, "no workers available", retry_after_s=10.0),
            sync=True,
        )
    _log_submission(st, trace_id, body, sync=True)
    row = await _make_job_row(request, body)
    row["priority"] = row["priority"] + 10
    try:
        with st.tracing.span("job.submit", trace_id=trace_id, sync=True):
            job_id = await st.store.create_job(row)
    except sqlite3.OperationalError as exc:
        return _store_unavailable(st, exc)
    st.metrics.record_store_degraded(False)
    st.bp_cache_clear()
    _flight_note(st, trace_id, "server.submitted", job_id=job_id,
                 sync=True)
    timeout = min(float(body.get("timeout_seconds") or 120.0), 300.0)
    job = await st.guarantee.wait_for_job(job_id, timeout_s=timeout)
    if job is None:
        return _json_error(404, "job vanished")
    if job["status"] != JobStatus.COMPLETED.value:
        return web.json_response(
            {"job_id": job_id, "status": job["status"], "error": job.get("error")},
            status=504 if job["status"] == JobStatus.RUNNING.value else 500,
        )
    return web.json_response(
        {"job_id": job_id, "status": job["status"], "result": job.get("result")}
    )


async def get_job(request: web.Request) -> web.Response:
    if (err := _check_api_key(request)) is not None:
        return err
    st = _state(request)
    job = await st.store.get_job(request.match_info["job_id"])
    if job is None:
        return _json_error(404, "job not found")
    return web.json_response(job)


async def cancel_job(request: web.Request) -> web.Response:
    if (err := _check_api_key(request)) is not None:
        return err
    st = _state(request)
    job_id = request.match_info["job_id"]
    job = await st.store.get_job(job_id)
    if job is None:
        return _json_error(404, "job not found")
    if job["status"] in (JobStatus.COMPLETED.value, JobStatus.FAILED.value):
        return _json_error(409, f"job already {job['status']}")
    await st.store.update_job(
        job_id, status=JobStatus.CANCELLED.value, completed_at=time.time()
    )
    wid = job.get("worker_id")
    if wid:  # free the assigned worker's capacity state
        w = await st.store.get_worker(wid)
        if w is not None and w.get("current_job_id") == job_id:
            await st.store.update_worker(
                wid, current_job_id=None, status=WorkerState.IDLE.value
            )
    if (job.get("params") or {}).get("pd_disaggregated"):
        # cancelling a PD container must not orphan its pinned stage jobs:
        # on_parent_terminal cancels queued children (a RUNNING child
        # finishes on its worker and the completion hook finds the parent
        # terminal — no-op) and releases the scheduler placement
        await st.pd_flow.on_parent_terminal(job_id)
    return web.json_response({"job_id": job_id, "status": "cancelled"})


async def nearest_direct_worker(request: web.Request) -> web.Response:
    """Direct-mode discovery: closest direct-capable idle worker
    (reference jobs.py:282-338)."""
    if (err := _check_api_key(request)) is not None:
        return err
    st = _state(request)
    client_ip = (request.headers.get("X-Forwarded-For", request.remote or "")
                 .split(",")[0].strip())
    region = request.query.get("region") or await st.geo.detect_client_region(
        client_ip
    )
    # ``exclude``: comma-separated worker ids the client just watched fail
    # (dropped stream / refused connection) — a failover reconnect must not
    # be handed straight back to the worker that died on it while the
    # heartbeat sweep is still counting down
    exclude = {
        e for e in (request.query.get("exclude") or "").split(",") if e
    }
    # batcher-backed workers serve many requests concurrently and report
    # BUSY while doing so — they stay discoverable as long as their graded
    # load shows headroom (legacy workers keep the IDLE-only contract)
    workers = await st.store.list_workers(
        status=[WorkerState.IDLE.value, WorkerState.BUSY.value]
    )
    now = time.time()
    # grade each worker's load ONCE — the filter, the score loop, and the
    # sort key all reuse it (graded_load_score json-decodes load_stats)
    headroom = {w["id"]: graded_load_score(w, now=now) for w in workers}
    cands = [
        w for w in workers
        if w.get("supports_direct") and w.get("direct_url")
        and w["id"] not in exclude
        and (w.get("status") == WorkerState.IDLE.value
             or headroom[w["id"]] > 0.0)
    ]
    if not cands:
        return _json_error(404, "no direct workers available")
    if st.health.enabled:
        # gray-failure defense: quarantined workers drop out of the
        # ranking (they still heartbeat, still serve /kv/export pulls,
        # still finish in-flight work). admissible() falls back to the
        # unfiltered list rather than answering 404 — availability beats
        # purity. Disabled (default): this block never runs and the
        # ranking below is byte-identical to the pre-health build.
        allowed = set(st.health.admissible([w["id"] for w in cands]))
        cands = [w for w in cands if w["id"] in allowed]
    # cache-aware routing: ``prefix_fps`` (comma-separated boundary
    # fingerprints, SDK-computed) ranks workers by advertised prefix
    # affinity — load-headroom-scaled so a hot cached replica spills over —
    # with region distance as the tiebreak. Advisory: no fingerprints (or
    # routing disabled) keeps the pure region sort.
    fps = sanitize_fingerprints(
        [s for s in (request.query.get("prefix_fps") or "").split(",") if s],
        st.routing.max_fps_per_request,
    )
    if fps and st.routing.enabled and st.routing.replicate:
        # proactive replication: every fingerprinted discovery feeds the
        # prefix heat tracker (bounded, lock-scoped; gated here so the
        # off path costs nothing)
        st.replication.note_query(fps, now=now)
    affinity = {}
    score = {}
    if fps and st.routing.enabled:
        await st.prefix_registry.ensure_loaded(st.store)
        cfg = st.routing
        floor = max(0.0, min(1.0, cfg.min_headroom_factor))
        for w in cands:
            raw = st.prefix_registry.affinity(w["id"], fps, now=now)
            head = headroom[w["id"]]
            affinity[w["id"]] = raw * (floor + (1.0 - floor) * head)
            # same term balance as SmartScheduler.score_worker (bonus vs
            # load vs region): the floored bonus of a SATURATED cached
            # worker stays below an idle cold worker's load term
            # (spillover is strict), and keeping the region WEIGHT in the
            # score means a zero-affinity request never crosses regions
            # over a mere load-headroom delta
            region_score = 1.0 - region_distance(
                region, w.get("region")) / _MAX_DISTANCE
            score[w["id"]] = (
                cfg.affinity_weight * affinity[w["id"]]
                + WEIGHTS["load"] * head
                + WEIGHTS["region"] * region_score
            )
    cands.sort(key=lambda w: (
        -score.get(w["id"], 0.0),
        region_distance(region, w.get("region")),
        -headroom[w["id"]],
        # reliability's measured avg latency as the LAST tiebreak: when
        # score, region, and headroom all tie, the historically faster
        # worker wins — the legacy reliability signal and the health
        # score agree on one surface. Workers with no history (0.0) tie,
        # preserving the previous stable order.
        float(w.get("avg_latency_ms") or 0.0),
    ))
    best = cands[0]
    if st.health.enabled:
        # probation canary gate at SELECTION time: a probation worker may
        # win only while its bounded canary budget lasts (allow_canary
        # charges it); past budget the next-ranked candidate takes the
        # request. Healthy/suspect workers always pass.
        best = next(
            (w for w in cands if st.health.allow_canary(w["id"])), best
        )
    migrate_hint: Optional[Dict[str, Any]] = None
    route_choice: Optional[str] = None
    route_decision: Optional[Dict[str, Any]] = None
    if fps and st.routing.enabled and st.routing.kv_migrate:
        # cluster-wide KV migration (round 13): a per-request cost model
        # decides route-to-warm / migrate-KV / recompute instead of
        # letting a saturated warm worker's cached KV go to waste. The
        # flag OFF keeps this whole block out — byte-identical round-7
        # behavior for the A/B.
        # source eligibility ≠ placement eligibility: a FULLY saturated
        # BUSY warm worker drops out of ``cands`` (it cannot take the
        # request) but its data plane can still SERVE the pull — which is
        # the storm scenario migration exists for. Sources come from every
        # live worker (minus client-excluded ones); placement stays cands.
        placeable = {w["id"] for w in cands}
        sources = {w["id"]: w for w in workers if w["id"] not in exclude}
        warm_id, warm_blocks, warm_tier = st.prefix_registry.best_match(
            list(sources), fps, now=now,
        )
        choice = "recompute"
        if warm_id is not None and warm_blocks > 0:
            # self-calibration: measured per-worker prefill rate, queue
            # wait, and handoff bandwidth replace the static priors when
            # routing.calibrate is on (every accessor returns None while
            # off or below min_samples — decide_kv_route then uses the
            # configured prior, byte-identical to the uncalibrated build)
            cal = st.calibration
            route_decision = decision = decide_kv_route(
                st.routing, request_blocks=len(fps),
                matched_blocks=warm_blocks, tier=warm_tier,
                warm_headroom=headroom[warm_id],
                cold_headroom=headroom[best["id"]],
                warm_is_cold=warm_id == best["id"],
                warm_prefill_tps=cal.prefill_tps(warm_id),
                cold_prefill_tps=cal.prefill_tps(best["id"]),
                warm_queue_wait_s=cal.queue_wait_s(warm_id),
                cold_queue_wait_s=cal.queue_wait_s(best["id"]),
                migrate_bandwidth=cal.bandwidth(best["id"], warm_tier),
                # a cold worker already running its pull budget is NOT
                # idle for one more: each hinted-but-unexpired pull adds
                # one queued transfer to the migrate estimate
                cold_inflight_pulls=st.migrate_hints.inflight(best["id"]),
            )
            choice = decision["choice"]
            costs = decision["costs"]
            if choice == "warm" and warm_id not in placeable:
                # the warm worker cannot take the request itself:
                # re-arbitrate the two remaining options
                choice = ("migrate"
                          if warm_blocks >= st.routing.migrate_min_blocks
                          and costs["migrate"] <= costs["recompute"]
                          else "recompute")
            if choice == "migrate" and \
                    not sources[warm_id].get("data_plane_url"):
                # the warm peer cannot serve a pull (no data plane):
                # re-arbitrate between the two feasible options rather
                # than hard-falling to recompute past a cheaper warm route
                choice = ("warm" if warm_id in placeable
                          and costs["warm"] <= costs["recompute"]
                          else "recompute")
            if choice == "warm":
                best = sources[warm_id]
            elif choice == "migrate":
                # the request runs on the score-best (cold) worker, which
                # pulls the prefix from the warm peer before admission
                migrate_hint = {
                    "worker_id": warm_id,
                    "data_plane_url": sources[warm_id]["data_plane_url"],
                    "matched_blocks": warm_blocks,
                    "tier": warm_tier,
                }
                st.migrate_hints.note(best["id"], now=now)
        st.metrics.record_kv_route_decision("direct", choice)
        route_choice = choice
    # direct-path requests never pass complete_job: a client that wants
    # the route decision on its timeline sends its trace_id with the
    # discovery query (the SDK/bench do) — the worker-side events arrive
    # through the heartbeat flight channel instead
    _flight_note(st, request.query.get("trace_id"), "server.route",
                 **route_flight_attrs(route_choice or "direct",
                                      route_decision,
                                      worker_id=best["id"]))
    if fps and st.routing.enabled:
        chosen_raw = st.prefix_registry.affinity(best["id"], fps, now=now)
        best_raw = st.prefix_registry.best_affinity_among(
            [w["id"] for w in cands], fps, now=now,
        )
        st.metrics.record_prefix_route(
            "direct", hit=chosen_raw > 0.0, spillover=best_raw > chosen_raw,
        )
    hedge_hint: Optional[Dict[str, Any]] = None
    if st.health.enabled and st.health.cfg.hedge \
            and request.query.get("hedge"):
        # hedged dispatch (round 18): a deadline-carrying client asked
        # for a backup — hand it the best-ranked DIFFERENT worker plus
        # the p95-derived fire delay. Both switches (health + hedge) and
        # the client's opt-in must agree, so the response is
        # byte-identical whenever any of the three is off.
        alt = next(
            (w for w in cands
             if w["id"] != best["id"] and st.health.allow_canary(w["id"])),
            None,
        )
        if alt is not None:
            hedge_hint = {
                "worker_id": alt["id"],
                "direct_url": alt["direct_url"],
                "delay_ms": round(st.health.hedge_delay_ms(), 1),
            }
            st.metrics.record_hedge("offered")
    return web.json_response(
        {
            "worker_id": best["id"],
            "direct_url": best["direct_url"],
            "region": best["region"],
            "client_region": region,
            **({"prefix_affinity": round(affinity.get(best["id"], 0.0), 4)}
               if affinity else {}),
            **({"kv_migrate": migrate_hint} if migrate_hint else {}),
            **({"hedge": hedge_hint} if hedge_hint else {}),
        }
    )


async def queue_stats(request: web.Request) -> web.Response:
    st = _state(request)
    return web.json_response(await st.scheduler.get_queue_stats())


# ---------------------------------------------------------------------------
# debug API: request flight recorder
# ---------------------------------------------------------------------------


async def debug_request_timeline(request: web.Request) -> web.Response:
    """Merged per-request timeline: server admission/route/claim/complete
    events + worker-side events (batcher, PD handoff from BOTH workers,
    kv-migration pulls), causally ordered, with the derived phase
    durations. The path segment accepts a job id (PD stage children
    resolve to the parent's trace) or a raw trace id; after a plane
    restart the completion-time snapshot persisted on the job row
    answers instead."""
    if (err := _check_api_key(request)) is not None:
        return err
    st = _state(request)
    ref = request.match_info["job_id"]
    tl = st.flight.timeline_for_job(ref) or st.flight.timeline(ref)
    if tl is not None:
        return web.json_response({"job_id": ref, **tl})
    job = await st.store.get_job(ref)
    if job is not None and isinstance(job.get("timeline"), dict):
        return web.json_response(
            {"job_id": ref, "stored": True, **job["timeline"]}
        )
    if job is not None and isinstance(job.get("params"), dict) \
            and job["params"].get("trace_id"):
        stored = st.flight.timeline(job["params"]["trace_id"])
        if stored is not None:
            return web.json_response({"job_id": ref, **stored})
    return _json_error(404, f"no timeline recorded for {ref}")


async def debug_slowest_requests(request: web.Request) -> web.Response:
    """Per-phase exemplar rings: the N slowest traces seen per phase
    (ring-buffered, slowest first) — the index from a histogram-tail
    alert to the concrete requests behind it."""
    if (err := _check_api_key(request)) is not None:
        return err
    st = _state(request)
    return web.json_response({
        "exemplars": st.flight.slowest(),
        "stats": dict(st.flight.stats),
    })


# ---------------------------------------------------------------------------
# admin API
# ---------------------------------------------------------------------------


async def admin_page(request: web.Request) -> web.Response:
    """Static admin SPA (reference serves server/static/admin/index.html —
    admin.py:75-87). Data calls authenticate with X-Admin-Key client-side."""
    import pathlib

    page = pathlib.Path(__file__).parent / "static" / "admin.html"
    return web.Response(text=page.read_text(), content_type="text/html")


async def admin_dashboard(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    stats = await st.store.queue_stats()
    usage = await st.usage.platform_stats()
    st.metrics.record_worker_counts(stats.get("workers", {}))
    return web.json_response(
        {
            "uptime_s": time.time() - st.started_at,
            "queue": stats,
            "usage": usage,
            "audit_recent": [
                {"ts": e.ts, "event": e.event, "actor": e.actor}
                for e in st.security.audit.recent(20)
            ],
        }
    )


async def admin_create_enterprise(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    body = await request.json()
    ent_id = await st.store.insert(
        "enterprises",
        {
            "name": body["name"],
            "contact_email": body.get("contact_email"),
            "custom_pricing": body.get("custom_pricing"),
            "price_plan_id": body.get("price_plan_id"),
            "allow_logging": int(body.get("allow_logging", True)),
            "retention_days": int(body.get("retention_days", 30)),
            "anonymize_data": int(body.get("anonymize_data", False)),
            "encrypt_fields": int(body.get("encrypt_fields", False)),
        },
    )
    return web.json_response({"enterprise_id": ent_id}, status=201)


async def admin_create_api_key(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent_id = request.match_info["enterprise_id"]
    from .security import generate_token, hash_token

    raw = generate_token()
    key_id = await st.store.insert(
        "api_keys",
        {
            "enterprise_id": ent_id,
            "key_hash": hash_token(raw),
            "name": (await request.json()).get("name") if request.can_read_body else None,
        },
    )
    return web.json_response({"api_key_id": key_id, "api_key": raw}, status=201)


async def admin_usage_summary(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent = request.query.get("enterprise_id")
    return web.json_response({"hourly": await st.usage.hourly_summary(ent)})


async def admin_generate_bill(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    body = await request.json()
    bill = await st.usage.generate_bill(
        request.match_info["enterprise_id"],
        float(body["period_start"]),
        float(body["period_end"]),
    )
    return web.json_response(bill, status=201)


async def admin_compliance(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    return web.json_response(await st.privacy.compliance_report())


async def admin_push_config(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    cfg = await st.worker_config.update_config(
        request.match_info["worker_id"], await request.json()
    )
    return web.json_response(cfg.to_dict())


async def admin_get_routing(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    # configured priors + what calibration has MEASURED, side by side:
    # the operator's predicted_vs_measured view of the cost model, plus
    # the replication planner's heat/hint counters
    return web.json_response({
        **st.routing.to_dict(),
        "calibration": st.calibration.snapshot(),
        "replication": st.replication.snapshot(),
    })


async def admin_put_routing(request: web.Request) -> web.Response:
    """Live routing A/B switch: flips/retunes the cache-aware routing
    knobs on the RUNNING control plane (no restart, no worker involvement
    — summaries keep flowing either way, only the scoring term reads the
    flag). ``block_chars`` is intentionally NOT pushable: changing the
    fingerprint basis requires a coordinated fleet restart.

    ``calibrate_reset: true`` (an action, not a stored knob) freezes the
    cost model back to the configured priors by dropping every learned
    estimate — combined with ``calibrate: false`` it is the hard half of
    the calibration A/B switch."""
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    body = await request.json()
    if not isinstance(body, dict):
        return _json_error(400, "body must be a JSON object")
    reset = bool(body.pop("calibrate_reset", False))
    try:
        st.routing.update(body)
    except (TypeError, ValueError) as exc:
        return _json_error(400, f"bad routing config: {exc}")
    if reset:
        st.calibration.reset()
    await st.store.audit("admin_update_routing", actor="admin",
                         detail=st.routing.to_dict())
    return web.json_response({
        **st.routing.to_dict(),
        "calibration": st.calibration.snapshot(),
        "replication": st.replication.snapshot(),
    })


async def admin_get_health(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    st.health.evaluate()
    return web.json_response({
        **st.health.cfg.to_dict(),
        "snapshot": st.health.snapshot(),
    })


async def admin_put_health(request: web.Request) -> web.Response:
    """Live gray-failure A/B switch: flips/retunes health scoring,
    quarantine thresholds, and hedging on the RUNNING control plane (no
    restart, no worker involvement — workers ship the same telemetry
    either way, only the scoring/ranking paths read the flags). Same
    contract as the routing endpoint: a bad field 400s without
    half-applying."""
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    body = await request.json()
    if not isinstance(body, dict):
        return _json_error(400, "body must be a JSON object")
    try:
        st.health.cfg.update(body)
    except (TypeError, ValueError) as exc:
        return _json_error(400, f"bad health config: {exc}")
    await st.store.audit("admin_update_health", actor="admin",
                         detail=st.health.cfg.to_dict())
    return web.json_response(st.health.cfg.to_dict())


async def admin_get_admission(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    return web.json_response({
        **st.admission.cfg.to_dict(),
        "snapshot": st.admission.snapshot(),
    })


async def admin_put_admission(request: web.Request) -> web.Response:
    """Live overload-control switch: flips/retunes the admission ladder on
    the RUNNING control plane (no restart, no worker involvement — only
    the submission path reads the config). Same contract as the routing
    A/B endpoint: a bad field 400s without half-applying."""
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    body = await request.json()
    if not isinstance(body, dict):
        return _json_error(400, "body must be a JSON object")
    try:
        st.admission.cfg.update(body)
    except (TypeError, ValueError) as exc:
        return _json_error(400, f"bad admission config: {exc}")
    await st.store.audit("admin_update_admission", actor="admin",
                         detail=st.admission.cfg.to_dict())
    return web.json_response(st.admission.cfg.to_dict())


async def admin_realtime(request: web.Request) -> web.Response:
    """Realtime fleet stats (reference admin.py:74-141): worker states by
    region, queue depths, jobs completed/failed in the last hour."""
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    stats = await st.store.queue_stats()
    workers = await st.store.list_workers()
    by_region: Dict[str, Dict[str, int]] = {}
    for w in workers:
        r = by_region.setdefault(w.get("region") or "unknown",
                                 {"online": 0, "busy": 0, "offline": 0})
        r[w.get("status", "offline")] = r.get(w.get("status", "offline"), 0) + 1
    hour_ago = time.time() - 3600.0
    recent = await st.store.query(
        "SELECT status, COUNT(*) AS n FROM jobs "
        "WHERE completed_at >= ? GROUP BY status", (hour_ago,),
    )
    return web.json_response(
        {
            "ts": time.time(),
            "queue": stats,
            "workers_by_region": by_region,
            "jobs_last_hour": {r["status"]: r["n"] for r in recent},
        }
    )


async def admin_list_workers(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    workers = await st.store.list_workers()
    out = []
    for w in workers:
        out.append({
            "id": w["id"], "name": w.get("name"),
            "region": w.get("region"), "status": w.get("status"),
            "current_job_id": w.get("current_job_id"),
            "reliability_score": w.get("reliability_score"),
            "total_jobs": w.get("total_jobs"),
            "completed_jobs": w.get("completed_jobs"),
            "failed_jobs": w.get("failed_jobs"),
            "last_heartbeat": w.get("last_heartbeat"),
            "supported_types": w.get("supported_types"),
            "loaded_models": w.get("loaded_models"),
            "config_version": w.get("config_version"),
        })
    return web.json_response({"workers": out})


async def admin_worker_detail(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    w = await st.store.get_worker(request.match_info["worker_id"])
    if w is None:
        return _json_error(404, "worker not found")
    w.pop("auth_token_hash", None)
    w.pop("refresh_token_hash", None)
    w.pop("signing_secret", None)
    w["predicted_online_probability"] = \
        st.reliability.predict_online_probability(w)
    return web.json_response(w)


async def admin_worker_force_offline(request: web.Request) -> web.Response:
    """Admin action: mark a worker offline and requeue its running jobs
    (reference worker admin actions, admin.py:172-320)."""
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    wid = request.match_info["worker_id"]
    if await st.store.get_worker(wid) is None:
        return _json_error(404, "worker not found")
    requeued = await st.guarantee.handle_worker_offline(
        wid, graceful=False
    )
    await st.store.audit("admin_force_offline", actor="admin",
                         detail={"worker_id": wid})
    return web.json_response({"status": "offline", "requeued": requeued})


async def admin_worker_delete(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    wid = request.match_info["worker_id"]
    if await st.store.get_worker(wid) is None:
        return _json_error(404, "worker not found")
    # handle_worker_offline's on_worker_offline hook already invalidates
    # the registry entry and deletes the persisted summary row (counted)
    await st.guarantee.handle_worker_offline(wid, graceful=False)
    await st.store.delete_worker(wid)
    # clean death supersedes gray state: drop any quarantine record so a
    # re-registered worker with the same id starts healthy
    st.health.forget(wid)
    await st.store.audit("admin_delete_worker", actor="admin",
                         detail={"worker_id": wid})
    return web.json_response({"status": "deleted"})


async def admin_list_enterprises(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    rows = await st.store.query(
        "SELECT e.*, (SELECT COUNT(*) FROM api_keys k "
        " WHERE k.enterprise_id = e.id AND k.active = 1) AS active_keys "
        "FROM enterprises e ORDER BY e.created_at DESC"
    )
    return web.json_response({"enterprises": rows})


async def admin_get_enterprise(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent = await st.store.get("enterprises",
                             request.match_info["enterprise_id"])
    if ent is None:
        return _json_error(404, "enterprise not found")
    return web.json_response(ent)


_ENTERPRISE_FIELDS = (
    "name", "contact_email", "custom_pricing", "price_plan_id",
    "allow_logging", "retention_days", "anonymize_data", "encrypt_fields",
)


async def admin_update_enterprise(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent_id = request.match_info["enterprise_id"]
    if await st.store.get("enterprises", ent_id) is None:
        return _json_error(404, "enterprise not found")
    body = await request.json()
    fields = {k: body[k] for k in _ENTERPRISE_FIELDS if k in body}
    if not fields:
        return _json_error(400, "no updatable fields given")
    sets = ", ".join(f"{k} = ?" for k in fields)
    import json as _json

    vals = [
        _json.dumps(v) if isinstance(v, (dict, list)) else v
        for v in fields.values()
    ]
    await st.store.execute(
        f"UPDATE enterprises SET {sets} WHERE id = ?", (*vals, ent_id)
    )
    await st.store.audit("admin_update_enterprise", actor="admin",
                         detail={"enterprise_id": ent_id,
                                 "fields": sorted(fields)})
    return web.json_response(await st.store.get("enterprises", ent_id))


async def admin_delete_enterprise(request: web.Request) -> web.Response:
    """Delete an enterprise AND its data (jobs/usage/bills/keys) — the
    reference's enterprise offboarding path."""
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent_id = request.match_info["enterprise_id"]
    if await st.store.get("enterprises", ent_id) is None:
        return _json_error(404, "enterprise not found")
    purged = await st.privacy.delete_enterprise_data(ent_id)
    await st.store.execute("DELETE FROM api_keys WHERE enterprise_id = ?",
                           (ent_id,))
    await st.store.execute("DELETE FROM enterprises WHERE id = ?", (ent_id,))
    await st.store.audit("admin_delete_enterprise", actor="admin",
                         detail={"enterprise_id": ent_id})
    return web.json_response({"status": "deleted", "purged": purged})


async def admin_list_api_keys(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    rows = await st.store.query(
        "SELECT id, enterprise_id, name, active, created_at, last_used_at "
        "FROM api_keys WHERE enterprise_id = ? ORDER BY created_at DESC",
        (request.match_info["enterprise_id"],),
    )
    return web.json_response({"api_keys": rows})


async def admin_revoke_api_key(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    key_id = request.match_info["key_id"]
    if await st.store.get("api_keys", key_id) is None:
        return _json_error(404, "api key not found")
    await st.store.execute("UPDATE api_keys SET active = 0 WHERE id = ?",
                           (key_id,))
    await st.store.audit("admin_revoke_api_key", actor="admin",
                         detail={"key_id": key_id})
    return web.json_response({"status": "revoked"})


async def admin_usage_records(request: web.Request) -> web.Response:
    """Raw usage records, newest first (reference admin.py:561-735)."""
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent = request.query.get("enterprise_id")
    try:
        limit = int(request.query.get("limit", 100))
    except ValueError:
        return _json_error(400, "limit must be an integer")
    limit = max(0, min(limit, 1000))  # negative LIMIT = unlimited in sqlite
    if ent:
        rows = await st.store.query(
            "SELECT * FROM usage_records WHERE enterprise_id = ? "
            "ORDER BY created_at DESC LIMIT ?", (ent, limit),
        )
    else:
        rows = await st.store.query(
            "SELECT * FROM usage_records ORDER BY created_at DESC LIMIT ?",
            (limit,),
        )
    return web.json_response({"usage_records": rows})


async def admin_list_bills(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent = request.query.get("enterprise_id")
    if ent:
        rows = await st.store.query(
            "SELECT * FROM bills WHERE enterprise_id = ? "
            "ORDER BY created_at DESC", (ent,),
        )
    else:
        rows = await st.store.query(
            "SELECT * FROM bills ORDER BY created_at DESC LIMIT 200"
        )
    return web.json_response({"bills": rows})


_PRIVACY_FIELDS = ("allow_logging", "retention_days", "anonymize_data",
                   "encrypt_fields")


async def admin_get_privacy(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent = await st.store.get("enterprises",
                             request.match_info["enterprise_id"])
    if ent is None:
        return _json_error(404, "enterprise not found")
    return web.json_response({k: ent.get(k) for k in _PRIVACY_FIELDS})


async def admin_put_privacy(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent_id = request.match_info["enterprise_id"]
    if await st.store.get("enterprises", ent_id) is None:
        return _json_error(404, "enterprise not found")
    body = await request.json()
    fields: Dict[str, int] = {}
    for k in _PRIVACY_FIELDS:
        if k not in body:
            continue
        v = body[k]
        # the enterprise-update endpoint accepts richer shapes (e.g. a list
        # of field names for encrypt_fields); this endpoint's contract is
        # int flags/days — reject anything else with a 400, not a 500
        if isinstance(v, bool):
            v = int(v)
        if not isinstance(v, int):
            return _json_error(400, f"{k} must be an integer (got {type(v).__name__})")
        fields[k] = v
    if not fields:
        return _json_error(400, "no privacy fields given")
    sets = ", ".join(f"{k} = ?" for k in fields)
    await st.store.execute(
        f"UPDATE enterprises SET {sets} WHERE id = ?",
        (*fields.values(), ent_id),
    )
    await st.store.audit("admin_update_privacy", actor="admin",
                         detail={"enterprise_id": ent_id, **fields})
    ent = await st.store.get("enterprises", ent_id)
    return web.json_response({k: ent.get(k) for k in _PRIVACY_FIELDS})


async def admin_privacy_cleanup(request: web.Request) -> web.Response:
    """Run retention cleanup now (reference retention sweep :273-395)."""
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    result = await st.privacy.retention.cleanup()
    await st.store.audit("admin_retention_cleanup", actor="admin",
                         detail=result)
    return web.json_response(result)


async def admin_privacy_export(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent_id = request.match_info["enterprise_id"]
    if await st.store.get("enterprises", ent_id) is None:
        return _json_error(404, "enterprise not found")
    return web.json_response(await st.privacy.export_enterprise_data(ent_id))


async def admin_privacy_delete_data(request: web.Request) -> web.Response:
    if (err := _check_admin_key(request)) is not None:
        return err
    st = _state(request)
    ent_id = request.match_info["enterprise_id"]
    if await st.store.get("enterprises", ent_id) is None:
        return _json_error(404, "enterprise not found")
    purged = await st.privacy.delete_enterprise_data(ent_id)
    await st.store.audit("admin_delete_enterprise_data", actor="admin",
                         detail={"enterprise_id": ent_id})
    return web.json_response({"status": "deleted", "purged": purged})


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------


async def health(request: web.Request) -> web.Response:
    st = _state(request)
    stats = await st.store.queue_stats()
    return web.json_response(
        {
            "status": "healthy",
            "uptime_s": time.time() - st.started_at,
            "workers": stats.get("workers", {}),
            "jobs": stats.get("jobs", {}),
            **({"plane": st.plane.describe()} if st.plane.enabled else {}),
        }
    )


async def regions(request: web.Request) -> web.Response:
    return web.json_response({"regions": list(REGIONS)})


async def metrics_endpoint(request: web.Request) -> web.Response:
    st = _state(request)
    # refresh summary gauges at SCRAPE time: age must keep climbing for a
    # worker that stopped advertising — the ingest-time value is ~0 by
    # construction and would hide exactly the staleness the gauge exposes
    for wid, n, age in st.prefix_registry.stats_for_metrics():
        st.metrics.record_prefix_summary(wid, n, age)
    # fleet strength at scrape time too: serving (idle/busy/draining still
    # count — a draining replica finishes its work) over every registered
    # replica. The ratio is what a brownout panel alerts on.
    stats = await st.store.queue_stats()
    w = stats.get("workers") or {}
    serving = sum(
        int(w.get(s) or 0)
        for s in (WorkerState.IDLE.value, WorkerState.BUSY.value,
                  WorkerState.DRAINING.value)
    )
    if st.health.enabled:
        # gray-failure defense: a quarantined worker is registered and
        # heartbeating but NOT taking new work — fleet strength must
        # count it degraded, not serving (pre-round-18 the gauge only
        # saw dead/offline replicas). Per-worker states refresh at
        # scrape time like the summary gauges above.
        st.health.evaluate()
        states = st.health.states()
        st.metrics.record_health_states(states)
        serving = max(0, serving - sum(
            1 for s in states.values() if s == "quarantined"
        ))
    st.metrics.record_fleet_strength(serving, sum(
        int(n or 0) for n in w.values()
    ))
    st.metrics.record_worker_counts(w)
    return web.Response(
        body=st.metrics.render(),
        content_type="text/plain",
        charset="utf-8",
    )


# ---------------------------------------------------------------------------
# app factory
# ---------------------------------------------------------------------------


@web.middleware
async def _store_degraded_middleware(request: web.Request, handler):
    """Backstop for the store-write seams the handlers don't wrap
    individually (heartbeat's update_worker, completion/release/claim
    transitions): a failed durable write surfaces as the SAME typed
    retryable 503 the submission path speaks — never a raw 500 stack
    trace. sqlite3.OperationalError is precisely the store-failure class
    (full disk, wedged file, injected chaos), so nothing else is
    masked."""
    try:
        return await handler(request)
    except sqlite3.OperationalError as exc:
        return _store_unavailable(_state(request), exc)


def create_app(state: Optional[ServerState] = None,
               start_background: bool = True) -> web.Application:
    app = web.Application(middlewares=[_store_degraded_middleware])
    app["state"] = state or ServerState()

    app.router.add_post(f"{API}/workers/register", register_worker)
    app.router.add_post(f"{API}/workers/{{worker_id}}/heartbeat", heartbeat)
    app.router.add_get(f"{API}/workers/{{worker_id}}/next-job", next_job)
    app.router.add_post(
        f"{API}/workers/{{worker_id}}/jobs/{{job_id}}/complete", complete_job
    )
    app.router.add_post(
        f"{API}/workers/{{worker_id}}/jobs/{{job_id}}/release", release_job
    )
    app.router.add_post(
        f"{API}/workers/{{worker_id}}/jobs/{{job_id}}/checkpoint",
        checkpoint_job,
    )
    app.router.add_post(
        f"{API}/workers/{{worker_id}}/streams/{{stream_id}}/checkpoint",
        checkpoint_stream,
    )
    app.router.add_post(
        f"{API}/workers/{{worker_id}}/streams/{{stream_id}}/adopt",
        adopt_stream,
    )
    app.router.add_post(f"{API}/workers/{{worker_id}}/going-offline", going_offline)
    app.router.add_post(f"{API}/workers/{{worker_id}}/offline", offline)
    app.router.add_post(f"{API}/workers/{{worker_id}}/verify", verify_worker)
    app.router.add_post(f"{API}/workers/{{worker_id}}/refresh-token", refresh_token)
    app.router.add_get(f"{API}/workers/{{worker_id}}/config", get_worker_config)
    app.router.add_put(f"{API}/workers/{{worker_id}}/config", put_worker_config)
    app.router.add_get(f"{API}/workers", list_workers)
    app.router.add_get(f"{API}/workers/{{worker_id}}", worker_detail)

    app.router.add_post(f"{API}/jobs", create_job)
    app.router.add_post(f"{API}/jobs/sync", create_job_sync)
    app.router.add_get(f"{API}/jobs/direct/nearest", nearest_direct_worker)
    app.router.add_get(f"{API}/jobs/stats/queue", queue_stats)
    app.router.add_get(f"{API}/jobs/{{job_id}}", get_job)
    app.router.add_delete(f"{API}/jobs/{{job_id}}", cancel_job)

    # static path FIRST (aiohttp matches in registration order): /slowest
    # must not be swallowed by the {job_id} route
    app.router.add_get(f"{API}/debug/requests/slowest",
                       debug_slowest_requests)
    app.router.add_get(f"{API}/debug/requests/{{job_id}}/timeline",
                       debug_request_timeline)

    app.router.add_get(f"{API}/admin/stats/dashboard", admin_dashboard)
    app.router.add_get(f"{API}/admin/stats/realtime", admin_realtime)
    app.router.add_get(f"{API}/admin/routing", admin_get_routing)
    app.router.add_put(f"{API}/admin/routing", admin_put_routing)
    app.router.add_get(f"{API}/admin/admission", admin_get_admission)
    app.router.add_put(f"{API}/admin/admission", admin_put_admission)
    app.router.add_get(f"{API}/admin/health", admin_get_health)
    app.router.add_put(f"{API}/admin/health", admin_put_health)
    app.router.add_get(f"{API}/admin/workers", admin_list_workers)
    app.router.add_get(f"{API}/admin/workers/{{worker_id}}",
                       admin_worker_detail)
    app.router.add_post(f"{API}/admin/workers/{{worker_id}}/offline",
                        admin_worker_force_offline)
    app.router.add_delete(f"{API}/admin/workers/{{worker_id}}",
                          admin_worker_delete)
    app.router.add_get(f"{API}/admin/enterprises", admin_list_enterprises)
    app.router.add_get(f"{API}/admin/enterprises/{{enterprise_id}}",
                       admin_get_enterprise)
    app.router.add_put(f"{API}/admin/enterprises/{{enterprise_id}}",
                       admin_update_enterprise)
    app.router.add_delete(f"{API}/admin/enterprises/{{enterprise_id}}",
                          admin_delete_enterprise)
    app.router.add_get(f"{API}/admin/enterprises/{{enterprise_id}}/api-keys",
                       admin_list_api_keys)
    app.router.add_delete(f"{API}/admin/api-keys/{{key_id}}",
                          admin_revoke_api_key)
    app.router.add_get(f"{API}/admin/usage/records", admin_usage_records)
    app.router.add_get(f"{API}/admin/bills", admin_list_bills)
    # static privacy paths FIRST: aiohttp matches in registration order and
    # /privacy/{enterprise_id} would otherwise swallow /privacy/compliance
    app.router.add_post(f"{API}/admin/privacy/cleanup",
                        admin_privacy_cleanup)
    app.router.add_get(f"{API}/admin/privacy/compliance", admin_compliance)
    app.router.add_get(f"{API}/admin/privacy/export/{{enterprise_id}}",
                       admin_privacy_export)
    app.router.add_delete(f"{API}/admin/privacy/data/{{enterprise_id}}",
                          admin_privacy_delete_data)
    app.router.add_get(f"{API}/admin/privacy/{{enterprise_id}}",
                       admin_get_privacy)
    app.router.add_put(f"{API}/admin/privacy/{{enterprise_id}}",
                       admin_put_privacy)
    app.router.add_post(f"{API}/admin/enterprises", admin_create_enterprise)
    app.router.add_post(
        f"{API}/admin/enterprises/{{enterprise_id}}/api-keys", admin_create_api_key
    )
    app.router.add_post(
        f"{API}/admin/enterprises/{{enterprise_id}}/bills", admin_generate_bill
    )
    app.router.add_get(f"{API}/admin/usage/summary", admin_usage_summary)
    app.router.add_put(
        f"{API}/admin/workers/{{worker_id}}/config", admin_push_config
    )

    app.router.add_get("/health", health)
    app.router.add_get("/regions", regions)
    app.router.add_get("/metrics", metrics_endpoint)
    app.router.add_get("/admin", admin_page)

    if start_background:
        async def _on_startup(app: web.Application) -> None:
            app["state"].background.start()

        async def _on_cleanup(app: web.Application) -> None:
            await app["state"].background.stop()

        app.on_startup.append(_on_startup)
        app.on_cleanup.append(_on_cleanup)

    async def _on_plane_cleanup(app: web.Application) -> None:
        await app["state"].plane.close()

    app.on_cleanup.append(_on_plane_cleanup)
    return app


def main() -> None:  # pragma: no cover - manual entry point
    import argparse

    ap = argparse.ArgumentParser(description="dgi-tpu control plane")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--db", default="dgi_tpu.sqlite")
    ap.add_argument("--api-key", default=None)
    ap.add_argument("--submit-queue-limit", type=int, default=0,
                    help="reject job submissions with 429 + Retry-After "
                         "past this queue depth (0 = unlimited)")
    ap.add_argument("--plane-id",
                    default=os.environ.get("DGI_PLANE_ID") or None,
                    help="this control-plane replica's identity in a "
                         "multi-plane cohort (enables the cohort; claims "
                         "are stamped with it)")
    ap.add_argument("--plane-peers",
                    default=os.environ.get("DGI_PLANE_PEERS") or "",
                    help="comma-separated peer plane base URLs for job "
                         "forwarding (all replicas must share --db)")
    args = ap.parse_args()
    peers = [p.strip() for p in str(args.plane_peers).split(",") if p.strip()]
    web.run_app(
        create_app(ServerState(db_path=args.db, api_key=args.api_key,
                               submit_queue_limit=args.submit_queue_limit,
                               plane_id=args.plane_id,
                               plane_peers=peers or None)),
        host=args.host,
        port=args.port,
    )


if __name__ == "__main__":  # pragma: no cover
    main()
