"""Score-based job→worker assignment + queue statistics.

Behavioral parity with the reference's ``server/app/services/scheduler.py``:
- Weighted scoring (:47-51): reliability 35, region proximity 25,
  predicted-online 20, performance 15, load 5.
- Static region distance matrix (:18-40).
- Job duration estimator by type/params (:166-192).
- Atomic claim — reference uses ``SELECT … FOR UPDATE SKIP LOCKED``
  (:194-234); here the Store's single-writer ``claim_next_job`` transaction
  provides the same at-most-once guarantee.
- Queue stats + wait estimate (:236-280).

TPU-aware additions: scoring knows chips/HBM so bigger slices win ties for
heavy jobs, and the duration estimator uses tokens-vs-MXU-throughput rather
than GPU heuristics.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from ..utils.data_structures import JobStatus, WorkerState
from .reliability import ReliabilityService
from .store import Store

# Static inter-region "distance" (0 = same region; reference scheduler.py:18-40).
REGIONS = ("us-west", "us-east", "eu-west", "eu-central", "asia-east",
           "asia-southeast", "unknown")
_REGION_DISTANCE: Dict[str, Dict[str, float]] = {
    "us-west":        {"us-west": 0, "us-east": 1, "eu-west": 3, "eu-central": 3, "asia-east": 2, "asia-southeast": 2, "unknown": 2},
    "us-east":        {"us-west": 1, "us-east": 0, "eu-west": 2, "eu-central": 2, "asia-east": 3, "asia-southeast": 3, "unknown": 2},
    "eu-west":        {"us-west": 3, "us-east": 2, "eu-west": 0, "eu-central": 1, "asia-east": 3, "asia-southeast": 3, "unknown": 2},
    "eu-central":     {"us-west": 3, "us-east": 2, "eu-west": 1, "eu-central": 0, "asia-east": 2, "asia-southeast": 2, "unknown": 2},
    "asia-east":      {"us-west": 2, "us-east": 3, "eu-west": 3, "eu-central": 2, "asia-east": 0, "asia-southeast": 1, "unknown": 2},
    "asia-southeast": {"us-west": 2, "us-east": 3, "eu-west": 3, "eu-central": 2, "asia-east": 1, "asia-southeast": 0, "unknown": 2},
    "unknown":        {r: 2 for r in REGIONS},
}
_MAX_DISTANCE = 3.0

WEIGHTS = {
    "reliability": 0.35,
    "region": 0.25,
    "predicted_online": 0.20,
    "performance": 0.15,
    "load": 0.05,
}

# Duration estimates (reference scheduler.py:166-192), re-derived for TPU:
# decode ≈ max_new_tokens / per-chip decode tok/s; diffusion ≈ steps * s/step.
_DECODE_TOKS_PER_S_PER_CHIP = 30.0
_DIFFUSION_S_PER_STEP = 0.4


def region_distance(a: Optional[str], b: Optional[str]) -> float:
    return _REGION_DISTANCE.get(a or "unknown", _REGION_DISTANCE["unknown"]).get(
        b or "unknown", 2.0
    )


def estimate_job_duration_s(job_type: str, params: Optional[Dict[str, Any]],
                            num_chips: int = 1) -> float:
    params = params or {}
    if job_type == "llm":
        toks = float(params.get("max_new_tokens") or params.get("max_tokens") or 256)
        tps = _DECODE_TOKS_PER_S_PER_CHIP * max(1, num_chips)
        return 2.0 + toks / tps
    if job_type == "image_gen":
        steps = float(params.get("num_inference_steps") or 30)
        return 3.0 + steps * _DIFFUSION_S_PER_STEP
    if job_type == "vision":
        return 5.0
    if job_type == "whisper":
        return float(params.get("audio_seconds") or 30.0) * 0.3
    if job_type == "embedding":
        return 1.0
    return 10.0


class SmartScheduler:
    """Scores candidate workers and drives atomic job claims."""

    def __init__(self, store: Store,
                 reliability: Optional[ReliabilityService] = None) -> None:
        self._store = store
        self._reliability = reliability or ReliabilityService(store)

    # -- scoring (reference scheduler.py:111-164) ---------------------------

    def score_worker(self, worker: Dict[str, Any], job: Dict[str, Any],
                     now: Optional[float] = None) -> float:
        reliability = float(worker.get("reliability_score") or 0.5)

        dist = region_distance(job.get("preferred_region") or job.get("client_region"),
                               worker.get("region"))
        region_score = 1.0 - dist / _MAX_DISTANCE

        online = self._reliability.predict_online_probability(worker, now=now)

        # performance: normalized inverse latency, boosted by slice size
        avg_ms = float(worker.get("avg_latency_ms") or 0.0)
        perf = 1.0 / (1.0 + avg_ms / 1000.0)
        chips = max(1, int(worker.get("num_chips") or 1))
        perf = min(1.0, perf * (1.0 + 0.05 * (chips - 1)))

        load = 0.0 if worker.get("current_job_id") else 1.0
        if worker.get("status") == WorkerState.BUSY.value:
            load = 0.0

        return (
            WEIGHTS["reliability"] * reliability
            + WEIGHTS["region"] * region_score
            + WEIGHTS["predicted_online"] * online
            + WEIGHTS["performance"] * perf
            + WEIGHTS["load"] * load
        )

    async def rank_workers(self, job: Dict[str, Any],
                           now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Eligible workers sorted by descending score."""
        cands = await self._store.list_workers(
            status=[WorkerState.IDLE.value, WorkerState.BUSY.value],
            supports_type=job.get("type"),
        )
        pref = job.get("preferred_region")
        if pref and not job.get("allow_cross_region", True):
            cands = [w for w in cands if w.get("region") == pref]
        scored = [(self.score_worker(w, job, now=now), w) for w in cands]
        scored.sort(key=lambda t: t[0], reverse=True)
        return [w for _, w in scored]

    # -- atomic claim (worker-pull path) ------------------------------------

    async def atomic_assign_job(self, worker_id: str) -> Optional[Dict[str, Any]]:
        w = await self._store.get_worker(worker_id)
        if w is None or w.get("status") in (
            WorkerState.OFFLINE.value,
            WorkerState.DRAINING.value,
        ):
            return None
        job = await self._store.claim_next_job(
            worker_id,
            supported_types=list(w.get("supported_types") or []),
            region=w.get("region"),
        )
        if job is not None:
            await self._store.update_worker(
                worker_id, current_job_id=job["id"], status=WorkerState.BUSY.value
            )
        return job

    # -- queue stats (reference scheduler.py:236-280) ------------------------

    async def get_queue_stats(self) -> Dict[str, Any]:
        stats = await self._store.queue_stats()
        queued = await self._store.list_jobs(
            status=[JobStatus.QUEUED.value], limit=500
        )
        workers = await self._store.list_workers(
            status=[WorkerState.IDLE.value, WorkerState.BUSY.value]
        )
        total_chips = sum(max(1, int(w.get("num_chips") or 1)) for w in workers)
        est_backlog_s = sum(
            estimate_job_duration_s(j["type"], j.get("params")) for j in queued
        )
        wait = est_backlog_s / max(1, len(workers)) if workers else float("inf")
        stats.update(
            {
                "active_workers": len(workers),
                "total_chips": total_chips,
                "estimated_wait_s": wait if workers else None,
            }
        )
        return stats
