"""Score-based job→worker assignment + queue statistics.

Behavioral parity with the reference's ``server/app/services/scheduler.py``:
- Weighted scoring (:47-51): reliability 35, region proximity 25,
  predicted-online 20, performance 15, load 5.
- Static region distance matrix (:18-40).
- Job duration estimator by type/params (:166-192).
- Atomic claim — reference uses ``SELECT … FOR UPDATE SKIP LOCKED``
  (:194-234); here the Store's single-writer ``claim_next_job`` transaction
  provides the same at-most-once guarantee.
- Queue stats + wait estimate (:236-280).

TPU-aware additions: scoring knows chips/HBM so bigger slices win ties for
heavy jobs, and the duration estimator uses tokens-vs-MXU-throughput rather
than GPU heuristics.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Sequence

from ..utils.data_structures import JobStatus, WorkerState
from .reliability import ReliabilityService
from .store import Store

# Static inter-region "distance" (0 = same region; reference scheduler.py:18-40).
REGIONS = ("us-west", "us-east", "eu-west", "eu-central", "asia-east",
           "asia-southeast", "unknown")
_REGION_DISTANCE: Dict[str, Dict[str, float]] = {
    "us-west":        {"us-west": 0, "us-east": 1, "eu-west": 3, "eu-central": 3, "asia-east": 2, "asia-southeast": 2, "unknown": 2},
    "us-east":        {"us-west": 1, "us-east": 0, "eu-west": 2, "eu-central": 2, "asia-east": 3, "asia-southeast": 3, "unknown": 2},
    "eu-west":        {"us-west": 3, "us-east": 2, "eu-west": 0, "eu-central": 1, "asia-east": 3, "asia-southeast": 3, "unknown": 2},
    "eu-central":     {"us-west": 3, "us-east": 2, "eu-west": 1, "eu-central": 0, "asia-east": 2, "asia-southeast": 2, "unknown": 2},
    "asia-east":      {"us-west": 2, "us-east": 3, "eu-west": 3, "eu-central": 2, "asia-east": 0, "asia-southeast": 1, "unknown": 2},
    "asia-southeast": {"us-west": 2, "us-east": 3, "eu-west": 3, "eu-central": 2, "asia-east": 1, "asia-southeast": 0, "unknown": 2},
    "unknown":        {r: 2 for r in REGIONS},
}
_MAX_DISTANCE = 3.0

WEIGHTS = {
    "reliability": 0.35,
    "region": 0.25,
    "predicted_online": 0.20,
    "performance": 0.15,
    "load": 0.05,
}

# Duration estimates (reference scheduler.py:166-192), re-derived for TPU:
# decode ≈ max_new_tokens / per-chip decode tok/s; diffusion ≈ steps * s/step.
_DECODE_TOKS_PER_S_PER_CHIP = 30.0
_DIFFUSION_S_PER_STEP = 0.4


def region_distance(a: Optional[str], b: Optional[str]) -> float:
    return _REGION_DISTANCE.get(a or "unknown", _REGION_DISTANCE["unknown"]).get(
        b or "unknown", 2.0
    )


def estimate_job_duration_s(job_type: str, params: Optional[Dict[str, Any]],
                            num_chips: int = 1) -> float:
    params = params or {}
    if job_type == "llm":
        toks = float(params.get("max_new_tokens") or params.get("max_tokens") or 256)
        tps = _DECODE_TOKS_PER_S_PER_CHIP * max(1, num_chips)
        return 2.0 + toks / tps
    if job_type == "image_gen":
        steps = float(params.get("num_inference_steps") or 30)
        return 3.0 + steps * _DIFFUSION_S_PER_STEP
    if job_type == "vision":
        return 5.0
    if job_type == "whisper":
        return float(params.get("audio_seconds") or 30.0) * 0.3
    if job_type == "embedding":
        return 1.0
    return 10.0


# a batcher load snapshot older than this (vs last_heartbeat cadence) is
# ignored and the binary BUSY signal takes over — a worker that stopped
# serving through a batcher must not keep its stale headroom forever
_LOAD_STATS_TTL_S = 120.0


def graded_load_score(worker: Dict[str, Any],
                      now: Optional[float] = None) -> float:
    """Load headroom in [0, 1]. Batcher-backed workers run MANY jobs
    concurrently, so the binary current_job_id/BUSY signal reads "full" the
    moment one request is in flight — grade from the heartbeat batcher
    snapshot (active slots + queue depth vs the shared-claim capacity)
    instead, falling back to the binary signal for legacy workers."""
    ls = worker.get("load_stats")
    if isinstance(ls, str):
        try:
            ls = json.loads(ls)
        except ValueError:
            ls = None
    now = time.time() if now is None else now
    if isinstance(ls, dict) and ls.get("capacity"):
        ts = float(ls.get("ts") or 0.0)
        if now - ts <= _LOAD_STATS_TTL_S:
            try:
                active = max(0, int(ls.get("active_slots") or 0))
                queue = max(0, int(ls.get("queue_depth") or 0))
                cap = max(1, int(ls.get("capacity") or 1))
            except (TypeError, ValueError):
                return _binary_load(worker)
            # queued work counts double: it is latency ALREADY being paid
            return max(0.0, 1.0 - (active + 2.0 * queue) / cap)
    return _binary_load(worker)


def _binary_load(worker: Dict[str, Any]) -> float:
    load = 0.0 if worker.get("current_job_id") else 1.0
    if worker.get("status") == WorkerState.BUSY.value:
        load = 0.0
    return load


class SmartScheduler:
    """Scores candidate workers and drives atomic job claims."""

    def __init__(self, store: Store,
                 reliability: Optional[ReliabilityService] = None,
                 prefix_registry: Optional[Any] = None,
                 metrics: Optional[Any] = None) -> None:
        self._store = store
        self._reliability = reliability or ReliabilityService(store)
        # cache-aware routing (server/prefix_routing.py): advisory prefix
        # affinity — a bounded score bonus and a bounded claim reordering,
        # never a placement gate
        self._prefix_registry = prefix_registry
        self._metrics = metrics
        # request flight recorder (round 14): claim-path route decisions
        # land on the request's timeline. Attached post-construction by
        # ServerState (the recorder needs metrics/tracing built first).
        self._flight = None
        # replicated control planes (round 15): the plane_id stamped on
        # every claim this scheduler brokers. None (NULL stamp) on
        # single-plane deployments; set by ServerState when the cohort is
        # configured.
        self.plane_id: Optional[str] = None
        # gray-failure defense (round 18): quarantine gate on the claim
        # path. Attached post-construction by ServerState; None (or the
        # service disabled) keeps the claim path byte-identical.
        self._health = None
        # cost-model self-calibration (round 20): measured per-worker
        # prefill/queue/bandwidth estimates + the in-flight migrate-pull
        # tracker. Attached post-construction; None (or calibrate off)
        # keeps the claim-path cost model on its static priors.
        self._calibration = None
        self._migrate_hints = None

    def attach_flight(self, flight: Any) -> None:
        self._flight = flight

    def attach_health(self, health: Any) -> None:
        self._health = health

    def attach_calibration(self, calibration: Any,
                           migrate_hints: Any = None) -> None:
        self._calibration = calibration
        self._migrate_hints = migrate_hints

    def _flight_note(self, job: Dict[str, Any], event: str,
                     **attrs: Any) -> None:
        """Advisory flight event for a claimed job — never raises, never
        reorders (the recorder is an observer, not a participant)."""
        if self._flight is None:
            return
        params = job.get("params")
        tid = params.get("trace_id") if isinstance(params, dict) else None
        if not tid:
            return
        try:
            self._flight.note(tid, event, job_id=job.get("id"), **attrs)
        except Exception:  # noqa: BLE001 — recorder is advisory
            pass

    # -- scoring (reference scheduler.py:111-164) ---------------------------

    def _job_fps(self, job: Dict[str, Any]) -> List[str]:
        fps = job.get("prefix_fps")
        if isinstance(fps, str):
            try:
                fps = json.loads(fps)
            except ValueError:
                return []
        if not isinstance(fps, list):
            return []
        return [fp for fp in fps if isinstance(fp, str)]

    def prefix_affinity(self, worker: Dict[str, Any], job: Dict[str, Any],
                        now: Optional[float] = None) -> float:
        """Bounded routing bonus: (affinity fraction of the request's
        prefix this worker advertises) × affinity_weight, scaled DOWN by
        the worker's load so a hot replica spills over to the fleet
        instead of starving it. 0 when routing is disabled/unknown."""
        reg = self._prefix_registry
        if reg is None or not reg.enabled:
            return 0.0
        fps = self._job_fps(job)
        if not fps:
            return 0.0
        aff = reg.affinity(worker["id"], fps, now=now)
        if aff <= 0.0:
            return 0.0
        cfg = reg.config
        headroom = graded_load_score(worker, now=now)
        floor = max(0.0, min(1.0, cfg.min_headroom_factor))
        return cfg.affinity_weight * aff * (floor + (1.0 - floor) * headroom)

    def score_worker(self, worker: Dict[str, Any], job: Dict[str, Any],
                     now: Optional[float] = None) -> float:
        reliability = float(worker.get("reliability_score") or 0.5)

        dist = region_distance(job.get("preferred_region") or job.get("client_region"),
                               worker.get("region"))
        region_score = 1.0 - dist / _MAX_DISTANCE

        online = self._reliability.predict_online_probability(worker, now=now)

        # performance: normalized inverse latency, boosted by slice size
        avg_ms = float(worker.get("avg_latency_ms") or 0.0)
        perf = 1.0 / (1.0 + avg_ms / 1000.0)
        chips = max(1, int(worker.get("num_chips") or 1))
        perf = min(1.0, perf * (1.0 + 0.05 * (chips - 1)))

        load = graded_load_score(worker, now=now)

        return (
            WEIGHTS["reliability"] * reliability
            + WEIGHTS["region"] * region_score
            + WEIGHTS["predicted_online"] * online
            + WEIGHTS["performance"] * perf
            + WEIGHTS["load"] * load
            + self.prefix_affinity(worker, job, now=now)
        )

    async def rank_workers(self, job: Dict[str, Any],
                           now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Eligible workers sorted by descending score."""
        if self._prefix_registry is not None:
            await self._prefix_registry.ensure_loaded(self._store)
        cands = await self._store.list_workers(
            status=[WorkerState.IDLE.value, WorkerState.BUSY.value],
            supports_type=job.get("type"),
        )
        pref = job.get("preferred_region")
        if pref and not job.get("allow_cross_region", True):
            cands = [w for w in cands if w.get("region") == pref]
        scored = [(self.score_worker(w, job, now=now), w) for w in cands]
        scored.sort(key=lambda t: t[0], reverse=True)
        return [w for _, w in scored]

    # -- atomic claim (worker-pull path) ------------------------------------

    async def atomic_assign_job(self, worker_id: str) -> Optional[Dict[str, Any]]:
        w = await self._store.get_worker(worker_id)
        if w is None or w.get("status") in (
            WorkerState.OFFLINE.value,
            WorkerState.DRAINING.value,
        ):
            return None
        if self._health is not None and self._health.enabled and \
                not self._health.allow_canary(worker_id):
            # gray-failure defense (round 18): a quarantined worker's
            # poll claims nothing — it keeps heartbeating, finishes its
            # in-flight work, and serves /kv/export pulls, but new work
            # routes around it. Probation re-admits through the bounded
            # canary budget (allow_canary charges it); the service
            # disabled keeps this path byte-identical.
            return None
        prefer = None
        reg = self._prefix_registry
        if reg is not None and reg.enabled:
            # cache-aware claim: within the head priority band (bounded
            # window — see claim_next_job), prefer the queued job whose
            # prefix THIS worker advertises. Pure in-memory lookup, safe
            # inside the claim transaction.
            await reg.ensure_loaded(self._store)

            def prefer(row: Dict[str, Any]) -> float:  # noqa: F811
                return reg.affinity(worker_id, self._job_fps(row))

        job = await self._store.claim_next_job(
            worker_id,
            supported_types=list(w.get("supported_types") or []),
            region=w.get("region"),
            prefer=prefer,
            plane_id=self.plane_id,
        )
        cands: Optional[List[Dict[str, Any]]] = None
        if job is not None:
            await self._store.update_worker(
                worker_id, current_job_id=job["id"], status=WorkerState.BUSY.value
            )
            if prefer is not None and self._metrics is not None:
                fps = self._job_fps(job)
                if fps:
                    aff = reg.affinity(worker_id, fps)
                    # spillover reference: warmest worker ELIGIBLE for
                    # this job (same scoping as the direct path) — a
                    # draining/offline/wrong-type worker advertising a
                    # warm summary is not "passed over". One indexed
                    # SELECT per claimed fingerprinted job buys an
                    # operator signal that means what the docs say.
                    cands = await self._store.list_workers(
                        status=[WorkerState.IDLE.value,
                                WorkerState.BUSY.value],
                        supports_type=job.get("type"),
                    )
                    best = reg.best_affinity_among(
                        [c["id"] for c in cands], fps,
                    )
                    self._metrics.record_prefix_route(
                        "queued", hit=aff > 0.0,
                        spillover=best > aff,
                    )
        if job is not None and reg is not None and reg.enabled and \
                reg.config.kv_migrate:
            await self._maybe_stamp_migration(worker_id, job, cands=cands)
        return job

    async def _maybe_stamp_migration(self, worker_id: str,
                                     job: Dict[str, Any],
                                     cands: Optional[List[Dict[str, Any]]]
                                     = None) -> None:
        """Cluster-wide KV migration on the claim path: the claiming
        worker is FIXED (route-to-warm is off the table once the claim
        lands), so the cost model only arbitrates migrate-KV vs recompute
        — when this worker is cold for the job's prefix but a live peer
        advertises a deep match and the estimated transfer beats the
        re-prefill, the handed-out job carries a ``kv_migrate_from`` hint
        (in-memory only: a requeue re-decides against fresh summaries).
        Counted per decision in ``kv_route_decisions_total{path="queued"}``."""
        from .prefix_routing import decide_kv_route

        reg = self._prefix_registry
        fps = self._job_fps(job)
        if not fps:
            return
        choice = "recompute"
        if reg.affinity(worker_id, fps) > 0.0:
            choice = "warm"   # claim preference already landed it warm
        else:
            if cands is None:
                # the spillover-metrics block usually just fetched this
                # exact list — reuse it instead of a second worker-table
                # scan inside the claim hot path
                cands = await self._store.list_workers(
                    status=[WorkerState.IDLE.value, WorkerState.BUSY.value],
                    supports_type=job.get("type"),
                )
            by_id = {c["id"]: c for c in cands if c["id"] != worker_id}
            warm_id, blocks, tier = reg.best_match(list(by_id), fps)
            if warm_id is not None and \
                    blocks >= reg.config.migrate_min_blocks and \
                    by_id[warm_id].get("data_plane_url") and \
                    isinstance(job.get("params"), dict):
                me = next((c for c in cands if c["id"] == worker_id), None)
                cold_head = graded_load_score(me) if me is not None else 1.0
                cal = self._calibration
                decision = decide_kv_route(
                    reg.config, request_blocks=len(fps),
                    matched_blocks=blocks, tier=tier,
                    warm_headroom=graded_load_score(by_id[warm_id]),
                    cold_headroom=cold_head,
                    # self-calibration (round 20): measured values when
                    # attached + warm + flag on; every accessor returns
                    # None otherwise, keeping the static priors verbatim
                    warm_prefill_tps=(cal.prefill_tps(warm_id)
                                      if cal is not None else None),
                    cold_prefill_tps=(cal.prefill_tps(worker_id)
                                      if cal is not None else None),
                    warm_queue_wait_s=(cal.queue_wait_s(warm_id)
                                       if cal is not None else None),
                    cold_queue_wait_s=(cal.queue_wait_s(worker_id)
                                       if cal is not None else None),
                    migrate_bandwidth=(cal.bandwidth(worker_id, tier)
                                       if cal is not None else None),
                    cold_inflight_pulls=(
                        self._migrate_hints.inflight(worker_id)
                        if self._migrate_hints is not None else 0),
                )
                # wait(cold) appears in both remaining costs, so this is
                # exactly "transfer beats the saved prefill"
                if decision["costs"]["migrate"] < \
                        decision["costs"]["recompute"]:
                    choice = "migrate"
                    job["params"]["kv_migrate_from"] = {
                        "worker_id": warm_id,
                        "data_plane_url": by_id[warm_id]["data_plane_url"],
                        "matched_blocks": blocks,
                        "tier": tier,
                    }
                    if self._migrate_hints is not None:
                        self._migrate_hints.note(worker_id)
        if self._metrics is not None:
            self._metrics.record_kv_route_decision("queued", choice)
        from .prefix_routing import route_flight_attrs

        self._flight_note(job, "server.route",
                          **route_flight_attrs(choice, worker_id=worker_id))

    # -- queue stats (reference scheduler.py:236-280) ------------------------

    async def get_queue_stats(self) -> Dict[str, Any]:
        stats = await self._store.queue_stats()
        queued = await self._store.list_jobs(
            status=[JobStatus.QUEUED.value], limit=500
        )
        workers = await self._store.list_workers(
            status=[WorkerState.IDLE.value, WorkerState.BUSY.value]
        )
        total_chips = sum(max(1, int(w.get("num_chips") or 1)) for w in workers)
        est_backlog_s = sum(
            estimate_job_duration_s(j["type"], j.get("params")) for j in queued
        )
        wait = est_backlog_s / max(1, len(workers)) if workers else float("inf")
        # overload-control observability: the queued backlog by tenant
        # tier (params["tier"], stamped at admission) — the brownout panel
        # and the autoscaler read "who is actually waiting" from this
        by_tier: Dict[str, int] = {}
        for j in queued:
            params = j.get("params")
            if isinstance(params, str):
                try:
                    params = json.loads(params)
                except ValueError:
                    params = None
            tier = (params or {}).get("tier") if isinstance(params, dict) \
                else None
            key = str(tier) if tier else "untiered"
            by_tier[key] = by_tier.get(key, 0) + 1
        stats.update(
            {
                "active_workers": len(workers),
                "total_chips": total_chips,
                "estimated_wait_s": wait if workers else None,
                "queued_by_tier": by_tier,
            }
        )
        return stats
