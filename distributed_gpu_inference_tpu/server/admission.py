"""SLO-native overload control: per-tenant admission budgets and
priority-aware shedding.

The fleet's pre-round-12 behavior under pressure was a single blanket
queue-depth check (``worker_config.should_accept_submission``): past
``submit_queue_limit`` EVERY submission 429s — the paying tenant and the
tenant spraying free-tier bursts alike. This module turns saturation into
*graceful, prioritized degradation*:

- **Per-tenant token buckets with weighted fair sharing.** Every tenant
  owns a bucket refilled from the fleet budget
  (``rate_tokens_per_s``) in proportion to its tier weight over the
  currently-active tenant mix — one bursting free tenant cannot starve
  the others, and paid tenants hold the lion's share by construction.
  Buckets live in a bounded LRU (``max_tenants``): a tenant-id-spraying
  client recycles bucket slots instead of growing plane memory.
- **A degrade-before-reject ladder.** As queue saturation (queued /
  ``submit_queue_limit``) climbs, requests are first *degraded* —
  ``max_tokens`` clamped (``degrade_at``), then speculation disabled
  (``no_spec_at``) — and only *shed* (429 + Retry-After) past the
  tier's queue fraction (``LoadControl.tier_queue_fractions``). Free
  and batch tiers shed at lower fractions than paid, so **paid traffic
  is never shed while free-tier capacity exists**: by the time the
  queue reaches the paid fraction (the full limit), every lower tier
  has been shedding for a while.
- **Observability for every decision.**
  ``admission_decisions_total{tenant_tier,action}`` counts the ladder
  by tier, and ``tenant_admission_decisions_total{tenant,action}``
  counts per tenant with a top-N + ``other`` label cap (the Prometheus
  registry must survive a tenant-id-spraying client too).

Decisions are made at job submission (``server/app.py`` POST /jobs[,
/sync]); the tier also boosts the job's scheduler/batcher priority so
shed ordering and service ordering agree.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

# named tenant tiers, best-served-first. Unknown tier strings normalize to
# DEFAULT_TIER — a client cannot invent a "platinum" tier to jump the shed
# ladder.
TIERS = ("paid", "free", "batch")
DEFAULT_TIER = "free"

# control-plane priority boost per tier: shed ordering (this module) and
# service ordering (scheduler claim heap + batcher admission heap) must
# agree, or paid jobs would survive admission only to queue behind batch
TIER_PRIORITY_BOOST = {"paid": 10, "free": 0, "batch": -10}

# how long a tenant counts toward the active-weight denominator after its
# last submission — fair shares rebalance on this timescale
_ACTIVE_TTL_S = 30.0


def normalize_tier(tier: Any) -> str:
    t = str(tier or "").strip().lower()
    return t if t in TIERS else DEFAULT_TIER


def tenant_of(body: Dict[str, Any]) -> Tuple[str, str]:
    """Extract ``(tenant, tier)`` from a job-submission body. The tenant
    id may ride the top level or ``params`` (the SDK sends params);
    untenanted traffic shares one ``anonymous`` bucket at the default
    tier, so legacy clients are budgeted too, not waved through."""
    params = body.get("params") if isinstance(body.get("params"), dict) \
        else {}
    tenant = body.get("tenant") or params.get("tenant") or "anonymous"
    tier = body.get("tier") or params.get("tier")
    return str(tenant)[:128], normalize_tier(tier)


def estimate_cost_tokens(params: Optional[Dict[str, Any]],
                         default_max_tokens: int = 256) -> int:
    """Budget cost of one submission, in tokens: the decode ask plus a
    coarse prompt-size term (chars/4 ≈ tokens for the byte tokenizer's
    upper bound; exactness doesn't matter — the bucket is a rate shaper,
    not a bill)."""
    params = params or {}
    toks = int(params.get("max_new_tokens") or params.get("max_tokens")
               or default_max_tokens)
    prompt = params.get("prompt")
    if isinstance(prompt, str):
        toks += len(prompt) // 4
    return max(1, toks)


@dataclass
class AdmissionConfig:
    """Live-pushable overload-control knobs (GET/PUT
    ``/api/v1/admin/admission`` — the same A/B surface as routing)."""

    enabled: bool = False
    # fleet-wide admission budget in tokens/s, split across active tenants
    # by tier weight. 0 = unlimited budget: the ladder is then driven by
    # queue saturation alone (buckets never run dry).
    rate_tokens_per_s: float = 0.0
    # bucket capacity = tenant_rate * burst_s: how much a quiet tenant may
    # burst before its fair-share rate gates it
    burst_s: float = 5.0
    tier_weights: Dict[str, float] = field(
        default_factory=lambda: {"paid": 8.0, "free": 1.0, "batch": 0.25}
    )
    # bounded tenant tracking: the LRU evicts the least-recently-seen
    # bucket past this — plane memory is O(max_tenants) no matter how many
    # tenant ids a client sprays
    max_tenants: int = 256
    # degrade ladder thresholds, as fractions of submit_queue_limit
    # (must be <= every tier's shed fraction to degrade before rejecting)
    degrade_at: float = 0.5       # clamp max_tokens
    no_spec_at: float = 0.7       # + disable speculation
    clamp_max_tokens: int = 32    # the degraded decode budget
    min_retry_after_s: float = 1.0
    max_retry_after_s: float = 30.0

    def update(self, updates: Dict[str, Any]) -> None:
        """Apply a validated partial update (admin PUT). Raises
        TypeError/ValueError on a bad field — never half-applies."""
        coerced: Dict[str, Any] = {}
        for key, val in updates.items():
            if not hasattr(self, key):
                raise ValueError(f"unknown admission field {key!r}")
            cur = getattr(self, key)
            if isinstance(cur, bool):
                if isinstance(val, str):
                    val = val.strip().lower() in ("1", "true", "yes", "on")
                coerced[key] = bool(val)
            elif isinstance(cur, dict):
                if not isinstance(val, dict):
                    raise TypeError(f"{key} must be an object")
                # MERGE partial weight updates: a PUT raising one tier's
                # weight must not silently drop the others onto the
                # _tier_weight fallback (1.0 — which would QUADRUPLE
                # batch's share and invert the tier ordering)
                coerced[key] = {**cur,
                                **{str(k): float(v) for k, v in
                                   val.items()}}
            elif isinstance(cur, int):
                coerced[key] = int(val)
            else:
                coerced[key] = float(val)
        for key, val in coerced.items():
            setattr(self, key, val)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "rate_tokens_per_s": self.rate_tokens_per_s,
            "burst_s": self.burst_s,
            "tier_weights": dict(self.tier_weights),
            "max_tenants": self.max_tenants,
            "degrade_at": self.degrade_at,
            "no_spec_at": self.no_spec_at,
            "clamp_max_tokens": self.clamp_max_tokens,
            "min_retry_after_s": self.min_retry_after_s,
            "max_retry_after_s": self.max_retry_after_s,
        }


class _Bucket:
    """One tenant's token bucket. Refill rate/capacity are recomputed by
    the controller every decision (fair shares move as tenants come and
    go), so the bucket only stores level + last-refill stamp."""

    __slots__ = ("level", "last", "tier")

    def __init__(self, tier: str, now: float, cap: float) -> None:
        self.tier = tier
        self.level = cap          # a fresh tenant starts with a full burst
        self.last = now

    def refill(self, rate: float, cap: float, now: float) -> None:
        self.level = min(cap, self.level + rate * max(0.0, now - self.last))
        self.last = now

    def deficit_s(self, cost: float, rate: float) -> float:
        """Seconds until the bucket affords ``cost`` at ``rate``."""
        if self.level >= cost:
            return 0.0
        if rate <= 0.0:
            return float("inf")
        return (cost - self.level) / rate


@dataclass
class AdmissionDecision:
    """One ladder outcome. ``action`` ∈ accept | degrade_clamp |
    degrade_no_spec | shed. Degrades compose: a ``degrade_no_spec``
    decision may also carry a clamp."""

    action: str
    tenant: str
    tier: str
    max_tokens: Optional[int] = None    # clamped decode budget, when set
    disable_spec: bool = False
    retry_after_s: float = 0.0          # shed only
    reason: str = ""

    @property
    def admitted(self) -> bool:
        return self.action != "shed"

    def flight_attrs(self) -> Dict[str, Any]:
        """Flat scalar attrs for the request's flight-recorder
        ``server.admission`` event — one place decides what a timeline
        reader sees about the ladder outcome, so the event shape cannot
        drift from the decision shape."""
        out: Dict[str, Any] = {"action": self.action, "tier": self.tier}
        if self.max_tokens is not None:
            out["max_tokens"] = int(self.max_tokens)
        if self.disable_spec:
            out["disable_spec"] = True
        if self.action == "shed":
            out["retry_after_s"] = round(float(self.retry_after_s), 3)
        return out


class AdmissionController:
    """Per-tenant budgeting + the degrade/shed ladder. One instance per
    control plane; every decision is counted (stats dict always, plane
    metrics when attached)."""

    def __init__(self, config: Optional[AdmissionConfig] = None,
                 metrics: Optional[Any] = None) -> None:
        self.cfg = config or AdmissionConfig()
        self.metrics = metrics
        # LRU: tenant -> _Bucket (move_to_end on touch, popitem(False) to
        # evict the coldest when over max_tenants)
        self._buckets: "OrderedDict[str, _Bucket]" = OrderedDict()
        self.stats: Dict[str, int] = {}

    # -- weighted fair sharing ------------------------------------------------

    def _tier_weight(self, tier: str) -> float:
        return max(0.0, float(self.cfg.tier_weights.get(tier, 1.0)))

    def _active_weight(self, now: float) -> float:
        """Sum of tier weights over tenants seen within the active TTL —
        the denominator of every tenant's fair share."""
        total = 0.0
        for b in self._buckets.values():
            if now - b.last <= _ACTIVE_TTL_S:
                total += self._tier_weight(b.tier)
        return total

    def tenant_rate(self, tier: str, now: Optional[float] = None) -> float:
        """This tier's per-tenant refill rate (tokens/s) under the current
        active mix. 0 budget = unlimited (callers treat rate 0 with an
        unlimited config as 'bucket never gates')."""
        if self.cfg.rate_tokens_per_s <= 0.0:
            return 0.0
        now = time.time() if now is None else now
        w = self._tier_weight(tier)
        denom = max(self._active_weight(now), w, 1e-9)
        return self.cfg.rate_tokens_per_s * w / denom

    def _touch(self, tenant: str, tier: str, now: float) -> _Bucket:
        b = self._buckets.get(tenant)
        rate = self.tenant_rate(tier, now)
        cap = max(rate * self.cfg.burst_s, float(self.cfg.clamp_max_tokens))
        if b is None:
            b = _Bucket(tier, now, cap)
            self._buckets[tenant] = b
            while len(self._buckets) > max(1, int(self.cfg.max_tenants)):
                self._buckets.popitem(last=False)   # coldest tenant out
        else:
            b.tier = tier
            b.refill(rate, cap, now)
            self._buckets.move_to_end(tenant)
        return b

    # -- the ladder -----------------------------------------------------------

    def decide(self, tenant: str, tier: str, cost_tokens: int,
               queued: int, active_workers: int,
               worker_config: Any,
               now: Optional[float] = None,
               decode_tokens: Optional[int] = None) -> AdmissionDecision:
        """Run one submission down the ladder. ``worker_config`` supplies
        the tier-aware queue-shed thresholds
        (``should_accept_submission(queued, active, tier=...)``) so the
        shed geometry lives with the other queue-depth policy.

        ``cost_tokens`` is the BUDGET cost (decode ask + prompt term);
        ``decode_tokens`` is the decode ask alone — the clamp applies to
        it (clamping cannot shrink a prompt), and defaults to
        ``cost_tokens`` for callers without the split."""
        now = time.time() if now is None else now
        tier = normalize_tier(tier)
        if not self.cfg.enabled:
            return self._done(AdmissionDecision("accept", tenant, tier))
        limit = int(getattr(worker_config, "submit_queue_limit", 0) or 0)
        saturation = (queued / limit) if limit > 0 else 0.0
        bucket = self._touch(tenant, tier, now)
        rate = self.tenant_rate(tier, now)
        budgeted = self.cfg.rate_tokens_per_s > 0.0

        # Stage D first — the tier's queue fraction is the hard floor no
        # budget can buy past (free/batch shed here long before paid's
        # fraction, which defaults to the full limit)
        ok_queue, retry_q = worker_config.should_accept_submission(
            queued, active_workers, tier=tier
        )
        if not ok_queue:
            retry = self._retry_after(max(retry_q, bucket.deficit_s(
                float(min(cost_tokens, self.cfg.clamp_max_tokens)), rate
            ) if budgeted else 0.0))
            return self._done(AdmissionDecision(
                "shed", tenant, tier, retry_after_s=retry,
                reason=f"queue saturated for tier {tier} "
                       f"({queued} queued)",
            ))

        clamp = None
        disable_spec = False
        decode = int(decode_tokens if decode_tokens is not None
                     else cost_tokens)
        cost = float(cost_tokens)
        over_budget = budgeted and bucket.level < cost
        if (saturation >= self.cfg.degrade_at or over_budget) \
                and decode > int(self.cfg.clamp_max_tokens):
            # Stage B: degrade the DECODE ask before rejecting anyone —
            # the clamp applies to the decode budget only (the prompt
            # term of the cost cannot be shrunk), and a request already
            # at/below the clamp is not "degraded"
            clamp = int(self.cfg.clamp_max_tokens)
            cost = max(1.0, cost - float(decode - clamp))
        if saturation >= self.cfg.no_spec_at:
            # Stage C: speculation spends draft compute the fleet no
            # longer has — serve vanilla
            disable_spec = True
        if budgeted and bucket.level < cost and tier != "paid":
            # even the clamped ask is over budget: shed (free/batch).
            # Paid debt is carried instead — the paid bucket floors at
            # its deficit and fairness catches up when the burst passes;
            # shedding paid on budget alone would violate the tier
            # contract while free capacity still exists.
            return self._done(AdmissionDecision(
                "shed", tenant, tier,
                retry_after_s=self._retry_after(
                    bucket.deficit_s(cost, rate)),
                reason=f"tenant budget exhausted "
                       f"({bucket.level:.0f} < {cost:.0f} tokens)",
            ))
        if budgeted:
            # charge; paid may run negative — debt bounded by the
            # TENANT's own burst allowance (not the fleet budget), so
            # one over-budget paid tenant free-rides at most one of its
            # own bursts past the weighted share before fairness gates it
            floor = -(rate * self.cfg.burst_s
                      + float(self.cfg.clamp_max_tokens))
            bucket.level = max(bucket.level - cost, floor)
        if disable_spec:
            return self._done(AdmissionDecision(
                "degrade_no_spec", tenant, tier, max_tokens=clamp,
                disable_spec=True,
            ))
        if clamp is not None:
            return self._done(AdmissionDecision(
                "degrade_clamp", tenant, tier, max_tokens=clamp,
            ))
        return self._done(AdmissionDecision("accept", tenant, tier))

    def _retry_after(self, hint_s: float) -> float:
        if hint_s == float("inf"):
            hint_s = self.cfg.max_retry_after_s
        return min(self.cfg.max_retry_after_s,
                   max(self.cfg.min_retry_after_s, float(hint_s)))

    def _done(self, d: AdmissionDecision) -> AdmissionDecision:
        key = f"{d.tier}:{d.action}"
        self.stats[key] = self.stats.get(key, 0) + 1
        if self.metrics is not None:
            try:
                self.metrics.record_admission(d.tier, d.action, d.tenant)
            except Exception:  # noqa: BLE001 — metrics must not gate
                pass
        return d

    # -- introspection --------------------------------------------------------

    def tracked_tenants(self) -> int:
        return len(self._buckets)

    def snapshot(self) -> Dict[str, Any]:
        """Admin/debug view: decision counts + bucket levels (top 32 by
        recency — the full map is bounded but still noisy)."""
        recent = list(self._buckets.items())[-32:]
        return {
            "decisions": dict(self.stats),
            "tracked_tenants": len(self._buckets),
            "buckets": {
                t: {"tier": b.tier, "level": round(b.level, 1)}
                for t, b in recent
            },
        }
