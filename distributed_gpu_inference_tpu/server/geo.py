"""Client IP → region detection with TTL cache and pluggable resolvers.

Behavioral parity with the reference's ``server/app/services/geo.py``:
- Country→region table (:11-36).
- In-memory TTL cache (:38-41).
- Primary + fallback external resolvers (:121, :144) — here pluggable async
  callables, network access gated off by default so tests and air-gapped
  deployments stay hermetic.
- Private/loopback IPs short-circuit to "unknown".
"""

from __future__ import annotations

import ipaddress
import time
from typing import Any, Awaitable, Callable, Dict, Optional

COUNTRY_TO_REGION: Dict[str, str] = {
    # north america
    "US": "us-west", "CA": "us-west", "MX": "us-west",
    # europe
    "GB": "eu-west", "IE": "eu-west", "FR": "eu-west", "ES": "eu-west",
    "PT": "eu-west", "NL": "eu-west", "BE": "eu-west",
    "DE": "eu-central", "AT": "eu-central", "CH": "eu-central",
    "PL": "eu-central", "CZ": "eu-central", "IT": "eu-central",
    "SE": "eu-central", "NO": "eu-central", "DK": "eu-central", "FI": "eu-central",
    # asia
    "CN": "asia-east", "JP": "asia-east", "KR": "asia-east", "TW": "asia-east",
    "HK": "asia-east",
    "SG": "asia-southeast", "TH": "asia-southeast", "VN": "asia-southeast",
    "MY": "asia-southeast", "ID": "asia-southeast", "PH": "asia-southeast",
    "IN": "asia-southeast", "AU": "asia-southeast", "NZ": "asia-southeast",
}
DEFAULT_REGION = "unknown"
CACHE_TTL_S = 3600.0

# An async resolver takes an IP string and returns {"country": "US", ...} or None.
Resolver = Callable[[str], Awaitable[Optional[Dict[str, Any]]]]


def region_for_country(country: Optional[str]) -> str:
    return COUNTRY_TO_REGION.get((country or "").upper(), DEFAULT_REGION)


def is_private_ip(ip: str) -> bool:
    try:
        addr = ipaddress.ip_address(ip)
    except ValueError:
        return True
    return addr.is_private or addr.is_loopback or addr.is_link_local


class GeoService:
    def __init__(self, resolvers: Optional[list[Resolver]] = None,
                 cache_ttl_s: float = CACHE_TTL_S) -> None:
        # no resolvers by default: hermetic (reference reaches ip-api.com then
        # ipinfo.io; deployments inject httpx-based resolvers via make_http_resolver)
        self._resolvers = resolvers or []
        self._ttl = cache_ttl_s
        self._cache: Dict[str, tuple[float, str]] = {}

    def cache_put(self, ip: str, region: str,
                  now: Optional[float] = None) -> None:
        self._cache[ip] = (time.time() if now is None else now, region)

    def cache_get(self, ip: str, now: Optional[float] = None) -> Optional[str]:
        hit = self._cache.get(ip)
        if hit is None:
            return None
        ts, region = hit
        now = time.time() if now is None else now
        if now - ts > self._ttl:
            del self._cache[ip]
            return None
        return region

    async def detect_client_region(self, ip: Optional[str]) -> str:
        """Reference ``geo.py:70`` — cache → resolver chain → unknown."""
        if not ip or is_private_ip(ip):
            return DEFAULT_REGION
        cached = self.cache_get(ip)
        if cached is not None:
            return cached
        for resolver in self._resolvers:
            try:
                info = await resolver(ip)
            except Exception:  # noqa: BLE001 — fall through to next resolver
                continue
            if info and info.get("country"):
                region = region_for_country(info["country"])
                self.cache_put(ip, region)
                return region
        return DEFAULT_REGION


def make_http_resolver(url_template: str, country_key: str = "country",
                       timeout_s: float = 3.0) -> Resolver:
    """Builds an httpx-backed resolver, e.g.
    ``make_http_resolver("http://ip-api.com/json/{ip}", "countryCode")``.
    Imported lazily so the module stays importable without httpx."""

    async def resolve(ip: str) -> Optional[Dict[str, Any]]:
        import httpx

        async with httpx.AsyncClient(timeout=timeout_s) as client:
            resp = await client.get(url_template.format(ip=ip))
            if resp.status_code != 200:
                return None
            data = resp.json()
            country = data.get(country_key)
            return {"country": country} if country else None

    return resolve
