"""Usage metering and billing.

Behavioral parity with the reference's ``server/app/services/usage.py``:
- Per-job usage records in units of tokens / pixels / seconds.
- Default price table (:178-186) with enterprise custom pricing and price
  plans overriding it (:171-175).
- Hourly aggregation (:323) and platform-wide stats (:387).
- Bill generation over a period with per-type line items.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from .store import Store

# price per unit (reference usage.py:178-186); units per type below
DEFAULT_PRICES: Dict[str, float] = {
    "llm": 0.000002,         # per token
    "embedding": 0.0000001,  # per token
    "image_gen": 0.00000001,  # per pixel
    "vision": 0.000004,      # per token
    "whisper": 0.0001,       # per audio second
}

UNIT_KINDS: Dict[str, str] = {
    "llm": "tokens",
    "embedding": "tokens",
    "image_gen": "pixels",
    "vision": "tokens",
    "whisper": "seconds",
}


def units_from_result(job_type: str, params: Optional[Dict[str, Any]],
                      result: Optional[Dict[str, Any]]) -> float:
    """Derive billable units from a job's params/result payloads."""
    params = params or {}
    result = result or {}
    if job_type in ("llm", "vision", "embedding"):
        usage = result.get("usage") or {}
        total = usage.get("total_tokens")
        if total is None:
            total = (usage.get("prompt_tokens") or 0) + (
                usage.get("completion_tokens") or 0
            )
        return float(total or 0)
    if job_type == "image_gen":
        w = int(params.get("width") or 1024)
        h = int(params.get("height") or 1024)
        n = int(params.get("num_images") or 1)
        return float(w * h * n)
    if job_type == "whisper":
        return float(result.get("audio_seconds") or params.get("audio_seconds") or 0)
    return 0.0


class UsageService:
    def __init__(self, store: Store) -> None:
        self._store = store

    async def _price_for(self, enterprise_id: Optional[str],
                         job_type: str) -> float:
        if enterprise_id:
            ent = await self._store.get("enterprises", enterprise_id)
            if ent:
                custom = ent.get("custom_pricing") or {}
                if job_type in custom:
                    return float(custom[job_type])
                plan_id = ent.get("price_plan_id")
                if plan_id:
                    plan = await self._store.get("price_plans", plan_id)
                    if plan and job_type in (plan.get("prices") or {}):
                        return float(plan["prices"][job_type])
        return DEFAULT_PRICES.get(job_type, 0.0)

    async def record_job_usage(self, job: Dict[str, Any],
                               enterprise_id: Optional[str] = None
                               ) -> Dict[str, Any]:
        job_type = job["type"]
        params = job.get("params") or {}
        units = units_from_result(job_type, params, job.get("result"))
        price = await self._price_for(enterprise_id, job_type)
        cost = units * price
        rec = {
            "enterprise_id": enterprise_id,
            "job_id": job["id"],
            "job_type": job_type,
            "worker_id": job.get("worker_id"),
            # overload control (round 12): the tenant/tier the plane
            # admitted the job under — per-tenant accounting shares the
            # table billing reads, so admission fairness is auditable
            "tenant": params.get("tenant"),
            "tier": params.get("tier"),
            "units": units,
            "unit_kind": UNIT_KINDS.get(job_type, "units"),
            "cost": cost,
        }
        rec["id"] = await self._store.insert("usage_records", dict(rec))
        return rec

    # -- aggregation ---------------------------------------------------------

    async def hourly_summary(self, enterprise_id: Optional[str] = None,
                             since: Optional[float] = None
                             ) -> List[Dict[str, Any]]:
        since = since if since is not None else time.time() - 24 * 3600
        sql = (
            "SELECT CAST(created_at / 3600 AS INTEGER) * 3600 AS hour, "
            "job_type, COUNT(*) AS jobs, SUM(units) AS units, "
            "SUM(cost) AS cost FROM usage_records WHERE created_at >= ?"
        )
        params: List[Any] = [since]
        if enterprise_id is not None:
            sql += " AND enterprise_id = ?"
            params.append(enterprise_id)
        sql += " GROUP BY hour, job_type ORDER BY hour"
        return await self._store.query(sql, params)

    async def tenant_summary(self, since: Optional[float] = None
                             ) -> List[Dict[str, Any]]:
        """Per-tenant usage aggregation (round 12 overload control): the
        consumption side of the admission budgets — jobs, units, and cost
        grouped by the tenant/tier stamped at admission. Untenanted
        legacy records group under NULL."""
        since = since if since is not None else time.time() - 24 * 3600
        return await self._store.query(
            "SELECT tenant, tier, COUNT(*) AS jobs, SUM(units) AS units, "
            "SUM(cost) AS cost FROM usage_records WHERE created_at >= ? "
            "GROUP BY tenant, tier ORDER BY units DESC", (since,),
        )

    async def platform_stats(self) -> Dict[str, Any]:
        rows = await self._store.query(
            "SELECT job_type, COUNT(*) AS jobs, SUM(units) AS units, "
            "SUM(cost) AS cost FROM usage_records GROUP BY job_type"
        )
        total_cost = sum(float(r["cost"] or 0) for r in rows)
        return {"by_type": rows, "total_cost": total_cost}

    # -- billing --------------------------------------------------------------

    async def generate_bill(self, enterprise_id: str, period_start: float,
                            period_end: float) -> Dict[str, Any]:
        rows = await self._store.query(
            "SELECT job_type, COUNT(*) AS jobs, SUM(units) AS units, "
            "SUM(cost) AS cost FROM usage_records "
            "WHERE enterprise_id=? AND created_at>=? AND created_at<? "
            "GROUP BY job_type",
            (enterprise_id, period_start, period_end),
        )
        line_items = [
            {
                "job_type": r["job_type"],
                "jobs": r["jobs"],
                "units": float(r["units"] or 0),
                "cost": float(r["cost"] or 0),
            }
            for r in rows
        ]
        total = sum(li["cost"] for li in line_items)
        bill = {
            "enterprise_id": enterprise_id,
            "period_start": period_start,
            "period_end": period_end,
            "total_cost": total,
            "line_items": line_items,
            "status": "open",
        }
        bill["id"] = await self._store.insert("bills", dict(bill))
        return bill
