"""Gray-failure defense (round 18): windowed per-worker health scoring
with a healthy → suspect → quarantined → probation state machine.

Clean deaths are easy — a killed worker stops heartbeating and the sweep
removes it. The dangerous replica is the one that is *alive and 10x
slow* (thermal throttle, dying disk, noisy neighbor) or answering 5xx at
some probability: it passes every liveness check, keeps winning affinity
for its warm prefixes, and silently blows every SLO routed through it.
This service turns the fleet's own phase-latency telemetry (the direct
serving channel + worker-measured heartbeat round-trips shipped over
heartbeats, the same side channel the flight recorder uses) into a
defensive routing signal.

Design invariants:

- **Relative, not absolute.** A worker is judged against the CURRENT
  fleet median p95 — a globally slow model/configuration quarantines
  nobody, and the thresholds need no per-deployment tuning.
- **Quarantine is a routing preference, not a death sentence.** A
  quarantined worker is excluded from discovery ranking and claim
  preference but keeps its registration, keeps heartbeating, still
  serves ``/kv/export`` pulls, and finishes in-flight work. Probation
  re-admits it through a bounded canary budget, so one noisy window
  cannot permanently evict a healthy replica.
- **Capped blast radius.** At most ``max_quarantined_frac`` of the
  scored fleet can be quarantined at once — if "everyone looks slow" the
  baseline is wrong, not the fleet.
- **Default OFF, byte-identical when disabled.** With ``enabled=False``
  nothing reads the samples, no response field changes, no ranking
  changes: the pre-round-18 discovery/claim path verbatim (asserted in
  tests/test_worker_health.py).

Live-pushable via ``GET/PUT /api/v1/admin/health`` exactly like
:class:`~.prefix_routing.RoutingConfig`.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

# state-machine states, in escalation order; the numeric codes are what
# the ``worker_health_state`` gauge exports (keep them stable)
HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
PROBATION = "probation"
STATE_CODES = {HEALTHY: 0, SUSPECT: 1, QUARANTINED: 2, PROBATION: 3}


@dataclass
class HealthConfig:
    """Live-pushable health/quarantine/hedge knobs
    (admin ``PUT /api/v1/admin/health``)."""

    # master switch: OFF keeps discovery/claim byte-identical to the
    # pre-health build (the A/B flip for BENCH_r16)
    enabled: bool = False
    # hedged dispatch for deadline-carrying direct requests: discovery
    # returns a second-ranked candidate + a p95-derived fire delay and
    # the SDK races the two, first winner cancelling the loser. Separate
    # switch so quarantine and hedging A/B independently.
    hedge: bool = False
    # sliding sample window; older samples fall out of the score
    window_s: float = 60.0
    # per-worker samples required before it is judged (or used as a
    # baseline peer) — one slow request is noise, not a gray failure
    min_samples: int = 5
    # scored peers required for a fleet baseline: with one worker there
    # is nothing to be relatively slow against
    min_peers: int = 2
    # worker p95 / fleet median p95 at or above this → suspect
    suspect_ratio: float = 3.0
    # hysteresis: ratio must fall BELOW this to clear back to healthy
    # (strictly < suspect_ratio or a worker on the rail would flap)
    clear_ratio: float = 1.5
    # suspect must persist this long before quarantine — a single slow
    # GC pause or compile storm should clear on its own
    grace_s: float = 3.0
    # quarantined at least this long before probation opens
    probation_after_s: float = 10.0
    # canary requests probation may route to the worker; its fresh
    # samples then decide re-admission vs re-quarantine
    canary_budget: int = 3
    # each server-side error (flaky 5xx) scores as a synthetic sample of
    # this latency — a fast-failing replica is as gray as a slow one
    error_sample_ms: float = 2000.0
    # at most this fraction of the SCORED fleet may sit in
    # quarantined/probation at once (rounded down, min 1 when any
    # worker qualifies) — baseline-poisoning containment
    max_quarantined_frac: float = 0.34
    # hedge fire delay = hedge_delay_factor × fleet median p95, clamped
    # to [hedge_delay_min_ms, hedge_delay_max_ms]; the factor keeps the
    # hedge AFTER the common case finishes (cheap) but well before the
    # deadline burns down (useful)
    hedge_delay_factor: float = 1.5
    hedge_delay_min_ms: float = 50.0
    hedge_delay_max_ms: float = 5000.0

    def update(self, d: Dict[str, Any]) -> None:
        # validate EVERYTHING before applying ANYTHING (same contract as
        # RoutingConfig.update: a 400 must leave the live config intact)
        staged: Dict[str, Any] = {}
        for flag in ("enabled", "hedge"):
            if d.get(flag) is not None:
                v = d[flag]
                if isinstance(v, str):
                    low = v.strip().lower()
                    if low in ("true", "1", "on"):
                        v = True
                    elif low in ("false", "0", "off"):
                        v = False
                    else:
                        raise ValueError(f"{flag}: not a boolean: {v!r}")
                elif not isinstance(v, bool):
                    raise ValueError(f"{flag}: not a boolean: {v!r}")
                staged[flag] = v
        for k, lo, hi in (("window_s", 1.0, float("inf")),
                          ("suspect_ratio", 1.0, float("inf")),
                          ("clear_ratio", 1.0, float("inf")),
                          ("grace_s", 0.0, float("inf")),
                          ("probation_after_s", 0.0, float("inf")),
                          ("error_sample_ms", 0.0, float("inf")),
                          ("max_quarantined_frac", 0.0, 1.0),
                          ("hedge_delay_factor", 0.0, float("inf")),
                          ("hedge_delay_min_ms", 0.0, float("inf")),
                          ("hedge_delay_max_ms", 0.0, float("inf"))):
            if d.get(k) is not None:
                v = float(d[k])
                if not lo <= v <= hi:
                    raise ValueError(f"{k}: {v} outside [{lo}, {hi}]")
                staged[k] = v
        for k in ("min_samples", "min_peers", "canary_budget"):
            if d.get(k) is not None:
                v = int(d[k])
                if v < 1:
                    raise ValueError(f"{k}: must be >= 1, got {v}")
                staged[k] = v
        clear = staged.get("clear_ratio", self.clear_ratio)
        suspect = staged.get("suspect_ratio", self.suspect_ratio)
        if clear >= suspect:
            raise ValueError(
                f"clear_ratio ({clear}) must stay below suspect_ratio "
                f"({suspect}) — equal thresholds make the state machine "
                "flap on the rail"
            )
        for k, v in staged.items():
            setattr(self, k, v)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "hedge": self.hedge,
            "window_s": self.window_s,
            "min_samples": self.min_samples,
            "min_peers": self.min_peers,
            "suspect_ratio": self.suspect_ratio,
            "clear_ratio": self.clear_ratio,
            "grace_s": self.grace_s,
            "probation_after_s": self.probation_after_s,
            "canary_budget": self.canary_budget,
            "error_sample_ms": self.error_sample_ms,
            "max_quarantined_frac": self.max_quarantined_frac,
            "hedge_delay_factor": self.hedge_delay_factor,
            "hedge_delay_min_ms": self.hedge_delay_min_ms,
            "hedge_delay_max_ms": self.hedge_delay_max_ms,
        }


@dataclass
class _WorkerHealth:
    # (ts, latency_ms) — bounded ring; the window prune is on read
    samples: Deque[Tuple[float, float]] = field(
        default_factory=lambda: deque(maxlen=512)
    )
    state: str = HEALTHY
    since: float = 0.0           # wall clock of the last state change
    suspect_since: float = 0.0   # first moment of the CURRENT suspect run
    canaries: int = 0            # canary requests granted this probation
    # fresh-sample watermark: probation verdicts only weigh samples
    # observed AFTER probation opened (pre-quarantine history must not
    # outvote the canary evidence either way)
    probation_mark: float = 0.0


def _p95(values: List[float]) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    # nearest-rank on the sorted window (small-n friendly: 1 sample → it)
    idx = min(len(vs) - 1, max(0, int(0.95 * len(vs) + 0.5) - 1))
    return vs[idx]


def _median(values: List[float]) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    mid = len(vs) // 2
    if len(vs) % 2:
        return vs[mid]
    return 0.5 * (vs[mid - 1] + vs[mid])


class HealthService:
    """Windowed per-worker latency scores + the quarantine state machine.

    Thread-safe (heartbeat ingest and discovery reads race): one lock
    around the sample rings and state table; every public read takes a
    consistent snapshot. Pure wall-clock logic over in-memory state —
    hermetically testable with injected ``now``."""

    def __init__(self, config: Optional[HealthConfig] = None,
                 on_transition: Optional[
                     Callable[[str, str, str], None]] = None) -> None:
        self.cfg = config or HealthConfig()
        self._workers: Dict[str, _WorkerHealth] = {}
        self._lock = threading.Lock()
        # (worker_id, from_state, to_state) → metrics counter; wrapped so
        # a metrics failure can never 500 a heartbeat
        self._on_transition = on_transition

    # -- ingest ---------------------------------------------------------------

    def observe(self, worker_id: str, latency_ms: float,
                now: Optional[float] = None) -> None:
        """One phase-latency sample for this worker (direct request
        wall time, heartbeat RTT, batcher step EMA — the score mixes
        whatever the worker ships)."""
        if not self.cfg.enabled:
            return   # disabled: do not even accumulate (byte-identical)
        now = time.time() if now is None else now
        try:
            ms = float(latency_ms)
        except (TypeError, ValueError):
            return
        if ms < 0.0 or ms != ms or ms == float("inf"):
            return
        with self._lock:
            wh = self._workers.setdefault(worker_id, _WorkerHealth())
            wh.samples.append((now, ms))

    def observe_error(self, worker_id: str, count: int = 1,
                      now: Optional[float] = None) -> None:
        """Server-side errors (flaky 5xx): each scores as a synthetic
        slow sample — a replica failing FAST must not look healthy."""
        for _ in range(max(0, min(int(count), 64))):
            self.observe(worker_id, self.cfg.error_sample_ms, now=now)

    def ingest(self, worker_id: str, engine_stats: Optional[Dict[str, Any]],
               body: Optional[Dict[str, Any]] = None,
               now: Optional[float] = None) -> None:
        """Heartbeat hook: pull every health-relevant sample out of one
        beat. Worker-supplied payloads degrade to skipped samples, never
        raise (a malformed beat must not get a live worker swept)."""
        if not self.cfg.enabled:
            return
        now = time.time() if now is None else now
        try:
            if isinstance(body, dict) and body.get("hb_rtt_ms") is not None:
                self.observe(worker_id, body["hb_rtt_ms"], now=now)
            direct = (engine_stats or {}).get("direct") \
                if isinstance(engine_stats, dict) else None
            if isinstance(direct, dict):
                recent = direct.get("recent_ms")
                if isinstance(recent, list):
                    for ms in recent[:64]:
                        self.observe(worker_id, ms, now=now)
                errs = direct.get("new_errors")
                if errs:
                    self.observe_error(worker_id, int(errs), now=now)
        except (TypeError, ValueError):
            pass
        self.evaluate(now=now)

    def forget(self, worker_id: str) -> None:
        """Worker deregistered/offline: a clean death supersedes gray
        state (the sweep path owns dead workers)."""
        with self._lock:
            self._workers.pop(worker_id, None)

    # -- scoring --------------------------------------------------------------

    def _window_values(self, wh: _WorkerHealth, now: float,
                       since: float = 0.0) -> List[float]:
        cutoff = max(now - self.cfg.window_s, since)
        return [ms for ts, ms in wh.samples if ts >= cutoff]

    def _scores(self, now: float) -> Dict[str, Tuple[float, int]]:
        """→ {worker: (p95_ms, n_samples)} over the live window."""
        out: Dict[str, Tuple[float, int]] = {}
        for wid, wh in self._workers.items():
            vals = self._window_values(wh, now)
            out[wid] = (_p95(vals), len(vals))
        return out

    def _baseline(self, scores: Dict[str, Tuple[float, int]]) -> float:
        """Fleet baseline: median of the qualified peers' p95s. Workers
        already quarantined are EXCLUDED — a quarantined straggler must
        not drag the baseline up and mask the next gray failure."""
        vals = [
            p95 for wid, (p95, n) in scores.items()
            if n >= self.cfg.min_samples and p95 > 0.0
            and self._workers[wid].state not in (QUARANTINED, PROBATION)
        ]
        if len(vals) < self.cfg.min_peers:
            return 0.0
        return _median(vals)

    # -- state machine --------------------------------------------------------

    def _transition(self, wid: str, wh: _WorkerHealth, to: str,
                    now: float) -> None:
        frm = wh.state
        if frm == to:
            return
        wh.state = to
        wh.since = now
        if to == SUSPECT:
            wh.suspect_since = now
        if to == PROBATION:
            wh.canaries = 0
            wh.probation_mark = now
        if self._on_transition is not None:
            try:
                self._on_transition(wid, frm, to)
            except Exception:  # noqa: BLE001 — metrics are best-effort
                pass

    def _quarantine_headroom(self, scores: Dict[str, Tuple[float, int]]
                             ) -> int:
        """How many MORE workers may enter quarantine right now."""
        scored = sum(1 for _, n in scores.values()
                     if n >= self.cfg.min_samples)
        cap = max(1, int(scored * self.cfg.max_quarantined_frac)) \
            if scored else 0
        held = sum(1 for wh in self._workers.values()
                   if wh.state in (QUARANTINED, PROBATION))
        return max(0, cap - held)

    def evaluate(self, now: Optional[float] = None) -> None:
        """Advance every worker's state machine against the current
        window. Called from heartbeat ingest; idempotent and cheap, so
        callers may also invoke it on demand (admin snapshot, tests)."""
        if not self.cfg.enabled:
            return
        now = time.time() if now is None else now
        with self._lock:
            scores = self._scores(now)
            baseline = self._baseline(scores)
            headroom = self._quarantine_headroom(scores)
            for wid, wh in self._workers.items():
                p95, n = scores[wid]
                ratio = (p95 / baseline) if baseline > 0.0 else 0.0
                judged = baseline > 0.0 and n >= self.cfg.min_samples
                if wh.state == HEALTHY:
                    if judged and ratio >= self.cfg.suspect_ratio:
                        self._transition(wid, wh, SUSPECT, now)
                elif wh.state == SUSPECT:
                    if not judged or ratio < self.cfg.clear_ratio:
                        self._transition(wid, wh, HEALTHY, now)
                    elif ratio >= self.cfg.suspect_ratio and \
                            now - wh.suspect_since >= self.cfg.grace_s:
                        if headroom > 0:
                            headroom -= 1
                            self._transition(wid, wh, QUARANTINED, now)
                elif wh.state == QUARANTINED:
                    if now - wh.since >= self.cfg.probation_after_s:
                        self._transition(wid, wh, PROBATION, now)
                elif wh.state == PROBATION:
                    fresh = self._window_values(wh, now,
                                                since=wh.probation_mark)
                    if len(fresh) >= min(self.cfg.min_samples,
                                         self.cfg.canary_budget):
                        fr = (_p95(fresh) / baseline) if baseline > 0.0 \
                            else 0.0
                        if baseline <= 0.0 or fr < self.cfg.clear_ratio:
                            self._transition(wid, wh, HEALTHY, now)
                        elif fr >= self.cfg.suspect_ratio:
                            # canaries came back slow: straight back to
                            # quarantine, probation timer restarts
                            self._transition(wid, wh, QUARANTINED, now)

    # -- routing reads --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    def state(self, worker_id: str) -> str:
        with self._lock:
            wh = self._workers.get(worker_id)
            return wh.state if wh is not None else HEALTHY

    def is_quarantined(self, worker_id: str) -> bool:
        """Routing gate: True only for full quarantine — suspects still
        serve (grace window), probation admits via :meth:`allow_canary`."""
        if not self.cfg.enabled:
            return False
        return self.state(worker_id) == QUARANTINED

    def allow_canary(self, worker_id: str) -> bool:
        """Probation admission: grant one canary slot if the budget
        allows. Quarantined workers never pass; healthy/suspect always
        do (they are not rationed)."""
        if not self.cfg.enabled:
            return True
        with self._lock:
            wh = self._workers.get(worker_id)
            if wh is None or wh.state in (HEALTHY, SUSPECT):
                return True
            if wh.state == QUARANTINED:
                return False
            if wh.canaries >= self.cfg.canary_budget:
                return False
            wh.canaries += 1
            return True

    def admissible(self, worker_ids: List[str]) -> List[str]:
        """Filter a candidate list for placement: drop quarantined
        workers (probation workers stay listed — the canary budget is
        charged by :meth:`allow_canary` only at SELECTION time, so
        ranking them costs nothing). Falls back to the ORIGINAL list
        when filtering would empty it — availability beats purity
        (better a slow answer than none)."""
        if not self.cfg.enabled:
            return worker_ids
        kept = [w for w in worker_ids if not self.is_quarantined(w)]
        return kept if kept else worker_ids

    def hedge_delay_ms(self, now: Optional[float] = None) -> float:
        """p95-derived hedge fire delay: factor × fleet median p95 over
        the live window, clamped. With no baseline yet, the clamp floor
        (a sane constant) is the answer."""
        now = time.time() if now is None else now
        with self._lock:
            base = self._baseline(self._scores(now))
        raw = self.cfg.hedge_delay_factor * base
        return max(self.cfg.hedge_delay_min_ms,
                   min(self.cfg.hedge_delay_max_ms, raw))

    # -- introspection --------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Admin/metrics view: per-worker state, score, sample count."""
        now = time.time() if now is None else now
        with self._lock:
            scores = self._scores(now)
            baseline = self._baseline(scores)
            return {
                "baseline_p95_ms": round(baseline, 3),
                "workers": {
                    wid: {
                        "state": wh.state,
                        "p95_ms": round(scores[wid][0], 3),
                        "samples": scores[wid][1],
                        "since": wh.since,
                        "canaries": wh.canaries,
                    }
                    for wid, wh in self._workers.items()
                },
            }

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {wid: wh.state for wid, wh in self._workers.items()}
