"""Metrics, tracing, structured logging — the observability surface.

Behavioral parity with the reference's ``server/app/services/observability.py``:
- Prometheus metric set (:30-141): inference requests/latency, tokens and
  tokens/s, KV-cache hit rate / size / evictions per tier, worker status,
  accelerator memory, distributed hop latency histogram, KV migration latency,
  batch size, per-phase queue size, speculative accept rate + speedup.
- Optional imports (:22-27, :146-154): everything degrades to no-op stubs when
  prometheus_client / opentelemetry are absent.
- ``MetricsCollector`` facade (:255-405), ``/metrics`` text endpoint factory
  (:410-450), ``StructuredLogger`` with bound context (:455-488).

TPU additions: ``tpu_profiler_trace`` context manager wraps
``jax.profiler.trace`` for on-device timeline capture, and memory gauges read
HBM (device memory stats) instead of nvidia-smi.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Any, Dict, Iterator, Optional

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    HAVE_PROMETHEUS = True
except Exception:  # pragma: no cover
    HAVE_PROMETHEUS = False

try:
    from opentelemetry import trace as _otel_trace
    from opentelemetry.sdk.trace import TracerProvider
    from opentelemetry.sdk.trace.export import (
        BatchSpanProcessor,
        ConsoleSpanExporter,
    )

    HAVE_OTEL = True
except Exception:  # pragma: no cover
    HAVE_OTEL = False


# ---------------------------------------------------------------------------
# Prometheus metrics (no-op fallbacks when the client is absent)
# ---------------------------------------------------------------------------

# request_phase_latency_seconds bucket boundaries: sub-ms resolution for
# worker-side phases (queue wait on an idle batcher, a local handoff),
# stretching to multi-minute long-context e2e. Module-level so tests and
# dashboards share one source of truth.
PHASE_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


class _Noop:
    def labels(self, *a: Any, **k: Any) -> "_Noop":
        return self

    def inc(self, *a: Any) -> None: ...
    def dec(self, *a: Any) -> None: ...
    def set(self, *a: Any) -> None: ...
    def observe(self, *a: Any) -> None: ...


class Metrics:
    """All platform metrics on one registry (names mirror reference :30-141)."""

    def __init__(self) -> None:
        if not HAVE_PROMETHEUS:
            self.registry = None
            noop = _Noop()
            for name in (
                "inference_requests", "inference_latency", "tokens_generated",
                "tokens_per_second", "kv_cache_hit_rate", "kv_cache_size",
                "kv_cache_evictions", "worker_status", "hbm_used_bytes",
                "hop_latency", "kv_migration_latency", "batch_size",
                "queue_size", "spec_accept_rate", "spec_speedup",
                "spec_accepted_tokens", "spec_drafted_tokens",
                "spec_decode_steps", "spec_worker_accept_rate",
                "spec_worker_tokens_per_step",
                "kv_preemptions", "kv_resumes", "kv_pressure_events",
                "job_checkpoints", "checkpoints_rejected",
                "stream_failovers", "kv_handoff_purged",
                "batcher_queue_depth", "batcher_active_slots",
                "batcher_occupancy", "batcher_horizon",
                "batcher_decode_rounds", "batcher_completed",
                "batcher_chunked_admissions", "batcher_preemptions",
                "batcher_migrated",
                "prefix_route_hits", "prefix_route_spillover",
                "prefix_summary_entries", "prefix_summary_age",
                "heartbeat_payload_rejected",
                "prefix_summaries_invalidated", "worker_rejoin",
                "fleet_degraded", "chaos_kills", "chaos_partitions",
                "chaos_events",
                "worker_health_state", "health_transitions",
                "jobs_abandoned", "hedges",
                "pd_handoffs", "pd_handoff_bytes", "pd_reprefill",
                "pd_fleet_balance",
                "kv_migrations", "kv_migration_bytes",
                "kv_route_decisions", "kv_replicate_hints",
                "predictive_rebalance",
                "admission_decisions", "tenant_admissions",
                "autoscaler_decisions", "autoscaler_replicas",
                "autoscaler_slo", "autoscaler_cold_start",
                "request_phase_latency", "flight_timelines",
                "flight_events_dropped",
                "kv_spill_errors", "spill_quarantined",
                "io_breaker_state", "store_degraded",
            ):
                setattr(self, name, noop)
            return
        r = CollectorRegistry()
        self.registry = r
        self.inference_requests = Counter(
            "inference_requests_total", "Inference requests",
            ["job_type", "status"], registry=r)
        self.inference_latency = Histogram(
            "inference_latency_seconds", "End-to-end inference latency",
            ["job_type"], registry=r,
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60))
        self.tokens_generated = Counter(
            "tokens_generated_total", "Decoded tokens", registry=r)
        self.tokens_per_second = Gauge(
            "tokens_per_second", "Recent decode throughput", registry=r)
        self.kv_cache_hit_rate = Gauge(
            "kv_cache_hit_rate", "KV/prefix cache hit rate", ["tier"],
            registry=r)
        self.kv_cache_size = Gauge(
            "kv_cache_size_blocks", "Allocated KV blocks", ["tier"], registry=r)
        self.kv_cache_evictions = Counter(
            "kv_cache_evictions_total", "KV block evictions", ["tier"],
            registry=r)
        self.worker_status = Gauge(
            "worker_status", "Workers by status", ["status"], registry=r)
        self.hbm_used_bytes = Gauge(
            "hbm_used_bytes", "Per-device HBM in use", ["device"], registry=r)
        self.hop_latency = Histogram(
            "distributed_hop_latency_seconds", "Pipeline hop latency",
            registry=r,
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1))
        self.kv_migration_latency = Histogram(
            "kv_migration_latency_seconds", "PD KV migration latency",
            registry=r, buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1))
        self.batch_size = Gauge(
            "batch_size", "Current decode batch size", registry=r)
        self.queue_size = Gauge(
            "queue_size", "Queued requests per phase", ["phase"], registry=r)
        self.spec_accept_rate = Gauge(
            "speculative_accept_rate", "Draft token accept rate", registry=r)
        self.spec_speedup = Gauge(
            "speculative_speedup", "Tokens per verify step", registry=r)
        # per-worker speculation efficiency (engine-integrated decode mode):
        # counters scrape-delta cleanly into fleet accept-rate / tokens-per-
        # step panels; the gauges mirror the engine's own derived numbers
        self.spec_accepted_tokens = Counter(
            "speculative_accepted_tokens_total",
            "Accepted draft tokens", ["worker"], registry=r)
        self.spec_drafted_tokens = Counter(
            "speculative_drafted_tokens_total",
            "Drafted tokens offered to verification", ["worker"], registry=r)
        self.spec_decode_steps = Counter(
            "speculative_decode_steps_total",
            "Per-slot speculative verify steps", ["worker"], registry=r)
        self.spec_worker_accept_rate = Gauge(
            "speculative_worker_accept_rate",
            "Draft token accept rate per worker", ["worker"], registry=r)
        self.spec_worker_tokens_per_step = Gauge(
            "speculative_worker_tokens_per_step",
            "Committed tokens per verify step per worker (weight-stream "
            "amortization factor)", ["worker"], registry=r)
        # KV-pressure recovery: preemption is a scheduling event, and these
        # are its fleet health panel — a rising preemption rate means pools
        # are running hot; preemptions without matching resumes mean
        # requests are dying preempted_too_often
        self.kv_preemptions = Counter(
            "kv_preemptions_total",
            "Sequences preempted under KV-block pressure", ["worker"],
            registry=r)
        self.kv_resumes = Counter(
            "kv_resumes_total",
            "Preempted sequences resumed (spill/cache restore)", ["worker"],
            registry=r)
        self.kv_pressure_events = Counter(
            "kv_pressure_events_total",
            "Step-boundary KV pressure signals (frozen slots / deferred "
            "admissions)", ["worker"], registry=r)
        # crash-safe generation: checkpoints accepted/fenced and streams
        # adopted by failover workers. A rising checkpoints_rejected
        # {reason=stale_epoch} means zombie workers are still reporting
        # after their assignments were taken over — exactly what the epoch
        # fence exists to absorb, but worth watching at fleet scale.
        self.job_checkpoints = Counter(
            "job_checkpoints_total",
            "Generation checkpoints accepted by the control plane",
            ["worker"], registry=r)
        self.checkpoints_rejected = Counter(
            "checkpoints_rejected_total",
            "Checkpoints/completions rejected by epoch or ownership "
            "fencing", ["reason"], registry=r)
        self.stream_failovers = Counter(
            "stream_failovers_total",
            "Direct-stream checkpoints adopted by a failover worker",
            registry=r)
        self.kv_handoff_purged = Counter(
            "kv_handoff_sessions_purged_total",
            "Abandoned streamed-handoff sessions purged by receivers",
            ["worker"], registry=r)
        # batcher-backed serving (the production worker path since round
        # 6): per-worker batch health — queue depth growing while
        # occupancy sits at the slot count means the worker is saturated;
        # chunked admissions trending up means long prompts dominate.
        self.batcher_queue_depth = Gauge(
            "batcher_queue_depth",
            "Requests waiting in the worker's continuous-batching "
            "admission queue", ["worker"], registry=r)
        self.batcher_active_slots = Gauge(
            "batcher_active_slots",
            "Engine slots decoding right now", ["worker"], registry=r)
        self.batcher_occupancy = Gauge(
            "batcher_avg_occupancy",
            "Average decoding slots per engine round", ["worker"],
            registry=r)
        self.batcher_horizon = Gauge(
            "batcher_horizon",
            "Current adaptive decode horizon (device steps per host "
            "round-trip)", ["worker"], registry=r)
        self.batcher_decode_rounds = Counter(
            "batcher_decode_rounds_total",
            "Engine decode rounds driven by the batcher", ["worker"],
            registry=r)
        self.batcher_completed = Counter(
            "batcher_requests_completed_total",
            "Requests completed through the batcher serving path",
            ["worker"], registry=r)
        self.batcher_chunked_admissions = Counter(
            "batcher_chunked_admissions_total",
            "Long prompts admitted chunk-interleaved", ["worker"],
            registry=r)
        self.batcher_preemptions = Counter(
            "batcher_preemptions_total",
            "KV-pressure preemptions applied by the batcher's victim "
            "policy", ["worker"], registry=r)
        self.batcher_migrated = Counter(
            "batcher_requests_migrated_total",
            "In-flight requests frozen into checkpoints on graceful "
            "drain", ["worker"], registry=r)
        # cache-aware routing (round 7): hits = placements that landed on
        # a worker advertising the request's prefix; spillover = requests
        # whose warmest worker was passed over (load headroom scaling or
        # claim ordering) — a high spillover rate with low hit rate means
        # the fleet is too hot for locality to matter.
        self.prefix_route_hits = Counter(
            "prefix_route_hits_total",
            "Requests routed to a worker advertising their prefix",
            ["path"], registry=r)
        self.prefix_route_spillover = Counter(
            "prefix_route_spillover_total",
            "Requests whose warmest worker was passed over (load "
            "spillover)", ["path"], registry=r)
        self.prefix_summary_entries = Gauge(
            "prefix_summary_entries",
            "Advertised radix-summary entries per worker", ["worker"],
            registry=r)
        self.prefix_summary_age = Gauge(
            "prefix_summary_age_seconds",
            "Age of the last accepted radix summary per worker",
            ["worker"], registry=r)
        # heartbeat payload hygiene: oversized engine_stats, bad summary
        # versions, mismatched fingerprint bases — counted, never 500d
        # (a failing heartbeat gets a LIVE worker swept offline)
        self.heartbeat_payload_rejected = Counter(
            "heartbeat_payload_rejected_total",
            "Heartbeat side-channel payloads rejected or truncated",
            ["reason"], registry=r)
        # fleet-under-fire panel (round 9): a dead/partitioned worker's
        # advertised prefix summary is zeroed the MOMENT it is marked
        # offline (not after staleness_ttl_s), so affinity can never route
        # at a dead warm worker; rejoins and the serving/registered ratio
        # show the fleet absorbing and recovering from churn; chaos
        # counters are emitted by the harness-facing seams so a chaos
        # run's injected events and the plane's observed reactions land
        # in ONE scrape.
        self.prefix_summaries_invalidated = Counter(
            "prefix_summaries_invalidated_total",
            "Worker prefix summaries zeroed before their staleness TTL",
            ["reason"], registry=r)
        self.worker_rejoin = Counter(
            "worker_rejoin_total",
            "Workers that rejoined the fleet (heartbeat revival of a "
            "swept-offline worker, or re-registration on an existing "
            "machine fingerprint)", ["worker"], registry=r)
        self.fleet_degraded = Gauge(
            "fleet_degraded",
            "Replicas serving / replicas registered (1.0 = full strength)",
            registry=r)
        # gray-failure defense (round 18): the quarantine state machine's
        # externals — per-worker state gauge (codes match
        # server.health.STATE_CODES), transition counter (a worker
        # cycling suspect↔healthy is noise; healthy→…→quarantined edges
        # are pages), worker-side deadline abandonment, and hedged
        # dispatch (offered by discovery, cancelled losers reported back
        # through the worker's direct channel)
        self.worker_health_state = Gauge(
            "worker_health_state",
            "Gray-failure health state per worker "
            "(0=healthy 1=suspect 2=quarantined 3=probation)",
            ["worker"], registry=r)
        self.health_transitions = Counter(
            "health_transitions_total",
            "Health state-machine transitions",
            ["from", "to"], registry=r)
        self.jobs_abandoned = Counter(
            "jobs_abandoned_total",
            "Requests abandoned by the worker batcher (hopeless work: "
            "the deadline passed and the projected remaining decode "
            "cannot land)",
            ["worker", "reason"], registry=r)
        self.hedges = Counter(
            "hedges_total",
            "Hedged-dispatch lifecycle events", ["outcome"], registry=r)
        self.chaos_kills = Counter(
            "chaos_kills_total",
            "Hard worker kills injected by the chaos harness", registry=r)
        self.chaos_partitions = Counter(
            "chaos_partitions_total",
            "Network partitions/blackouts injected by the chaos harness",
            registry=r)
        self.chaos_events = Counter(
            "chaos_events_total",
            "All chaos events injected by the fleet harness", ["kind"],
            registry=r)
        # disaggregated prefill/decode under fire (round 11): handoff
        # lifecycle by outcome (sender commits/failures/aborts + receiver
        # abort/purge reasons — a rising failed:committed ratio means the
        # handoff link is sick), bytes actually moved, re-prefill
        # fallbacks by reason (the flow recovering a lost handoff/KV by
        # redoing the prompt), and the per-role free-capacity balance
        # (one side at 0 while the other has headroom = the brownout the
        # role-rebalance fallback absorbs).
        self.pd_handoffs = Counter(
            "pd_handoffs_total",
            "Prefill→decode KV handoff lifecycle events by outcome",
            ["worker", "outcome"], registry=r)
        self.pd_handoff_bytes = Counter(
            "pd_handoff_bytes_total",
            "Serialized KV handoff bytes pushed by prefill workers",
            ["worker"], registry=r)
        self.pd_reprefill = Counter(
            "pd_reprefill_total",
            "PD flows re-prefilled after a stage failure, by reason",
            ["reason"], registry=r)
        self.pd_fleet_balance = Gauge(
            "pd_fleet_balance",
            "Free PD serving capacity by role (prefill/decode slots "
            "available across the registered pool)", ["role"], registry=r)
        # cluster-wide KV migration (round 13): pulls by outcome (pulled /
        # aborted mid-pull / fallback_recompute — a rising aborted rate
        # means the fleet's data planes are flaky; fallback_recompute
        # rising means budgets/backoffs or peer evictions are eating the
        # wins), bytes moved by direction, and the router's three-way
        # decision mix (warm routing collapsing into migrate under load is
        # the whole point of the feature)
        self.kv_migrations = Counter(
            "kv_migrations_total",
            "Cluster-KV prefix migration pull outcomes per worker",
            ["worker", "outcome"], registry=r)
        self.kv_migration_bytes = Counter(
            "kv_migration_bytes_total",
            "Bytes moved by cluster-KV prefix migration",
            ["worker", "direction"], registry=r)
        self.kv_route_decisions = Counter(
            "kv_route_decisions_total",
            "Router cost-model decisions (warm / migrate / recompute)",
            ["path", "choice"], registry=r)
        # predictive placement (round 20): proactive-replication hints
        # handed out per heartbeat, and predictive PD rebalance actions —
        # both advisory signals, so a panel reading hints without a
        # matching rise in kv_migrations{outcome=replicated} means the
        # workers are dropping them (budget/backoff) rather than failing
        self.kv_replicate_hints = Counter(
            "kv_replicate_hints_total",
            "Proactive prefix-replication pull hints handed to workers",
            registry=r)
        self.predictive_rebalance = Counter(
            "predictive_rebalance_total",
            "Predictive PD rebalance actions "
            "(preflip / restore / scale_out_role)",
            ["action"], registry=r)
        # SLO-native overload control (round 12): every rung of the
        # degrade/shed ladder is counted by tier — a brownout panel reads
        # "free degrading, paid accepting" directly from this series, and
        # a paid:shed sample while free:accept still flows is the alarm
        # the tier contract exists to prevent.
        self.admission_decisions = Counter(
            "admission_decisions_total",
            "Overload-control ladder decisions (accept / degrade_clamp / "
            "degrade_no_spec / shed) by tenant tier",
            ["tenant_tier", "action"], registry=r)
        # per-tenant view, label-capped: MetricsCollector maps tenants
        # beyond the top-N LRU onto one "other" label so a tenant-id-
        # spraying client cannot blow up the registry
        self.tenant_admissions = Counter(
            "tenant_admission_decisions_total",
            "Admission decisions per tenant (top-N tenants by recency; "
            "overflow aggregates under tenant=\"other\")",
            ["tenant", "action"], registry=r)
        # brownout-driven autoscaling: decisions, the replica target, the
        # measured SLO-in-window the decisions were made from, and the
        # measured cold-start lead time the scale-out projection uses
        self.autoscaler_decisions = Counter(
            "autoscaler_decisions_total",
            "Autoscaler actions (scale_out / scale_in / hold)",
            ["action"], registry=r)
        self.autoscaler_replicas = Gauge(
            "autoscaler_target_replicas",
            "Replica count the autoscaler currently targets", registry=r)
        self.autoscaler_slo = Gauge(
            "autoscaler_slo_in_window",
            "Fraction of recent requests meeting the SLO bound inside "
            "the autoscaler's observation window", registry=r)
        self.autoscaler_cold_start = Gauge(
            "autoscaler_cold_start_seconds",
            "Measured replica cold-start time (EMA) used as scale-out "
            "lead time", registry=r)
        # request flight recorder (round 14): per-phase latency
        # attribution — until now only hop and kv-migration latencies had
        # histograms; a p95 blowout could not be attributed to queue wait
        # vs prefill vs handoff vs decode. Buckets span sub-ms worker-side
        # phases through multi-minute long-context e2e.
        self.request_phase_latency = Histogram(
            "request_phase_latency_seconds",
            "Per-request phase latency from merged flight-recorder "
            "timelines (queue_wait / prefill / ttft / handoff / decode / "
            "e2e)", ["phase"], registry=r,
            buckets=PHASE_LATENCY_BUCKETS)
        self.flight_timelines = Counter(
            "flight_timelines_total",
            "Per-request timelines recorded by each worker's flight "
            "recorder", ["worker"], registry=r)
        self.flight_events_dropped = Counter(
            "flight_events_dropped_total",
            "Flight-recorder events dropped at the per-request cap",
            ["worker"], registry=r)
        # durable tier under fire (round 19): spill-tier IO health per
        # worker — a browned-out host/remote tier shows up as rising
        # errors, tripped breakers (gauge 0=closed 1=half_open 2=open),
        # and quarantined corrupt entries; store_degraded flips to 1 while
        # the plane's own job store rejects writes (reads keep serving)
        self.kv_spill_errors = Counter(
            "kv_spill_errors_total",
            "Spill-tier put/get failures absorbed by the KV manager",
            ["worker", "tier", "op"], registry=r)
        self.spill_quarantined = Counter(
            "spill_quarantined_total",
            "Spilled/persisted entries quarantined instead of served",
            ["worker", "tier", "reason"], registry=r)
        self.io_breaker_state = Gauge(
            "io_breaker_state",
            "Per-tier spill circuit breaker state "
            "(0=closed, 1=half_open, 2=open)",
            ["worker", "tier"], registry=r)
        self.store_degraded = Gauge(
            "store_degraded",
            "1 while the plane's job store is rejecting writes "
            "(submissions bounce with error_code=store_unavailable)",
            registry=r)

    def render(self) -> bytes:
        if not HAVE_PROMETHEUS or self.registry is None:
            return b"# prometheus_client not installed\n"
        return generate_latest(self.registry)


class MetricsCollector:
    """High-level facade the runtime calls into (reference :255-405)."""

    # distinct tenant label values admitted into per-tenant series before
    # new tenants aggregate under "other" — the Prometheus registry must
    # stay bounded no matter how many tenant ids a client sprays
    TENANT_LABEL_CAP = 64

    def __init__(self, metrics: Optional[Metrics] = None,
                 tenant_label_cap: Optional[int] = None) -> None:
        self.metrics = metrics or Metrics()
        self._tok_window: list[tuple[float, int]] = []
        # last-seen cumulative spec counters per worker: engines report
        # monotonic totals, Prometheus counters advance by deltas
        self._spec_prev: Dict[str, Dict[str, int]] = {}
        self._pressure_prev: Dict[str, Dict[str, int]] = {}
        self._batcher_prev: Dict[str, Dict[str, int]] = {}
        self._pd_prev: Dict[str, Dict[str, int]] = {}
        self._kvmig_prev: Dict[str, Dict[str, int]] = {}
        self._kvspill_prev: Dict[str, Dict[str, int]] = {}
        self._flight_prev: Dict[str, Dict[str, int]] = {}
        self._direct_prev: Dict[str, Dict[str, int]] = {}
        # bounded tenant-label admission (insertion-ordered dict as LRU):
        # once full, unseen tenants map to "other" — existing series keep
        # their labels (a label that has emitted samples must not migrate)
        self._tenant_label_cap = int(
            tenant_label_cap if tenant_label_cap is not None
            else self.TENANT_LABEL_CAP
        )
        self._tenant_labels: Dict[str, None] = {}

    def record_request(self, job_type: str, status: str,
                       latency_s: Optional[float] = None) -> None:
        self.metrics.inference_requests.labels(job_type, status).inc()
        if latency_s is not None:
            self.metrics.inference_latency.labels(job_type).observe(latency_s)

    def record_tokens(self, n: int, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self.metrics.tokens_generated.inc(n)
        self._tok_window.append((now, n))
        cutoff = now - 10.0
        self._tok_window = [(t, c) for t, c in self._tok_window if t >= cutoff]
        span = max(1e-6, now - self._tok_window[0][0]) if self._tok_window else 1.0
        total = sum(c for _, c in self._tok_window)
        self.metrics.tokens_per_second.set(total / span if span > 0 else 0.0)

    def record_kv_stats(self, tier: str, hit_rate: float, size_blocks: int,
                        evictions: int = 0) -> None:
        self.metrics.kv_cache_hit_rate.labels(tier).set(hit_rate)
        self.metrics.kv_cache_size.labels(tier).set(size_blocks)
        if evictions:
            self.metrics.kv_cache_evictions.labels(tier).inc(evictions)

    def record_worker_counts(self, by_status: Dict[str, int]) -> None:
        for status, n in by_status.items():
            self.metrics.worker_status.labels(status).set(n)

    def record_hop(self, latency_s: float) -> None:
        self.metrics.hop_latency.observe(latency_s)

    def record_kv_migration(self, latency_s: float) -> None:
        self.metrics.kv_migration_latency.observe(latency_s)

    def record_batch(self, size: int) -> None:
        self.metrics.batch_size.set(size)

    def record_queue(self, phase: str, size: int) -> None:
        self.metrics.queue_size.labels(phase).set(size)

    def record_speculative(self, accept_rate: float,
                           tokens_per_step: float) -> None:
        self.metrics.spec_accept_rate.set(accept_rate)
        self.metrics.spec_speedup.set(tokens_per_step)

    def record_spec_engine(self, worker: str,
                           engine_stats: Dict[str, Any]) -> None:
        """Ingest one worker engine's speculative counters
        (``TPUEngine.get_stats()`` — spec_accepted / spec_drafted /
        spec_slot_steps totals plus the derived rate/amortization gauges)
        so ``/metrics`` surfaces speculation efficiency per worker. Safe to
        call with stats from a non-speculative engine (no-op counters)."""
        prev = self._spec_prev.setdefault(worker, {})
        for key, metric in (
            ("spec_accepted", self.metrics.spec_accepted_tokens),
            ("spec_drafted", self.metrics.spec_drafted_tokens),
            ("spec_slot_steps", self.metrics.spec_decode_steps),
        ):
            try:
                cur = int(engine_stats.get(key, 0) or 0)
            except (TypeError, ValueError):
                # worker-supplied payload: one malformed field must degrade
                # to a skipped sample, never 500 the heartbeat (a failing
                # heartbeat gets a LIVE worker swept offline)
                continue
            delta = cur - prev.get(key, 0)
            if delta > 0:
                metric.labels(worker).inc(delta)
            # an engine restart resets totals — re-anchor instead of
            # emitting a bogus negative/huge delta
            prev[key] = cur
        if "spec_accept_rate" in engine_stats:
            try:
                rate = float(engine_stats.get("spec_accept_rate") or 0.0)
                tps = float(engine_stats.get("spec_tokens_per_step") or 0.0)
            except (TypeError, ValueError):
                return
            self.metrics.spec_worker_accept_rate.labels(worker).set(rate)
            self.metrics.spec_worker_tokens_per_step.labels(worker).set(tps)

    def record_pressure_engine(self, worker: str,
                               engine_stats: Dict[str, Any]) -> None:
        """Ingest one worker engine's KV-pressure counters (heartbeat
        ``engine_stats``: cumulative ``preemptions`` / ``resumes`` /
        ``kv_pressure_events`` from ``TPUEngine.get_stats()`` or the
        batcher) so ``/metrics`` surfaces per-worker preemption health.
        Same delta-anchoring as the spec counters: totals re-anchor on
        engine restart, malformed fields skip the sample, and a payload
        with no pressure keys is a no-op."""
        prev = self._pressure_prev.setdefault(worker, {})
        for key, metric in (
            ("preemptions", self.metrics.kv_preemptions),
            ("resumes", self.metrics.kv_resumes),
            ("kv_pressure_events", self.metrics.kv_pressure_events),
            # abandoned streamed-handoff sessions purged on the worker's
            # HandoffReceiver (TTL, no-progress, or session-cap eviction)
            # — rides the same heartbeat payload and delta anchoring
            ("kv_handoff_sessions_purged", self.metrics.kv_handoff_purged),
        ):
            if key not in engine_stats:
                continue
            try:
                cur = int(engine_stats.get(key, 0) or 0)
            except (TypeError, ValueError):
                continue
            delta = cur - prev.get(key, 0)
            if delta > 0:
                metric.labels(worker).inc(delta)
            prev[key] = cur

    def record_batcher_engine(self, worker: str,
                              stats: Dict[str, Any]) -> None:
        """Ingest one worker's batcher serving stats (heartbeat
        ``engine_stats["batcher"]`` — ``Worker._batcher_stats``): gauges
        set directly, counters delta-anchored like the spec/pressure
        payloads (totals re-anchor on engine restart, malformed fields
        skip the sample)."""
        for key, gauge in (
            ("queue_depth", self.metrics.batcher_queue_depth),
            ("active_slots", self.metrics.batcher_active_slots),
            ("avg_occupancy", self.metrics.batcher_occupancy),
            ("horizon", self.metrics.batcher_horizon),
        ):
            if key not in stats:
                continue
            try:
                gauge.labels(worker).set(float(stats.get(key) or 0.0))
            except (TypeError, ValueError):
                continue
        prev = self._batcher_prev.setdefault(worker, {})
        for key, metric in (
            ("decode_rounds", self.metrics.batcher_decode_rounds),
            ("completed", self.metrics.batcher_completed),
            ("chunked_admissions", self.metrics.batcher_chunked_admissions),
            ("preemptions", self.metrics.batcher_preemptions),
            ("migrated", self.metrics.batcher_migrated),
        ):
            if key not in stats:
                continue
            try:
                cur = int(stats.get(key, 0) or 0)
            except (TypeError, ValueError):
                continue
            delta = cur - prev.get(key, 0)
            if delta > 0:
                metric.labels(worker).inc(delta)
            prev[key] = cur
        if "abandoned" in stats:
            # deadline-abandonment (round 18): hopeless slots the batcher
            # freed at a step boundary — same cumulative channel, reason
            # label for future abandonment causes
            try:
                cur = int(stats.get("abandoned", 0) or 0)
            except (TypeError, ValueError):
                return
            delta = cur - prev.get("abandoned", 0)
            if delta > 0:
                self.metrics.jobs_abandoned.labels(
                    worker, "deadline").inc(delta)
            prev["abandoned"] = cur

    # heartbeat ``engine_stats["pd"]`` key → pd_handoffs_total outcome label
    _PD_OUTCOMES = (
        ("handoffs_committed", "committed"),
        ("handoffs_failed", "failed"),
        ("handoffs_aborted", "aborted"),
        ("handoffs_local", "local"),
        ("piece_retries", "piece_retry"),
        ("adopted_expired", "adopted_expired"),
        ("rx_aborts", "rx_abort"),
        ("rx_purged_ttl", "rx_purged_ttl"),
        ("rx_purged_no_progress", "rx_purged_no_progress"),
        ("rx_purged_cap", "rx_purged_cap"),
    )

    def record_pd_engine(self, worker: str,
                         pd_stats: Dict[str, Any]) -> None:
        """Ingest one worker's PD handoff lifecycle counters (heartbeat
        ``engine_stats["pd"]`` — ``TPULLMEngine.pd_wire_stats()``): sender
        outcomes + receiver abort/purge reasons into
        ``pd_handoffs_total{outcome}``, bytes into
        ``pd_handoff_bytes_total``. Same delta anchoring as the
        spec/pressure payloads: totals re-anchor on engine restart,
        malformed fields skip the sample."""
        prev = self._pd_prev.setdefault(worker, {})
        for key, outcome in self._PD_OUTCOMES:
            if key not in pd_stats:
                continue
            try:
                cur = int(pd_stats.get(key, 0) or 0)
            except (TypeError, ValueError):
                continue
            delta = cur - prev.get(key, 0)
            if delta > 0:
                self.metrics.pd_handoffs.labels(worker, outcome).inc(delta)
            prev[key] = cur
        if "handoff_bytes" in pd_stats:
            try:
                cur = int(pd_stats.get("handoff_bytes", 0) or 0)
            except (TypeError, ValueError):
                return
            delta = cur - prev.get("handoff_bytes", 0)
            if delta > 0:
                self.metrics.pd_handoff_bytes.labels(worker).inc(delta)
            prev["handoff_bytes"] = cur

    # heartbeat ``engine_stats["kv_migrate"]`` key → outcome label
    _KVMIG_OUTCOMES = (
        ("pulled", "pulled"),
        ("fallback_recompute", "fallback_recompute"),
        ("aborted", "aborted"),
        ("local_hits", "local_hit"),
        ("exports", "export_served"),
        ("prefix_commits", "prefix_commit"),
        # proactive replication (round 20): hint-driven pulls, keyed off
        # the same engine stats dict — committed / fp-miss (exporter
        # churned the prefix out) / aborted mid-pull
        ("replicated", "replicated"),
        ("replicate_miss", "replicate_miss"),
        ("replicate_aborted", "replicate_aborted"),
    )

    def record_kv_migrate_engine(self, worker: str,
                                 stats: Dict[str, Any]) -> None:
        """Ingest one worker's cluster-KV migration counters (heartbeat
        ``engine_stats["kv_migrate"]`` — ``TPULLMEngine.
        kv_migrate_wire_stats()``): pull outcomes into
        ``kv_migrations_total{outcome}``, bytes into
        ``kv_migration_bytes_total{direction}``. Same delta anchoring as
        the spec/pressure/pd payloads: totals re-anchor on engine restart,
        malformed fields skip the sample."""
        prev = self._kvmig_prev.setdefault(worker, {})
        for key, outcome in self._KVMIG_OUTCOMES:
            if key not in stats:
                continue
            try:
                cur = int(stats.get(key, 0) or 0)
            except (TypeError, ValueError):
                continue
            delta = cur - prev.get(key, 0)
            if delta > 0:
                self.metrics.kv_migrations.labels(worker, outcome).inc(delta)
            prev[key] = cur
        for key, direction in (("pull_bytes", "pull"),
                               ("export_bytes", "export")):
            if key not in stats:
                continue
            try:
                cur = int(stats.get(key, 0) or 0)
            except (TypeError, ValueError):
                continue
            delta = cur - prev.get(key, 0)
            if delta > 0:
                self.metrics.kv_migration_bytes.labels(
                    worker, direction
                ).inc(delta)
            prev[key] = cur

    def record_kv_replicate_hints(self, n: int) -> None:
        """Count proactive-replication hints handed out in a heartbeat
        response (the plane-side half; the worker-side outcomes arrive
        through ``record_kv_migrate_engine``)."""
        if n > 0:
            self.metrics.kv_replicate_hints.inc(n)

    def record_predictive_rebalance(self, action: str) -> None:
        """Count one predictive PD rebalance action (preflip / restore /
        scale_out_role)."""
        self.metrics.predictive_rebalance.labels(action).inc()

    def record_kv_spill_engine(self, worker: str,
                               stats: Dict[str, Any]) -> None:
        """Ingest one worker's spill-tier IO health counters (heartbeat
        ``engine_stats["kv_spill"]`` — ``TPULLMEngine.
        kv_spill_wire_stats()``): per-tier put/get failures into
        ``kv_spill_errors_total{tier,op}``, corrupt-entry quarantines (and
        refused corrupt checkpoints) into
        ``spill_quarantined_total{tier,reason}``, breaker states straight
        onto the ``io_breaker_state{tier}`` gauge. Same delta anchoring as
        the spec/pressure/pd/kv-migrate payloads: totals re-anchor on
        engine restart, malformed fields skip the sample."""
        prev = self._kvspill_prev.setdefault(worker, {})

        def _delta(key: str) -> int:
            try:
                cur = int(stats.get(key, 0) or 0)
            except (TypeError, ValueError):
                return 0
            d = cur - prev.get(key, 0)
            prev[key] = cur
            return max(0, d)

        for tier in ("host", "remote"):
            for op in ("put", "get"):
                d = _delta(f"{tier}_{op}_errors")
                if d:
                    self.metrics.kv_spill_errors.labels(
                        worker, tier, op
                    ).inc(d)
            d = _delta(f"{tier}_quarantined_corrupt")
            if d:
                self.metrics.spill_quarantined.labels(
                    worker, tier, "corrupt"
                ).inc(d)
            if f"breaker_{tier}_state" in stats:
                try:
                    self.metrics.io_breaker_state.labels(worker, tier).set(
                        int(stats[f"breaker_{tier}_state"])
                    )
                except (TypeError, ValueError):
                    pass
        d = _delta("ckpt_corrupt")
        if d:
            self.metrics.spill_quarantined.labels(
                worker, "checkpoint", "corrupt"
            ).inc(d)

    def record_store_degraded(self, degraded: bool) -> None:
        """Flip the ``store_degraded`` gauge: 1 while the plane's own job
        store rejects writes (submissions bounce typed-503), back to 0 on
        the next write that lands."""
        self.metrics.store_degraded.set(1 if degraded else 0)

    def record_phase(self, phase: str, seconds: float) -> None:
        """One derived flight-recorder phase duration → the
        ``request_phase_latency_seconds{phase}`` histogram. Unknown phase
        names are recorded as-is (the label set is the canonical
        ``runtime.flight.PHASES``, but the histogram is not the place to
        police it)."""
        try:
            self.metrics.request_phase_latency.labels(str(phase)).observe(
                float(seconds)
            )
        except (TypeError, ValueError):
            pass

    def record_flight_engine(self, worker: str,
                             stats: Dict[str, Any]) -> None:
        """Ingest one worker's flight-recorder counters (heartbeat
        ``engine_stats["flight"]`` — cumulative ``timelines`` /
        ``events_dropped``). Same delta anchoring as the
        spec/pressure/pd/kv-migrate payloads: totals re-anchor on engine
        restart (a smaller total emits no bogus negative delta, just
        re-anchors), malformed fields skip the sample."""
        prev = self._flight_prev.setdefault(worker, {})
        for key, metric in (
            ("timelines", self.metrics.flight_timelines),
            ("events_dropped", self.metrics.flight_events_dropped),
        ):
            if key not in stats:
                continue
            try:
                cur = int(stats.get(key, 0) or 0)
            except (TypeError, ValueError):
                continue
            delta = cur - prev.get(key, 0)
            if delta > 0:
                metric.labels(worker).inc(delta)
            prev[key] = cur

    def record_kv_route_decision(self, path: str, choice: str) -> None:
        """One cost-model route decision on ``path`` (``direct`` discovery
        or the ``queued`` claim): warm / migrate / recompute."""
        self.metrics.kv_route_decisions.labels(path, choice).inc()

    def record_pd_reprefill(self, reason: str) -> None:
        """One PD flow fell back to re-prefill (stage failure, lost
        handoff, dead kv_holder) — plane-side, counted by reason."""
        self.metrics.pd_reprefill.labels(reason).inc()

    def record_pd_fleet_balance(self, capacity: Dict[str, int]) -> None:
        """Refresh the per-role free-capacity gauge from the PD
        scheduler's registered pool (``capacity_by_role()``)."""
        for role in ("prefill", "decode"):
            self.metrics.pd_fleet_balance.labels(role).set(
                float(capacity.get(role, 0) or 0)
            )

    # -- overload control / autoscaling (round 12) --------------------------

    def tenant_label(self, tenant: str) -> str:
        """Map a tenant id onto a bounded label set: known tenants keep
        their label, new tenants are admitted until the cap, then
        aggregate under ``other``. Deliberately NOT an evicting LRU for
        label purposes: a label that has emitted samples keeps meaning
        forever (re-assigning it would corrupt the series), so admission
        is first-come-first-labeled."""
        tenant = str(tenant)[:128]
        if tenant in self._tenant_labels:
            return tenant
        if len(self._tenant_labels) < self._tenant_label_cap:
            self._tenant_labels[tenant] = None
            return tenant
        return "other"

    def record_admission(self, tier: str, action: str,
                         tenant: Optional[str] = None) -> None:
        """One overload-ladder decision: counted by tier always, and per
        tenant under the bounded label map."""
        self.metrics.admission_decisions.labels(tier, action).inc()
        if tenant is not None:
            self.metrics.tenant_admissions.labels(
                self.tenant_label(tenant), action
            ).inc()

    def record_autoscaler(self, action: str,
                          target_replicas: Optional[int] = None,
                          slo_in_window: Optional[float] = None,
                          cold_start_s: Optional[float] = None) -> None:
        """One autoscaler tick: the decision (scale_out/scale_in/hold)
        plus the observations it was made from."""
        self.metrics.autoscaler_decisions.labels(action).inc()
        if target_replicas is not None:
            self.metrics.autoscaler_replicas.set(float(target_replicas))
        if slo_in_window is not None:
            self.metrics.autoscaler_slo.set(float(slo_in_window))
        if cold_start_s is not None:
            self.metrics.autoscaler_cold_start.set(float(cold_start_s))

    def record_prefix_route(self, path: str, hit: bool,
                            spillover: bool = False) -> None:
        """One routing decision on ``path`` (``direct`` discovery or the
        ``queued`` claim): hit when the chosen worker advertised the
        request's prefix, spillover when a warmer worker existed but was
        passed over."""
        if hit:
            self.metrics.prefix_route_hits.labels(path).inc()
        if spillover:
            self.metrics.prefix_route_spillover.labels(path).inc()

    def record_prefix_summary(self, worker: str, entries: int,
                              age_s: float) -> None:
        self.metrics.prefix_summary_entries.labels(worker).set(entries)
        self.metrics.prefix_summary_age.labels(worker).set(age_s)

    def record_heartbeat_payload_rejected(self, reason: str) -> None:
        self.metrics.heartbeat_payload_rejected.labels(reason).inc()

    def record_prefix_summary_invalidated(self, reason: str) -> None:
        """One worker's advertised summary zeroed ahead of its staleness
        TTL (marked offline, swept for a stale heartbeat, partitioned)."""
        self.metrics.prefix_summaries_invalidated.labels(reason).inc()

    def record_worker_rejoin(self, worker: str) -> None:
        self.metrics.worker_rejoin.labels(worker).inc()

    def record_fleet_strength(self, serving: int, registered: int) -> None:
        """Refresh the ``fleet_degraded`` gauge: replicas currently able
        to take work over replicas the plane knows about."""
        ratio = (serving / registered) if registered else 1.0
        self.metrics.fleet_degraded.set(max(0.0, min(1.0, ratio)))

    def record_health_transition(self, frm: str, to: str) -> None:
        """One edge of the gray-failure state machine (round 18)."""
        self.metrics.health_transitions.labels(frm, to).inc()

    def record_health_states(self, states: Dict[str, str]) -> None:
        """Scrape-time refresh of the per-worker health-state gauge."""
        from .health import STATE_CODES

        for wid, state in states.items():
            self.metrics.worker_health_state.labels(wid).set(
                STATE_CODES.get(state, 0)
            )

    def record_hedge(self, outcome: str, n: int = 1) -> None:
        """Hedged-dispatch lifecycle: ``offered`` at discovery time
        (plane-side), ``cancelled`` losers delta-reported through the
        worker's direct channel."""
        if n > 0:
            self.metrics.hedges.labels(outcome).inc(n)

    def record_direct_engine(self, worker: str,
                             stats: Dict[str, Any]) -> None:
        """Ingest one worker's direct-serving channel (heartbeat
        ``engine_stats["direct"]`` — ``DirectServer.wire_stats()``):
        cancelled hedge losers into ``hedges_total{outcome=cancelled}``.
        Same delta anchoring as every other engine payload; the latency
        samples riding the same channel feed the HealthService, not a
        metric."""
        prev = self._direct_prev.setdefault(worker, {})
        if "hedge_cancels" in stats:
            try:
                cur = int(stats.get("hedge_cancels", 0) or 0)
            except (TypeError, ValueError):
                return
            delta = cur - prev.get("hedge_cancels", 0)
            if delta > 0:
                self.metrics.hedges.labels("cancelled").inc(delta)
            prev["hedge_cancels"] = cur

    def record_chaos_event(self, kind: str) -> None:
        """Harness-facing seam: the fleet chaos driver reports each event
        it executes, so injected faults and the plane's observed reactions
        (requeues, rejoins, invalidations) share one scrape timeline."""
        self.metrics.chaos_events.labels(kind).inc()
        if kind in ("kill",):
            self.metrics.chaos_kills.inc()
        elif kind in ("partition", "blackout", "handoff_partition"):
            self.metrics.chaos_partitions.inc()

    def record_checkpoint(self, worker: str) -> None:
        self.metrics.job_checkpoints.labels(worker).inc()

    def record_checkpoint_rejected(self, reason: str) -> None:
        self.metrics.checkpoints_rejected.labels(reason).inc()

    def record_stream_failover(self) -> None:
        self.metrics.stream_failovers.inc()

    def render(self) -> bytes:
        return self.metrics.render()


# ---------------------------------------------------------------------------
# Tracing (reference :157-246)
# ---------------------------------------------------------------------------


def otel_console_from_env() -> bool:
    """``DGI_OTEL_CONSOLE=1`` turns on the console span exporter — the
    previously-unreachable ``TracingManager(console_export=...)`` knob
    (no caller could ever enable it) is now operator-settable without a
    code change. Off by default."""
    return os.environ.get("DGI_OTEL_CONSOLE", "").strip().lower() in (
        "1", "true", "yes", "on",
    )


class TracingManager:
    def __init__(self, service_name: str = "dgi-tpu",
                 console_export: Optional[bool] = None) -> None:
        if console_export is None:
            console_export = otel_console_from_env()
        self.enabled = HAVE_OTEL
        if not self.enabled:
            self._tracer = None
            return
        provider = TracerProvider()
        if console_export:  # deployments swap in OTLP/Jaeger exporters
            provider.add_span_processor(
                BatchSpanProcessor(ConsoleSpanExporter())
            )
        self._tracer = provider.get_tracer(service_name)

    @contextlib.contextmanager
    def span(self, name: str, **attributes: Any) -> Iterator[Any]:
        if not self.enabled or self._tracer is None:
            yield None
            return
        with self._tracer.start_as_current_span(name) as sp:
            for k, v in attributes.items():
                try:
                    sp.set_attribute(k, v)
                except Exception:  # noqa: BLE001
                    pass
            try:
                yield sp
            except Exception as exc:
                sp.record_exception(exc)
                raise

    def emit_span(self, name: str, start_s: float, end_s: float,
                  **attributes: Any) -> None:
        """One RETROACTIVE span (explicit wall-clock start/end): the
        flight recorder derives phase boundaries after the fact and maps
        each onto an OTel span. No-op without opentelemetry; best-effort
        with it (a tracing failure must never fail a request)."""
        if not self.enabled or self._tracer is None:
            return
        try:
            sp = self._tracer.start_span(
                name, start_time=int(float(start_s) * 1e9)
            )
            for k, v in attributes.items():
                try:
                    sp.set_attribute(k, v)
                except Exception:  # noqa: BLE001
                    pass
            sp.end(end_time=int(float(end_s) * 1e9))
        except Exception:  # noqa: BLE001 — advisory by contract
            pass


@contextlib.contextmanager
def tpu_profiler_trace(log_dir: str = "/tmp/dgi_tpu_profile") -> Iterator[None]:
    """Wrap a region in a jax.profiler trace (TPU timeline capture).

    No-op when jax is unavailable; safe to leave in production paths.
    """
    try:
        import jax

        with jax.profiler.trace(log_dir):
            yield
    except Exception:  # noqa: BLE001 — profiling must never break serving
        yield


# ---------------------------------------------------------------------------
# Structured logging (reference :455-488)
# ---------------------------------------------------------------------------


class StructuredLogger:
    def __init__(self, name: str = "dgi-tpu",
                 context: Optional[Dict[str, Any]] = None) -> None:
        self._log = logging.getLogger(name)
        self._context = dict(context or {})

    def bind(self, **context: Any) -> "StructuredLogger":
        merged = {**self._context, **context}
        child = StructuredLogger(self._log.name, merged)
        return child

    def _emit(self, level: int, event: str, **fields: Any) -> None:
        payload = {"event": event, "ts": time.time(), **self._context, **fields}
        self._log.log(level, json.dumps(payload, default=str))

    def info(self, event: str, **fields: Any) -> None:
        self._emit(logging.INFO, event, **fields)

    def warning(self, event: str, **fields: Any) -> None:
        self._emit(logging.WARNING, event, **fields)

    def error(self, event: str, **fields: Any) -> None:
        self._emit(logging.ERROR, event, **fields)
