"""Failure detection, retry/requeue, sync-wait — the delivery guarantee layer.

Behavioral parity with the reference's ``server/app/services/task_guarantee.py``:
- On worker offline: requeue its RUNNING jobs until ``max_retries``, then fail
  (:60-96).
- Stale-job sweep: RUNNING jobs past per-job timeout (default cap 30 min)
  are requeued/failed (:98-158).
- Dead-worker sweep: heartbeat older than 90 s → worker OFFLINE, its jobs
  requeued (:160-185).
- ``wait_for_job``: poll until terminal status or timeout (:187-228).
- Background loop every 30 s (:231-263).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional

from ..utils.data_structures import JobStatus, WorkerState
from .reliability import ReliabilityService
from .store import Store

log = logging.getLogger("dgi-tpu.task_guarantee")

HEARTBEAT_TIMEOUT_S = 90.0
STALE_JOB_CAP_S = 30 * 60.0
SWEEP_INTERVAL_S = 30.0
SYNC_POLL_INTERVAL_S = 0.5
# direct-stream checkpoints are retired by AGE, not by the worker: a worker
# cannot know its final SSE bytes reached the client, so an eager "done"
# delete could erase the state a tail-less client still needs to resume
STREAM_CHECKPOINT_TTL_S = 30 * 60.0


class TaskGuaranteeService:
    def __init__(self, store: Store,
                 reliability: Optional[ReliabilityService] = None,
                 heartbeat_timeout_s: float = HEARTBEAT_TIMEOUT_S,
                 on_permanent_failure: Optional[
                     Callable[[Dict[str, Any]], Awaitable[None]]
                 ] = None,
                 on_worker_offline: Optional[
                     Callable[[str, str], Awaitable[None]]
                 ] = None) -> None:
        self._store = store
        self._reliability = reliability or ReliabilityService(store)
        self._heartbeat_timeout_s = heartbeat_timeout_s
        # called with the job row whenever a sweep marks a job FAILED for
        # good (retries exhausted, container timeout, pinned worker gone);
        # the PD flow uses it to fail containers promptly (server/app.py)
        self.on_permanent_failure = on_permanent_failure
        # called with (worker_id, reason) whenever a worker is marked
        # offline — ServerState uses it to zero the worker's advertised
        # prefix summary immediately (routing must not keep preferring a
        # dead warm worker for the rest of its staleness TTL)
        self.on_worker_offline = on_worker_offline

    async def _notify_failed(self, job_id: str) -> None:
        if self.on_permanent_failure is None:
            return
        job = await self._store.get_job(job_id)
        if job is None:
            return
        try:
            await self.on_permanent_failure(job)
        except Exception:  # noqa: BLE001 — propagation must not break sweeps
            log.exception(
                "permanent-failure hook failed for job %s (its PD container,"
                " if any, will only terminate via its own timeout)",
                job.get("id"),
            )

    # -- requeue machinery ---------------------------------------------------

    async def requeue_job(self, job: Dict[str, Any],
                          reason: str = "worker_offline") -> str:
        """Requeue one job (or fail it if retries exhausted). Returns the
        job's resulting status value. Frees the assigned worker's capacity
        state so a timed-out job doesn't leave a phantom BUSY worker.

        Every job write is a CONDITIONAL transition from the caller's
        snapshot status: a completion racing the sweep (slow-but-alive
        worker reporting just as the sweep fires) must keep its terminal
        status — an unconditional overwrite would revert COMPLETED to
        QUEUED and re-execute the job, double-applying reliability and
        usage."""
        wid = job.get("worker_id")
        if wid:
            w = await self._store.get_worker(wid)
            if w is not None and w.get("current_job_id") == job["id"]:
                fields: Dict[str, Any] = {"current_job_id": None}
                if w.get("status") == WorkerState.BUSY.value:
                    fields["status"] = WorkerState.IDLE.value
                await self._store.update_worker(wid, **fields)

        async def _lost_race() -> str:
            cur = await self._store.get_job(job["id"])
            return cur["status"] if cur is not None else JobStatus.FAILED.value

        params = job.get("params") or {}
        if params.get("pd_disaggregated") and not params.get("pd_stage"):
            # a PD CONTAINER job must never become claimable: requeueing it
            # would hand the whole generation to an arbitrary worker while
            # its pinned stage children still run (double execution). On
            # timeout the flow fails; a late stage completion finds the
            # parent terminal and no-ops (pd_flow.on_child_complete guard).
            # Stage children requeue normally below — they INHERIT the
            # parent's params (pd_disaggregated included), so the pd_stage
            # exclusion above is what keeps them out of this branch.
            won = await self._store.try_transition_job(
                job["id"], job["status"],
                status=JobStatus.FAILED.value,
                error=f"pd flow timed out: {reason}",
                completed_at=time.time(),
            )
            if not won:
                return await _lost_race()
            await self._notify_failed(job["id"])
            return JobStatus.FAILED.value
        retries = int(job.get("retry_count") or 0)
        max_retries = int(job.get("max_retries") or 3)
        if retries + 1 > max_retries:
            fields: Dict[str, Any] = {
                "status": JobStatus.FAILED.value,
                "error": f"exceeded max_retries ({max_retries}): {reason}",
                "completed_at": time.time(),
            }
            partial = self._partial_from_checkpoint(job)
            if partial is not None and not job.get("result"):
                # the job dies, but its last checkpoint's decoded tokens
                # don't have to: surface them exactly like the engine's
                # preempted_too_often partials, so a client can keep what
                # the fleet DID produce across however many failovers
                fields["result"] = partial
            won = await self._store.try_transition_job(
                job["id"], job["status"], owned_by=wid, **fields
            )
            if not won:
                return await _lost_race()
            await self._notify_failed(job["id"])
            return JobStatus.FAILED.value
        # NOTE: the job's ``checkpoint`` column is deliberately untouched —
        # a requeued job carries its latest generation checkpoint to the
        # next claimant, which resumes instead of regenerating
        won = await self._store.try_transition_job(
            job["id"], job["status"], owned_by=wid,
            status=JobStatus.QUEUED.value,
            worker_id=None,
            started_at=None,
            retry_count=retries + 1,
        )
        if not won:
            return await _lost_race()
        return JobStatus.QUEUED.value

    @staticmethod
    def _partial_from_checkpoint(
        job: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        """Partial-output payload recovered from a job's latest generation
        checkpoint (None when there is nothing to preserve)."""
        ckpt = job.get("checkpoint")
        if not isinstance(ckpt, dict):
            return None
        gen = ckpt.get("generated")
        if not gen:
            return None
        return {
            "partial": True,
            "partial_token_ids": [int(t) for t in gen],
            "partial_tokens": len(gen),
        }

    async def handle_worker_offline(self, worker_id: str,
                                    graceful: bool = False,
                                    reason: str = "worker_offline"
                                    ) -> List[str]:
        """Mark worker offline and requeue its running jobs (:60-96)."""
        running = await self._store.list_jobs(
            status=[JobStatus.RUNNING.value], worker_id=worker_id
        )
        requeued = []
        for job in running:
            await self.requeue_job(job, reason=reason)
            requeued.append(job["id"])
        await self._store.update_worker(
            worker_id,
            status=WorkerState.OFFLINE.value,
            current_job_id=None,
        )
        await self._reliability.end_session(worker_id, graceful=graceful)
        if self.on_worker_offline is not None:
            try:
                await self.on_worker_offline(worker_id, reason)
            except Exception:  # noqa: BLE001 — advisory hook, never fatal
                log.exception("worker-offline hook failed for %s", worker_id)
        return requeued

    # -- sweeps ---------------------------------------------------------------

    async def sweep_stale_jobs(self, now: Optional[float] = None) -> List[str]:
        now = time.time() if now is None else now
        running = await self._store.list_jobs(
            status=[JobStatus.RUNNING.value], limit=1000
        )
        swept = []
        for job in running:
            started = job.get("started_at")
            if started is None:
                continue
            timeout = min(
                float(job.get("timeout_seconds") or 300.0), STALE_JOB_CAP_S
            )
            if now - float(started) > timeout:
                await self.requeue_job(job, reason="job_timeout")
                swept.append(job["id"])
        return swept

    async def sweep_dead_workers(self, now: Optional[float] = None) -> List[str]:
        now = time.time() if now is None else now
        workers = await self._store.list_workers(
            status=[
                WorkerState.IDLE.value,
                WorkerState.BUSY.value,
                WorkerState.DRAINING.value,
            ]
        )
        dead = []
        for w in workers:
            hb = w.get("last_heartbeat")
            if hb is None or now - float(hb) > self._heartbeat_timeout_s:
                # handle_worker_offline → end_session(graceful=False) already
                # applies the unexpected_offline penalty exactly once
                await self.handle_worker_offline(
                    w["id"], graceful=False, reason="heartbeat_stale"
                )
                dead.append(w["id"])
        return dead

    async def sweep_orphaned_pins(
        self, now: Optional[float] = None
    ) -> List[str]:
        """QUEUED jobs pinned to a worker (``params.target_worker`` — PD
        stage children, whose KV lives or lands on exactly that worker)
        can only ever be claimed by their pin. When the pinned worker is
        gone for good the job is unrunnable — no retry can help, because
        the pin IS the point — so fail it; the permanent-failure hook
        fails the container in the same pass. Without this sweep such a
        child sits QUEUED forever (the stale sweep covers only RUNNING)
        and strands its parent for the full container timeout.

        A freshly-OFFLINE worker gets a grace window of one extra
        heartbeat timeout: heartbeats revive OFFLINE workers (a flap is
        recoverable), and failing every pinned generation on a single
        missed heartbeat would turn a transient blip into data loss."""
        import json as _json

        now = time.time() if now is None else now
        # substring pre-filter (same idiom as the claim path): pinned jobs
        # are the rare case, so select exactly them — no LIMIT cap that
        # could silently exempt low-priority pins under a deep backlog
        rows = await self._store.query(
            "SELECT id, params FROM jobs WHERE status=? AND params LIKE ?",
            (JobStatus.QUEUED.value, '%"target_worker"%'),
        )
        failed = []
        worker_cache: Dict[str, Optional[Dict[str, Any]]] = {}
        for job in rows:
            try:
                target = (_json.loads(job["params"] or "{}")
                          .get("target_worker"))
            except ValueError:
                continue
            if not target:
                continue
            if target not in worker_cache:
                worker_cache[target] = await self._store.get_worker(target)
            w = worker_cache[target]
            if w is not None:
                if w.get("status") != WorkerState.OFFLINE.value:
                    continue
                hb = w.get("last_heartbeat")
                if hb is not None and \
                        now - float(hb) < 2.0 * self._heartbeat_timeout_s:
                    continue    # flap grace: the pin may still come back
            # conditional transition: a revived pin racing this sweep may
            # have just claimed the job (QUEUED→RUNNING) — never clobber a
            # live claim with FAILED
            won = await self._store.try_transition_job(
                job["id"], JobStatus.QUEUED.value,
                status=JobStatus.FAILED.value,
                error=f"pinned worker {target} offline",
                completed_at=now,
            )
            if not won:
                continue
            await self._notify_failed(job["id"])
            failed.append(job["id"])
        return failed

    async def sweep_stale_stream_checkpoints(
        self, now: Optional[float] = None
    ) -> List[str]:
        """Age out direct-stream checkpoints nobody resumed: a client that
        lost a stream tail reconnects within seconds, so anything older
        than ``STREAM_CHECKPOINT_TTL_S`` is an abandoned stream whose
        state would otherwise accumulate forever."""
        now = time.time() if now is None else now
        rows = await self._store.query(
            "SELECT stream_id FROM stream_checkpoints WHERE updated_at < ?",
            (now - STREAM_CHECKPOINT_TTL_S,),
        )
        purged = [r["stream_id"] for r in rows]
        if purged:
            await self._store.execute(
                "DELETE FROM stream_checkpoints WHERE updated_at < ?",
                (now - STREAM_CHECKPOINT_TTL_S,),
            )
        return purged

    async def sweep(self, now: Optional[float] = None) -> Dict[str, List[str]]:
        return {
            "dead_workers": await self.sweep_dead_workers(now=now),
            "stale_jobs": await self.sweep_stale_jobs(now=now),
            # after the dead-worker pass: once a pinned worker's flap grace
            # (2× heartbeat timeout) has elapsed, its freshly-OFFLINE state
            # and its children's orphaning land in the same sweep pass
            "orphaned_pins": await self.sweep_orphaned_pins(now=now),
            "stale_stream_checkpoints":
                await self.sweep_stale_stream_checkpoints(now=now),
        }

    # -- sync wait (reference :187-228) ---------------------------------------

    async def wait_for_job(self, job_id: str, timeout_s: float = 300.0,
                           poll_s: float = SYNC_POLL_INTERVAL_S
                           ) -> Optional[Dict[str, Any]]:
        deadline = time.monotonic() + timeout_s
        terminal = {
            JobStatus.COMPLETED.value,
            JobStatus.FAILED.value,
            JobStatus.CANCELLED.value,
        }
        while time.monotonic() < deadline:
            job = await self._store.get_job(job_id)
            if job is None:
                return None
            if job["status"] in terminal:
                return job
            await asyncio.sleep(poll_s)
        return await self._store.get_job(job_id)


class TaskGuaranteeBackgroundWorker:
    """Runs the sweeps every ``interval_s`` (reference :231-263)."""

    def __init__(self, service: TaskGuaranteeService,
                 interval_s: float = SWEEP_INTERVAL_S) -> None:
        self._service = service
        self._interval = interval_s
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    async def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self._service.sweep()
            except Exception:  # noqa: BLE001 — sweep must never kill the loop
                pass
            try:
                await asyncio.wait_for(self._stop.wait(), timeout=self._interval)
            except asyncio.TimeoutError:
                continue

    def start(self) -> None:
        if self._task is None:
            self._stop.clear()
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._stop.set()
            await self._task
            self._task = None
