"""Prefill/decode-disaggregated scheduling (DistServe-style) for TPU pools.

Behavioral parity with the reference's ``server/app/services/pd_scheduler.py``:

- :class:`WorkerCapability` separates compute capacity (prefill is
  FLOPs-bound) from memory bandwidth (decode is HBM-bound) — reference
  ``pd_scheduler.py:38-79``.
- Separate prefill/decode queues (:133-135), batched pop with per-phase
  timeouts (prefill 20 ms, decode 5 ms — :121-123, :350-380).
- Prefill assignment maximizes ``flops / (1 + active)`` (:245-272); decode
  assignment is KV-affinity-first, else best bandwidth + migration flag
  (:274-323); analytic latency estimators (:325-348).
- :class:`KVCacheMigrator` dedups concurrent migrations of the same key
  (:432-438) — but unlike the reference, whose migration body is a simulated
  50 ms sleep (:462-472), migration here is REAL: a pluggable transport moves
  serialized KV pages between engines (`runtime/kv_handoff.py`), and the
  in-process default does a full export→wire→adopt round trip.

TPU re-design notes:

- Capacities derive from :class:`TpuTopology` (chip generation → bf16 TFLOP/s
  and HBM GB/s), not nvidia-smi probes. A v5e-64 deployment splits the pod's
  slices into a prefill partition and a decode partition (BASELINE config 5:
  16 prefill chips / 48 decode chips); each partition is one "worker" here.
- Intra-pod handoff rides ICI (device-to-device), so the migrator's transport
  is where the deployment chooses ICI vs DCN; the scheduler only decides
  *whether* and *where* to move KV.
"""

from __future__ import annotations

import asyncio
import heapq
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from ..utils.data_structures import (
    TpuTopology,
    WorkerRole,
    estimate_kv_cache_bytes,
)

# Per-chip HBM bandwidth by generation (GB/s) — public TPU specs.
_HBM_GBPS = {"v4": 1228.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1640.0, "cpu": 50.0}

PREFILL_BATCH_TIMEOUT_S = 0.020   # reference pd_scheduler.py:121
DECODE_BATCH_TIMEOUT_S = 0.005    # reference pd_scheduler.py:123


@dataclass
class WorkerCapability:
    """Compute-vs-bandwidth profile of one pool partition
    (reference ``WorkerCapability``, pd_scheduler.py:38-79)."""

    worker_id: str
    role: WorkerRole = WorkerRole.HYBRID
    # bandwidth fields are GB/s (gigaBYTES), matching _HBM_GBPS
    compute_tflops: float = 197.0        # aggregate bf16 TFLOP/s
    memory_bandwidth_gbps: float = 819.0  # aggregate HBM GB/s
    hbm_gb: float = 16.0
    interconnect_gbps: float = 25.0      # GB/s to OTHER partitions (ICI/DCN)
    max_prefill_batch: int = 8
    max_decode_batch: int = 64

    @classmethod
    def from_topology(cls, worker_id: str, topo: TpuTopology,
                      role: WorkerRole = WorkerRole.HYBRID,
                      **kw: Any) -> "WorkerCapability":
        per_chip_bw = _HBM_GBPS.get(topo.chip_type, 819.0)
        derived: Dict[str, Any] = dict(
            worker_id=worker_id,
            role=role,
            compute_tflops=topo.peak_bf16_tflops * topo.num_chips,
            memory_bandwidth_gbps=per_chip_bw * topo.num_chips,
            hbm_gb=topo.total_hbm_gb,
            interconnect_gbps=topo.ici_bandwidth_gbps,
        )
        derived.update(kw)  # explicit overrides win over topology-derived
        return cls(**derived)

    @property
    def can_prefill(self) -> bool:
        return self.role in (WorkerRole.PREFILL, WorkerRole.HYBRID)

    @property
    def can_decode(self) -> bool:
        return self.role in (WorkerRole.DECODE, WorkerRole.HYBRID)


@dataclass
class _PoolWorker:
    cap: WorkerCapability
    active_prefill: int = 0
    active_decode: int = 0
    total_prefills: int = 0
    total_decodes: int = 0


@dataclass(order=True)
class _QueueEntry:
    sort_key: Tuple[int, float]
    req: "PDRequest" = field(compare=False)


@dataclass
class PDRequest:
    """One request tracked through both phases."""

    request_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    prompt_tokens: int = 0
    max_new_tokens: int = 256
    priority: int = 0
    model_name: str = "llama3-8b"
    arrival: float = field(default_factory=time.time)
    # phase state
    phase: str = "prefill"               # prefill | decode | done
    prefill_worker: Optional[str] = None
    decode_worker: Optional[str] = None
    kv_cache_key: Optional[str] = None
    kv_holder: Optional[str] = None      # worker currently holding the KV
    needs_migration: bool = False
    excluded_workers: set = field(default_factory=set)  # failed migration dsts
    migration_attempts: int = 0
    # re-prefill fallback counter (pd_flow): a failed stage re-places the
    # WHOLE flow — prefill again from the prompt — up to the flow's budget,
    # without burning the job's own retry_count
    attempt: int = 0
    # model geometry for KV size estimates
    num_layers: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128

    @property
    def kv_bytes(self) -> int:
        return estimate_kv_cache_bytes(
            self.num_layers, self.num_kv_heads, self.head_dim,
            self.prompt_tokens + self.max_new_tokens,
        )


class PrefillDecodeScheduler:
    """Routes requests through disaggregated prefill and decode pools."""

    def __init__(self, migrator: Optional["KVCacheMigrator"] = None,
                 max_migration_attempts: int = 3,
                 allow_role_rebalance: bool = True) -> None:
        self._workers: Dict[str, _PoolWorker] = {}
        self._prefill_q: List[_QueueEntry] = []
        self._decode_q: List[_QueueEntry] = []
        # decode requests whose background KV migration has completed and are
        # ready to hand out on the next get_batch("decode")
        self._ready_migrated: deque = deque()
        self._bg_tasks: set = set()
        self._cv = asyncio.Condition()
        self.migrator = migrator
        self.max_migration_attempts = max_migration_attempts
        # brownout rebalance: when one SIDE of a split fleet has no capacity
        # (every prefill worker dead or saturated), workers of the OTHER
        # role temporarily accept hybrid work instead of idling while the
        # starved queue melts down — counted, so the condition is visible
        self.allow_role_rebalance = allow_role_rebalance
        # predictive rebalance (round 20): worker_id -> ORIGINAL role for
        # workers temporarily flipped to HYBRID ahead of a projected SLO
        # miss (reactive role_rebalance above only fires once a side is
        # already dark; the preflip acts on the projection)
        self._preflipped: Dict[str, WorkerRole] = {}
        self.stats: Dict[str, Any] = {
            "submitted": 0, "prefills_assigned": 0, "decodes_assigned": 0,
            "migrations_requested": 0, "affinity_hits": 0, "completed": 0,
            "migration_failures": 0, "migration_dropped": 0,
            "role_rebalanced_prefill": 0, "role_rebalanced_decode": 0,
            "preflipped": 0, "preflip_restored": 0,
        }

    # -- pool membership ----------------------------------------------------

    def register_worker(self, cap: WorkerCapability) -> None:
        self._workers[cap.worker_id] = _PoolWorker(cap=cap)

    def remove_worker(self, worker_id: str) -> None:
        self._workers.pop(worker_id, None)
        self._preflipped.pop(worker_id, None)

    def refresh_worker(self, cap: WorkerCapability) -> None:
        """Refresh a live worker's capability IN PLACE (register_worker
        would replace the pool entry and zero active_prefill/active_decode
        for live placements, unbinding the batch caps). A preflipped
        worker keeps its temporary HYBRID role across refreshes — the
        store-configured role becomes the restore target instead."""
        w = self._workers.get(cap.worker_id)
        if w is None:
            self.register_worker(cap)
            return
        if cap.worker_id in self._preflipped:
            self._preflipped[cap.worker_id] = cap.role
            cap.role = WorkerRole.HYBRID
        w.cap = cap

    # -- predictive preflip (round 20) ---------------------------------------

    def preflip_role(self, starved: str) -> Optional[str]:
        """Flip ONE worker of the role OPPOSITE ``starved`` to HYBRID so
        it can absorb starved-side work before the projected brownout
        lands. Picks the donor with the most free capacity on its own
        side (the flip costs the donating side least). Returns the
        flipped worker id, or None (no single-role donor left). The
        original role is remembered; :meth:`restore_preflips` reverts."""
        donor_role = (WorkerRole.DECODE if starved == "prefill"
                      else WorkerRole.PREFILL)
        best: Optional[_PoolWorker] = None
        best_free = -1
        for w in self._workers.values():
            if w.cap.role is not donor_role or \
                    w.cap.worker_id in self._preflipped:
                continue
            free = (w.cap.max_decode_batch - w.active_decode
                    if donor_role is WorkerRole.DECODE
                    else w.cap.max_prefill_batch - w.active_prefill)
            if free > best_free:
                best, best_free = w, free
        if best is None:
            return None
        self._preflipped[best.cap.worker_id] = best.cap.role
        best.cap.role = WorkerRole.HYBRID
        self.stats["preflipped"] += 1
        return best.cap.worker_id

    def restore_preflips(self) -> int:
        """Put every preflipped worker back on its configured role (the
        projected miss resolved). Returns the number restored. In-flight
        work on a restored worker finishes normally — roles gate NEW
        assignments only."""
        n = 0
        for wid, role in list(self._preflipped.items()):
            w = self._workers.get(wid)
            if w is not None:
                w.cap.role = role
                n += 1
            del self._preflipped[wid]
        if n:
            self.stats["preflip_restored"] += n
        return n

    def worker(self, worker_id: str) -> Optional[_PoolWorker]:
        return self._workers.get(worker_id)

    @property
    def prefill_workers(self) -> List[_PoolWorker]:
        return [w for w in self._workers.values() if w.cap.can_prefill]

    @property
    def decode_workers(self) -> List[_PoolWorker]:
        return [w for w in self._workers.values() if w.cap.can_decode]

    # -- submission / phase transitions -------------------------------------

    async def submit_job(self, req: PDRequest) -> None:
        async with self._cv:
            req.phase = "prefill"
            heapq.heappush(
                self._prefill_q, _QueueEntry((-req.priority, req.arrival), req)
            )
            self.stats["submitted"] += 1
            self._cv.notify_all()

    async def transition_to_decode(self, req: PDRequest, kv_cache_key: str,
                                   holder_worker: str) -> None:
        """Prefill finished on ``holder_worker``; queue the decode phase
        (reference ``pd_scheduler.py:207-231``)."""
        async with self._cv:
            if req.prefill_worker:
                w = self._workers.get(req.prefill_worker)
                if w:
                    w.active_prefill = max(0, w.active_prefill - 1)
            req.phase = "decode"
            req.kv_cache_key = kv_cache_key
            req.kv_holder = holder_worker
            heapq.heappush(
                self._decode_q, _QueueEntry((-req.priority, req.arrival), req)
            )
            self._cv.notify_all()

    async def complete(self, req: PDRequest) -> None:
        async with self._cv:
            if req.decode_worker:
                w = self._workers.get(req.decode_worker)
                if w:
                    w.active_decode = max(0, w.active_decode - 1)
            req.phase = "done"
            self.stats["completed"] += 1

    # -- assignment (reference :245-323) -------------------------------------

    # -- direct placement (control-plane flow, server/pd_flow.py) ------------

    def place_prefill(self, req: PDRequest) -> Optional[str]:
        """Assign a prefill worker immediately (no queue wait) — the jobs-API
        path (``server/pd_flow.py``) places at submission; the queued
        ``submit_job``/``get_batch`` machinery serves pool-level batching."""
        return self._assign_prefill(req)

    def place_decode(self, req: PDRequest) -> Optional[str]:
        """Assign a decode worker immediately (KV-affinity first)."""
        return self._assign_decode(req)

    def release(self, req: PDRequest) -> None:
        """Return a placed request's worker slots (job finished or failed)."""
        for wid, attr in ((req.prefill_worker, "active_prefill"),
                          (req.decode_worker, "active_decode")):
            w = self._workers.get(wid or "")
            if w is not None and getattr(w, attr) > 0:
                setattr(w, attr, getattr(w, attr) - 1)

    def _assign_prefill(self, req: PDRequest) -> Optional[str]:
        # admission by queue depth: active_prefill counts this worker's
        # in-flight prefill placements (queued + running stage children);
        # a worker at max_prefill_batch takes nothing more, and with EVERY
        # prefill worker saturated the flow answers 503 + Retry-After —
        # backpressure, not silent queue growth
        def _pick(pool: List[_PoolWorker],
                  ignore_exclusions: bool) -> Optional[_PoolWorker]:
            best, best_score = None, -1.0
            for w in pool:
                if w.active_prefill >= w.cap.max_prefill_batch:
                    continue
                if not ignore_exclusions and \
                        w.cap.worker_id in req.excluded_workers:
                    continue
                score = w.cap.compute_tflops / (1.0 + w.active_prefill)
                if score > best_score:
                    best, best_score = w, score
            return best

        # exclusion fallback: workers that already failed THIS request are
        # skipped, and a HEALTHY rebalance candidate (other role) beats
        # retrying an excluded one — the excluded worker just failed us,
        # possibly persistently (partitioned pushes). Only when nothing
        # un-excluded exists anywhere does the retry-over-everyone pass
        # run, so a transient failure can never strand the request.
        rebalance = [w for w in self._workers.values()
                     if not w.cap.can_prefill] \
            if self.allow_role_rebalance else []
        best = _pick(self.prefill_workers, False)
        rebalanced = False
        if best is None and rebalance:
            best = _pick(rebalance, False)
            rebalanced = best is not None
        if best is None and req.excluded_workers:
            best = _pick(self.prefill_workers, True)
            rebalanced = False
        if best is None and rebalance and req.excluded_workers:
            best = _pick(rebalance, True)
            rebalanced = best is not None
        if best is None:
            return None
        if rebalanced:
            self.stats["role_rebalanced_prefill"] += 1
        best.active_prefill += 1
        best.total_prefills += 1
        req.prefill_worker = best.cap.worker_id
        self.stats["prefills_assigned"] += 1
        return best.cap.worker_id

    def _assign_decode(self, req: PDRequest) -> Optional[str]:
        # KV affinity first: the holder keeps the request if it can decode
        holder = self._workers.get(req.kv_holder or "")
        if holder is not None and holder.cap.can_decode and \
                holder.cap.worker_id not in req.excluded_workers and \
                holder.active_decode < holder.cap.max_decode_batch:
            holder.active_decode += 1
            holder.total_decodes += 1
            req.decode_worker = holder.cap.worker_id
            req.needs_migration = False
            self.stats["affinity_hits"] += 1
            self.stats["decodes_assigned"] += 1
            return holder.cap.worker_id

        # else: best aggregate bandwidth with headroom → migrate KV there.
        # Workers that already failed a migration for THIS request are skipped
        # (no livelock against a dead link); if exclusion empties the candidate
        # set, retry over everyone — a transient failure must not strand the
        # request when only one decode worker exists. A browned-out decode
        # side falls back to prefill-role workers (rebalance, counted).
        def _pick(pool: List[_PoolWorker],
                  ignore_exclusions: bool) -> Optional[_PoolWorker]:
            best, best_score = None, -1.0
            for w in pool:
                if w.active_decode >= w.cap.max_decode_batch:
                    continue
                if not ignore_exclusions and \
                        w.cap.worker_id in req.excluded_workers:
                    continue
                score = w.cap.memory_bandwidth_gbps / (1.0 + w.active_decode)
                if score > best_score:
                    best, best_score = w, score
            return best

        rebalance = [w for w in self._workers.values()
                     if not w.cap.can_decode] \
            if self.allow_role_rebalance else []
        best = _pick(self.decode_workers, False)
        rebalanced = False
        if best is None and rebalance:
            # healthy other-role capacity beats retrying an excluded
            # (just-failed) decode worker — same order as prefill
            best = _pick(rebalance, False)
            rebalanced = best is not None
        if best is None and req.excluded_workers:
            best = _pick(self.decode_workers, True)
            rebalanced = False
        if best is None and rebalance and req.excluded_workers:
            best = _pick(rebalance, True)
            rebalanced = best is not None
        if best is None:
            return None
        if rebalanced:
            self.stats["role_rebalanced_decode"] += 1
        best.active_decode += 1
        best.total_decodes += 1
        req.decode_worker = best.cap.worker_id
        req.needs_migration = req.kv_holder is not None and \
            req.kv_holder != best.cap.worker_id
        if req.needs_migration:
            self.stats["migrations_requested"] += 1
        self.stats["decodes_assigned"] += 1
        return best.cap.worker_id

    # -- batched pop (reference :350-380) ------------------------------------

    async def get_batch(self, phase: str, max_batch: int = 8,
                        timeout_s: Optional[float] = None) -> List[PDRequest]:
        """Pop up to ``max_batch`` assignable requests for ``phase``. Waits up
        to the per-phase timeout for the FIRST request, then drains what is
        immediately assignable (prefill batches amortize big matmuls; decode
        pops stay snappy to keep TPOT low)."""
        q = self._prefill_q if phase == "prefill" else self._decode_q
        assign = self._assign_prefill if phase == "prefill" else self._assign_decode
        if timeout_s is None:
            timeout_s = (
                PREFILL_BATCH_TIMEOUT_S if phase == "prefill"
                else DECODE_BATCH_TIMEOUT_S
            )
        out: List[PDRequest] = []
        deadline = time.monotonic() + timeout_s

        def _has_work() -> bool:
            if phase == "decode" and self._ready_migrated:
                return True
            return bool(q)

        async with self._cv:
            while not _has_work():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return out
                try:
                    await asyncio.wait_for(self._cv.wait(), timeout=remaining)
                except asyncio.TimeoutError:
                    return out
            # migrated-and-ready requests go out first (their KV is local now)
            if phase == "decode":
                while self._ready_migrated and len(out) < max_batch:
                    out.append(self._ready_migrated.popleft())
            skipped: List[_QueueEntry] = []
            while q and len(out) < max_batch:
                entry = heapq.heappop(q)
                if assign(entry.req) is None:
                    skipped.append(entry)  # no capacity now; retain order
                    break
                req = entry.req
                if phase == "decode" and self.migrator is not None and \
                        req.needs_migration and req.kv_cache_key and \
                        req.kv_holder and req.decode_worker:
                    # KV must move first: run the transfer in the background so
                    # affinity-hit requests in this batch aren't stalled behind
                    # it; the request is delivered by a later get_batch once
                    # its migration lands in _ready_migrated
                    task = asyncio.ensure_future(self._migrate_bg(req))
                    self._bg_tasks.add(task)
                    task.add_done_callback(self._bg_tasks.discard)
                else:
                    out.append(req)
            for entry in skipped:
                heapq.heappush(q, entry)
        return out

    async def _migrate_bg(self, req: PDRequest) -> None:
        """Background KV migration with per-request failure isolation:
        a dead link excludes that destination and requeues the request (up to
        ``max_migration_attempts``), releasing the reserved decode capacity."""
        assert self.migrator is not None
        try:
            await self.migrator.migrate(
                req.kv_cache_key, req.kv_holder, req.decode_worker  # type: ignore[arg-type]
            )
        except Exception:
            async with self._cv:
                w = self._workers.get(req.decode_worker or "")
                if w:
                    w.active_decode = max(0, w.active_decode - 1)
                req.excluded_workers.add(req.decode_worker)
                req.migration_attempts += 1
                req.decode_worker = None
                req.needs_migration = False
                self.stats["migration_failures"] += 1
                if req.migration_attempts >= self.max_migration_attempts:
                    req.phase = "failed"
                    self.stats["migration_dropped"] += 1
                else:
                    heapq.heappush(
                        self._decode_q, _QueueEntry((-req.priority, req.arrival), req)
                    )
                self._cv.notify_all()
            return
        req.kv_holder = req.decode_worker
        async with self._cv:
            self._ready_migrated.append(req)
            self._cv.notify_all()

    # -- latency estimators (reference :325-348) -----------------------------

    def estimate_prefill_latency_ms(self, req: PDRequest,
                                    worker_id: Optional[str] = None) -> float:
        """Prefill is FLOPs-bound: ≈ 2·P·prompt_tokens / peak_flops, with P
        approximated from KV geometry (layers × heads × dim scaling)."""
        w = self._workers.get(worker_id or req.prefill_worker or "")
        tflops = w.cap.compute_tflops if w else 197.0
        # ~2 * params * tokens; params ≈ 12 * L * hidden² with hidden = heads*dim
        hidden = req.num_kv_heads * req.head_dim * 4  # GQA: q heads ≈ 4x kv
        params = 12.0 * req.num_layers * hidden * hidden
        flop = 2.0 * params * req.prompt_tokens
        return flop / (tflops * 1e12) * 1000.0

    def estimate_decode_tpot_ms(self, req: PDRequest,
                                worker_id: Optional[str] = None) -> float:
        """Decode is bandwidth-bound: each token streams weights + KV once."""
        w = self._workers.get(worker_id or req.decode_worker or "")
        bw = w.cap.memory_bandwidth_gbps if w else 819.0
        hidden = req.num_kv_heads * req.head_dim * 4
        weight_bytes = 2.0 * 12.0 * req.num_layers * hidden * hidden
        bytes_per_tok = weight_bytes + req.kv_bytes
        return bytes_per_tok / (bw * 1e9) * 1000.0

    def estimate_migration_ms(self, req: PDRequest, src: str, dst: str) -> float:
        w = self._workers.get(src)
        gBps = w.cap.interconnect_gbps if w else 25.0  # GB/s, like all BW here
        return req.kv_bytes / (gBps * 1e9) * 1000.0

    def capacity_by_role(self) -> Dict[str, int]:
        """Free serving capacity per PD role (prefill slots / decode slots
        still available across the registered pool) — the ``pd_fleet_
        balance`` gauge. A side at 0 while the other has headroom is the
        brownout the role-rebalance fallback exists for."""
        cap = {"prefill": 0, "decode": 0}
        for w in self._workers.values():
            if w.cap.can_prefill:
                cap["prefill"] += max(
                    0, w.cap.max_prefill_batch - w.active_prefill
                )
            if w.cap.can_decode:
                cap["decode"] += max(
                    0, w.cap.max_decode_batch - w.active_decode
                )
        return cap

    def get_stats(self) -> Dict[str, Any]:
        out = dict(self.stats)
        out["prefill_queue"] = len(self._prefill_q)
        out["decode_queue"] = len(self._decode_q)
        out["workers"] = {
            wid: {
                "role": w.cap.role.value,
                "active_prefill": w.active_prefill,
                "active_decode": w.active_decode,
                "total_prefills": w.total_prefills,
                "total_decodes": w.total_decodes,
            }
            for wid, w in self._workers.items()
        }
        if self.migrator is not None:
            out["migrator"] = self.migrator.get_stats()
        return out


# ---------------------------------------------------------------------------
# KV migration
# ---------------------------------------------------------------------------

# transport(kv_cache_key, src_worker, dst_worker) -> bytes moved
Transport = Callable[[str, str, str], Awaitable[int]]


class KVCacheMigrator:
    """Moves KV pages between pool partitions, deduping concurrent migrations
    of the same key (reference ``KVCacheMigrator``, pd_scheduler.py:404-479 —
    whose transfer body was a simulated sleep; ours calls a real transport)."""

    def __init__(self, transport: Transport) -> None:
        self._transport = transport
        self._in_flight: Dict[str, asyncio.Task] = {}
        self.stats: Dict[str, Any] = {
            "migrations": 0, "deduped": 0, "bytes_moved": 0, "failures": 0,
            # bounded: a long-lived scheduler must not grow stats without limit
            "latencies_ms": deque(maxlen=1024),
        }

    async def migrate(self, kv_cache_key: str, src: str, dst: str) -> int:
        """Returns bytes moved. Concurrent calls for the same key await ONE
        underlying transfer."""
        key = f"{kv_cache_key}->{dst}"
        task = self._in_flight.get(key)
        if task is not None:
            self.stats["deduped"] += 1
            return await asyncio.shield(task)
        task = asyncio.ensure_future(self._run(kv_cache_key, src, dst))
        self._in_flight[key] = task
        try:
            return await task
        finally:
            self._in_flight.pop(key, None)

    async def _run(self, kv_cache_key: str, src: str, dst: str) -> int:
        t0 = time.monotonic()
        try:
            moved = await self._transport(kv_cache_key, src, dst)
        except Exception:
            self.stats["failures"] += 1
            raise
        self.stats["migrations"] += 1
        self.stats["bytes_moved"] += moved
        self.stats["latencies_ms"].append((time.monotonic() - t0) * 1000.0)
        return moved

    def get_stats(self) -> Dict[str, Any]:
        lat = list(self.stats["latencies_ms"])
        out = {k: v for k, v in self.stats.items() if k != "latencies_ms"}
        if lat:
            s = sorted(lat)
            out["p50_ms"] = s[len(s) // 2]
            out["p95_ms"] = s[min(len(s) - 1, int(len(s) * 0.95))]
        return out


class InProcessKVTransport:
    """Real in-process transport for tests/benchmarks and single-host
    deployments: export from the source engine, frame the bytes through the
    DCN wire format, adopt into the destination engine.

    Register each partition's engine plus the slot resolver; production
    deployments swap this for an HTTP/ICI transport with the same signature.

    Engine access is serialized through ``executor``: pass the SAME
    single-thread executor the engines' batcher uses
    (``ContinuousBatcher._exec``) so export/adopt never race a decode_step;
    by default the transport owns a dedicated max_workers=1 executor, which
    is safe when nothing else drives the engines concurrently.
    """

    def __init__(self, compress: bool = True,
                 executor: Optional[Any] = None) -> None:
        from concurrent.futures import ThreadPoolExecutor

        self._engines: Dict[str, Any] = {}
        # kv_cache_key -> (worker_id, slot)
        self._locations: Dict[str, Tuple[str, int]] = {}
        self._adopted: Dict[str, int] = {}
        self.compress = compress
        self._exec = executor or ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kv-migrate"
        )

    def register_engine(self, worker_id: str, engine: Any) -> None:
        self._engines[worker_id] = engine

    def record_location(self, kv_cache_key: str, worker_id: str, slot: int) -> None:
        self._locations[kv_cache_key] = (worker_id, slot)

    def adopted_slot(self, kv_cache_key: str) -> Optional[int]:
        return self._adopted.get(kv_cache_key)

    async def __call__(self, kv_cache_key: str, src: str, dst: str) -> int:
        from distributed_gpu_inference_tpu.runtime.kv_handoff import (
            adopt_kv,
            deserialize_handoff,
            export_slot_kv,
            serialize_handoff,
        )

        loc = self._locations.get(kv_cache_key)
        if loc is None:
            raise KeyError(f"unknown kv_cache_key {kv_cache_key}")
        src_worker, slot = loc
        if src_worker != src:
            src = src_worker
        src_engine = self._engines[src]
        dst_engine = self._engines[dst]
        loop = asyncio.get_running_loop()

        def _move() -> Tuple[int, int]:
            handoff = export_slot_kv(src_engine, slot)
            wire = serialize_handoff(handoff, compress=self.compress)
            new_slot = adopt_kv(dst_engine, deserialize_handoff(wire))
            src_engine.finish_slot(slot, cache=False)
            return len(wire), new_slot

        nbytes, new_slot = await loop.run_in_executor(self._exec, _move)
        self._locations[kv_cache_key] = (dst, new_slot)
        self._adopted[kv_cache_key] = new_slot
        return nbytes
