"""Control-plane half of cache-aware routing: per-worker prefix-summary
registry + the affinity scoring the scheduler and the direct-mode
discovery endpoint share.

Workers advertise bounded radix summaries over the heartbeat
``engine_stats.prefix_summary`` channel (``runtime/prefix_summary.py``
wire format); this registry validates (version, size, block basis),
applies deltas, persists per worker (store table
``worker_prefix_summaries``, so a control-plane restart warm-starts
instead of routing blind until every worker resyncs), and answers
synchronous in-memory match queries from the scoring paths.

Invariants the rest of the plane relies on:

- **Advisory only.** A summary never gates placement — it adds a bounded
  score bonus. Claim atomicity, epoch fencing, failover, and backpressure
  are untouched: a routed worker dying fails over exactly as before.
- **Staleness-tolerant.** Summaries older than ``staleness_ttl_s`` score
  zero (the worker may have restarted with a cold cache); a worker that
  never advertises is simply locality-unknown.
- **Bounded ingest.** Oversized summaries are truncated (counted), bad
  versions and mismatched block bases rejected (counted) — a misbehaving
  worker cannot bloat the heartbeat path or the registry.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..runtime.prefix_summary import SUMMARY_WIRE_VERSION
from ..utils.prefixes import PREFIX_BLOCK_CHARS, deepest_match

# score multiplier per advertised tier: device-resident KV beats a host
# spill (restore is an upload) beats a remote spill (restore is a fetch)
TIER_WEIGHT = {"dev": 1.0, "host": 0.7, "spill": 0.5}

# per-tier transfer-cost multiplier for the MIGRATE decision: a dev-tier
# pull is one pool gather on the source; a host-tier pull adds the host
# read; a remote ("spill") tier pull pays the remote fetch + L2 promote
# before a single byte crosses to the puller
MIGRATE_TIER_COST = {"dev": 1.0, "host": 1.25, "spill": 1.75}


@dataclass
class RoutingConfig:
    """Live-pushable routing knobs (admin ``PUT /api/v1/admin/routing``)."""

    enabled: bool = True
    # affinity is a bounded BONUS on top of the base score (reliability/
    # region/online/perf/load sum to 1.0) — never a hard pin
    affinity_weight: float = 0.2
    # a fully-loaded worker keeps only this fraction of its affinity bonus,
    # so a hot replica spills over to the fleet instead of starving it —
    # strictly below WEIGHTS["load"]/affinity_weight (0.05/0.2), so a
    # saturated cached worker LOSES to an idle cold one, never ties it
    min_headroom_factor: float = 0.2
    # server-side entry cap per worker (workers self-cap lower; this is the
    # defense against a misbehaving one)
    summary_max_entries: int = 256
    # summaries older than this score zero (worker restarted / went quiet)
    staleness_ttl_s: float = 120.0
    block_chars: int = PREFIX_BLOCK_CHARS
    # request fingerprints accepted per job / discovery call
    max_fps_per_request: int = 32
    # -- cluster-wide KV migration (round 13) -------------------------------
    # master switch for the per-request route-to-warm / migrate-KV /
    # recompute cost model. OFF by default: routing behaves byte-identically
    # to the round-7 advisory scoring (the A/B flip for BENCH_r12)
    kv_migrate: bool = False
    # matches shallower than this never migrate (the transfer setup isn't
    # worth a block or two of saved prefill)
    migrate_min_blocks: int = 2
    # cost-model estimates. Fingerprints are text-space, so token counts
    # are estimated as blocks × block_chars (exact for the byte tokenizer,
    # advisory for every other — same stance as affinity itself):
    #   transfer_s  = matched_tokens × bytes_per_token × tier_cost / bw
    #   prefill_s   = tokens / prefill_tokens_per_s
    #   queue_s     = (1 − graded headroom) × queue_wait_s
    # defaults sized for intra-cluster links (≥1 GB/s effective): per
    # token, transfer (~0.07 ms at 64 KiB/token) undercuts re-prefill
    # (~0.25 ms at 4k tok/s), so deep matches migrate; a WAN deployment
    # should push its measured bandwidth here or migration over-fires
    migrate_bytes_per_token: float = 65536.0
    migrate_bandwidth_bytes_per_s: float = 1e9
    migrate_prefill_tokens_per_s: float = 4000.0
    migrate_queue_wait_s: float = 2.0
    # -- cost-model self-calibration (round 20) -----------------------------
    # master switch: when ON, decide-time calls substitute per-worker
    # MEASURED prefill tok/s, queue-wait and per-(worker, tier) handoff
    # bandwidth (server/calibration.py, fed from flight traces and the
    # worker kv_migrate wire counters) for the four static priors above.
    # OFF by default: routing is byte-identical to the static cost model —
    # ingestion still runs (the /admin/routing snapshot shows what WOULD
    # be used), but no decision reads a learned value
    calibrate: bool = False
    # EMA smoothing for each estimator (higher = reacts faster)
    calibrate_alpha: float = 0.3
    # once warm, a sample further than this factor from the running value
    # is clamped before blending (one 60 s GC pause must not poison the
    # queue-wait estimate)
    calibrate_clamp: float = 5.0
    # estimators answer None (→ caller keeps the prior) below this many
    # samples — never steer placement off one lucky measurement
    calibrate_min_samples: int = 3
    # sliding window for the in-flight migrate-hint tracker: hints older
    # than this are presumed resolved (pull done or abandoned) and stop
    # inflating the cold-side queue estimate. Always on with kv_migrate —
    # it is a correctness-of-estimate fix, not a predictor
    migrate_hint_window_s: float = 10.0
    # -- proactive prefix replication (round 20) ----------------------------
    # master switch: the plane watches prefix hit-velocity at discovery
    # time and rides kv_replicate hints down the heartbeat response to
    # cold workers, which pull via the existing /kv/export protocol under
    # the same budget/backoff as reactive migration. OFF by default
    replicate: bool = False
    # a deepest-boundary fingerprint is "hot" at this many discovery hits
    # inside replicate_window_s
    replicate_hot_threshold: int = 3
    replicate_window_s: float = 10.0
    # hints per heartbeat response (each is one bounded pull on the worker)
    replicate_max_hints: int = 2
    # per-(worker, prefix) re-hint cooldown: a worker that dropped or
    # failed a hint is not re-asked until this elapses
    replicate_cooldown_s: float = 30.0

    def update(self, d: Dict[str, Any]) -> None:
        # validate EVERYTHING before applying ANYTHING: a 400 answer must
        # leave the live config untouched (a half-applied push would flip
        # the A/B switch while reporting failure)
        staged: Dict[str, Any] = {}
        for flag in ("enabled", "kv_migrate", "calibrate", "replicate"):
            if d.get(flag) is not None:
                v = d[flag]
                if isinstance(v, str):
                    # bool("false") is True — the ONE coercion that would
                    # silently invert an A/B switch for shell/curl callers
                    low = v.strip().lower()
                    if low in ("true", "1", "on"):
                        v = True
                    elif low in ("false", "0", "off"):
                        v = False
                    else:
                        raise ValueError(f"{flag}: not a boolean: {v!r}")
                elif not isinstance(v, bool):
                    raise ValueError(f"{flag}: not a boolean: {v!r}")
                staged[flag] = v
        for k, lo, hi in (("affinity_weight", 0.0, 10.0),
                          ("min_headroom_factor", 0.0, 1.0),
                          ("staleness_ttl_s", 1.0, float("inf")),
                          ("migrate_bytes_per_token", 1.0, float("inf")),
                          ("migrate_bandwidth_bytes_per_s", 1.0,
                           float("inf")),
                          ("migrate_prefill_tokens_per_s", 1.0,
                           float("inf")),
                          ("migrate_queue_wait_s", 0.0, float("inf")),
                          ("calibrate_alpha", 0.0, 1.0),
                          ("calibrate_clamp", 1.0, float("inf")),
                          ("migrate_hint_window_s", 0.1, float("inf")),
                          ("replicate_window_s", 0.1, float("inf")),
                          ("replicate_cooldown_s", 0.0, float("inf"))):
            if d.get(k) is not None:
                v = float(d[k])
                if not lo <= v <= hi:
                    raise ValueError(f"{k}: {v} outside [{lo}, {hi}]")
                staged[k] = v
        for k in ("summary_max_entries", "max_fps_per_request",
                  "migrate_min_blocks", "calibrate_min_samples",
                  "replicate_hot_threshold", "replicate_max_hints"):
            if d.get(k) is not None:
                v = int(d[k])
                if v < 1:
                    raise ValueError(f"{k}: must be >= 1, got {v}")
                staged[k] = v
        # the documented no-starvation invariant: a SATURATED cached
        # worker's floored bonus must stay below an idle cold worker's
        # entire load term, or affinity becomes a de-facto pin
        aw = staged.get("affinity_weight", self.affinity_weight)
        floor = staged.get("min_headroom_factor", self.min_headroom_factor)
        from .scheduler import WEIGHTS
        if aw * floor >= WEIGHTS["load"]:
            raise ValueError(
                f"affinity_weight * min_headroom_factor ({aw} * {floor}) "
                f"must stay below the load weight {WEIGHTS['load']} — "
                "otherwise a saturated cached worker outranks an idle "
                "cold one and affinity starves the fleet"
            )
        for k, v in staged.items():
            setattr(self, k, v)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "affinity_weight": self.affinity_weight,
            "min_headroom_factor": self.min_headroom_factor,
            "summary_max_entries": self.summary_max_entries,
            "staleness_ttl_s": self.staleness_ttl_s,
            "block_chars": self.block_chars,
            "max_fps_per_request": self.max_fps_per_request,
            "kv_migrate": self.kv_migrate,
            "migrate_min_blocks": self.migrate_min_blocks,
            "migrate_bytes_per_token": self.migrate_bytes_per_token,
            "migrate_bandwidth_bytes_per_s":
                self.migrate_bandwidth_bytes_per_s,
            "migrate_prefill_tokens_per_s":
                self.migrate_prefill_tokens_per_s,
            "migrate_queue_wait_s": self.migrate_queue_wait_s,
            "calibrate": self.calibrate,
            "calibrate_alpha": self.calibrate_alpha,
            "calibrate_clamp": self.calibrate_clamp,
            "calibrate_min_samples": self.calibrate_min_samples,
            "migrate_hint_window_s": self.migrate_hint_window_s,
            "replicate": self.replicate,
            "replicate_hot_threshold": self.replicate_hot_threshold,
            "replicate_window_s": self.replicate_window_s,
            "replicate_max_hints": self.replicate_max_hints,
            "replicate_cooldown_s": self.replicate_cooldown_s,
        }


@dataclass
class IngestResult:
    applied: bool = False
    resync: bool = False          # tell the worker to send a full snapshot
    reason: Optional[str] = None  # counted rejection/truncation reason
    truncated: int = 0


@dataclass
class _WorkerSummary:
    seq: int = 0
    block_chars: int = PREFIX_BLOCK_CHARS
    # fp -> (depth, tier)
    entries: Dict[str, Tuple[int, str]] = field(default_factory=dict)
    updated_at: float = 0.0


class PrefixRegistry:
    """In-memory per-worker summaries with write-through persistence."""

    def __init__(self, config: Optional[RoutingConfig] = None) -> None:
        self.config = config or RoutingConfig()
        self._workers: Dict[str, _WorkerSummary] = {}
        self._loaded = False

    # -- persistence ---------------------------------------------------------

    async def ensure_loaded(self, store: Any) -> None:
        """Warm-start from the store once per process — after a restart the
        plane routes on persisted summaries until fresh heartbeats arrive
        (the staleness TTL guards against routing on ancient state)."""
        if self._loaded:
            return
        self._loaded = True
        try:
            # reclaim rows from long-dead worker ids while we're here —
            # worker churn must not grow this table forever (anything
            # past 10x the TTL could never score again anyway)
            await store.execute(
                "DELETE FROM worker_prefix_summaries WHERE updated_at < ?",
                (time.time() - 10.0 * self.config.staleness_ttl_s,),
            )
            rows = await store.query(
                "SELECT worker_id, seq, block_chars, entries, updated_at "
                "FROM worker_prefix_summaries"
            )
        except Exception:  # noqa: BLE001 — a missing table must not 500
            return
        import json

        for r in rows:
            if r.get("worker_id") in self._workers:
                # a fresh summary was ingested while we awaited the DB
                # (concurrent heartbeat during warm start) — never clobber
                # live state with the persisted pre-restart row
                continue
            try:
                raw = r.get("entries")
                ent = json.loads(raw) if isinstance(raw, str) else (raw or [])
                self._workers[r["worker_id"]] = _WorkerSummary(
                    seq=int(r.get("seq") or 0),
                    block_chars=int(r.get("block_chars")
                                    or self.config.block_chars),
                    entries={
                        str(fp): (int(d), str(t)) for fp, d, t in ent
                    },
                    updated_at=float(r.get("updated_at") or 0.0),
                )
            except (ValueError, TypeError, KeyError):
                continue   # one corrupt row must not poison the warm start

    async def persist(self, worker_id: str, store: Any) -> None:
        ws = self._workers.get(worker_id)
        if ws is None:
            return
        import json

        await store.save_prefix_summary(
            worker_id, ws.seq, ws.block_chars,
            json.dumps([[fp, d, t] for fp, (d, t) in ws.entries.items()]),
            ws.updated_at,
        )

    def drop_worker(self, worker_id: str) -> None:
        self._workers.pop(worker_id, None)

    def invalidate_worker(self, worker_id: str, reason: str = "offline",
                          metrics: Optional[Any] = None) -> bool:
        """Zero a worker's advertised summary the MOMENT the plane decides
        it is gone (marked offline, heartbeat swept stale, partitioned) —
        not after ``staleness_ttl_s``. Affinity scoring must never prefer a
        dead warm worker over a live cold one: between the sweep and the
        TTL the dead worker's KV is as good as gone (it will restart cold,
        or never), while the bonus would keep steering spillover math and
        the claim path at its corpse.

        The whole record is dropped (not just emptied): a revived worker's
        next delta then base-mismatches → resync → full snapshot, so both
        sides converge in one round-trip instead of the worker diffing
        against entries the plane no longer holds. Returns True when a
        summary actually existed (callers use it to gate persistence
        cleanup and the counted metric)."""
        ws = self._workers.pop(worker_id, None)
        if ws is None:
            return False
        if metrics is not None:
            try:
                metrics.record_prefix_summary_invalidated(reason)
            except Exception:  # noqa: BLE001 — metrics are best-effort
                pass
        return True

    def touch(self, worker_id: str, now: Optional[float] = None) -> None:
        """A heartbeat arrived from this worker: its summary is still
        live even when no payload rode along (``wire()`` returns None
        while in sync). Without this, a warm worker that simply receives
        no NEW prefixes for ``staleness_ttl_s`` would lose all affinity
        while holding the KV — staleness must mean "stopped heartbeating
        or restarted", not "stopped changing"."""
        ws = self._workers.get(worker_id)
        if ws is not None:
            ws.updated_at = time.time() if now is None else now

    # -- ingest ---------------------------------------------------------------

    @staticmethod
    def _clean_entries(raw: Any, limit: int) -> Tuple[Dict[str, Tuple[int, str]], int, bool]:
        """→ (entries, truncated_count, malformed). Screens every field:
        worker-supplied payloads must degrade, never throw."""
        if not isinstance(raw, list):
            return {}, 0, True
        out: Dict[str, Tuple[int, str]] = {}
        truncated = max(0, len(raw) - limit)
        for item in raw[:limit]:
            if (not isinstance(item, (list, tuple)) or len(item) != 3
                    or not isinstance(item[0], str) or len(item[0]) > 32):
                return {}, 0, True
            try:
                depth = int(item[1])
            except (TypeError, ValueError):
                return {}, 0, True
            tier = item[2] if item[2] in TIER_WEIGHT else "dev"
            out[item[0]] = (max(1, depth), tier)
        return out, truncated, False

    def _gc(self, now: float) -> None:
        """Bound registry growth under worker-id churn: entries long past
        the staleness TTL score zero anyway — reclaim them once the
        registry is big enough for the dead weight to matter (workers
        that merely went quiet re-advertise with a full snapshot)."""
        if len(self._workers) <= 512:
            return
        cutoff = now - 10.0 * self.config.staleness_ttl_s
        for wid in [w for w, ws in self._workers.items()
                    if ws.updated_at < cutoff]:
            del self._workers[wid]

    def ingest(self, worker_id: str, payload: Any,
               now: Optional[float] = None) -> IngestResult:
        now = time.time() if now is None else now
        self._gc(now)
        cfg = self.config
        if not isinstance(payload, dict):
            return IngestResult(reason="summary_malformed", resync=True)
        if int(payload.get("v") or 0) != SUMMARY_WIRE_VERSION:
            # versioned channel: an unknown wire version is rejected with a
            # counted reason, never guessed at (no resync — the worker
            # would just resend the same unparseable thing)
            return IngestResult(reason="summary_bad_version")
        if int(payload.get("block_chars") or 0) != cfg.block_chars:
            # mismatched fingerprint basis would MIS-match, not just miss
            return IngestResult(reason="summary_block_mismatch")
        seq = int(payload.get("seq") or 0)
        limit = max(1, cfg.summary_max_entries)
        if "full" in payload:
            entries, truncated, bad = self._clean_entries(
                payload.get("full"), limit
            )
            if bad:
                return IngestResult(reason="summary_malformed", resync=True)
            self._workers[worker_id] = _WorkerSummary(
                seq=seq, block_chars=cfg.block_chars,
                entries=entries, updated_at=now,
            )
            return IngestResult(
                applied=True, truncated=truncated,
                reason="summary_truncated" if truncated else None,
            )
        # delta: only applicable on top of the exact base the worker diffed
        # against — anything else (restart on either side, lost heartbeat)
        # asks for a resync instead of silently diverging
        ws = self._workers.get(worker_id)
        base = int(payload.get("base_seq") or 0)
        if ws is None or ws.seq != base:
            return IngestResult(reason="summary_resync", resync=True)
        add, truncated, bad = self._clean_entries(
            payload.get("add") or [], limit
        )
        if bad:
            return IngestResult(reason="summary_malformed", resync=True)
        dels = payload.get("del") or []
        if not isinstance(dels, list):
            return IngestResult(reason="summary_malformed", resync=True)
        for fp in dels:
            if isinstance(fp, str):
                ws.entries.pop(fp, None)
        ws.entries.update(add)
        over = len(ws.entries) - limit
        if over > 0:
            # arbitrary-but-bounded trim; the worker's own LRU keeps it hot
            for fp in list(ws.entries.keys())[:over]:
                del ws.entries[fp]
            truncated += over
        ws.seq = seq
        ws.updated_at = now
        return IngestResult(
            applied=True, truncated=truncated,
            reason="summary_truncated" if truncated else None,
        )

    # -- match / scoring ------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    def _match(self, worker_id: str, fps: Sequence[str],
               now: Optional[float] = None) -> Tuple[int, str]:
        """→ (matched_blocks, tier) of the deepest request boundary this
        worker advertises; (0, "dev") when stale/unknown/no match. The ONE
        staleness-guarded lookup both scoring and peer selection share."""
        if not fps:
            return 0, "dev"
        ws = self._workers.get(worker_id)
        if ws is None:
            return 0, "dev"
        now = time.time() if now is None else now
        if now - ws.updated_at > self.config.staleness_ttl_s:
            return 0, "dev"
        n = deepest_match(fps, ws.entries)
        if n <= 0:
            return 0, "dev"
        _, tier = ws.entries[fps[n - 1]]
        return n, tier

    def match_blocks(self, worker_id: str, fps: Sequence[str],
                     now: Optional[float] = None) -> Tuple[int, float]:
        """→ (matched_blocks, tier_weight) of the deepest request boundary
        this worker advertises; (0, 0) when stale/unknown/no match."""
        n, tier = self._match(worker_id, fps, now=now)
        if n <= 0:
            return 0, 0.0
        return n, TIER_WEIGHT.get(tier, 1.0)

    def affinity(self, worker_id: str, fps: Sequence[str],
                 now: Optional[float] = None) -> float:
        """Fraction of the request's routable prefix this worker holds,
        tier-weighted, in [0, 1]."""
        if not fps:
            return 0.0
        n, tw = self.match_blocks(worker_id, fps, now=now)
        return (n / len(fps)) * tw

    def best_affinity(self, fps: Sequence[str],
                      now: Optional[float] = None
                      ) -> Tuple[Optional[str], float]:
        """Best (worker_id, affinity) across every advertised summary —
        the spillover detector's reference point."""
        best_w, best_a = None, 0.0
        for wid in self._workers:
            a = self.affinity(wid, fps, now=now)
            if a > best_a:
                best_w, best_a = wid, a
        return best_w, best_a

    def best_match(self, worker_ids: Sequence[str], fps: Sequence[str],
                   now: Optional[float] = None
                   ) -> Tuple[Optional[str], int, str]:
        """Peer selection for KV migration: the eligible worker advertising
        the DEEPEST match of ``fps`` → (worker_id, matched_blocks, tier).
        Depth wins; a warmer tier (dev > host > remote) breaks depth ties —
        the cost model prices the pull by both. (None, 0, "dev") when
        nobody matches."""
        best_w: Optional[str] = None
        best_n, best_tier = 0, "dev"
        for wid in worker_ids:
            n, tier = self._match(wid, fps, now=now)
            if n <= 0:
                continue
            if n > best_n or (n == best_n and
                              TIER_WEIGHT.get(tier, 0.0)
                              > TIER_WEIGHT.get(best_tier, 0.0)):
                best_w, best_n, best_tier = wid, n, tier
        return best_w, best_n, best_tier

    def best_affinity_among(self, worker_ids: Sequence[str],
                            fps: Sequence[str],
                            now: Optional[float] = None) -> float:
        """Best affinity across ONLY the given workers — the spillover
        metric's reference point must range over the workers actually
        eligible for this placement (excluding dead/excluded ones keeps
        the counter meaning 'a warmer ELIGIBLE worker was passed over')."""
        return max(
            (self.affinity(wid, fps, now=now) for wid in worker_ids),
            default=0.0,
        )

    def stats_for_metrics(self, now: Optional[float] = None
                          ) -> List[Tuple[str, int, float]]:
        """→ [(worker_id, entry_count, age_s)] for the /metrics gauges."""
        now = time.time() if now is None else now
        return [
            (wid, len(ws.entries), max(0.0, now - ws.updated_at))
            for wid, ws in self._workers.items()
        ]


# ---------------------------------------------------------------------------
# Cluster-wide KV migration: the per-request route cost model (round 13)
# ---------------------------------------------------------------------------


def decide_kv_route(cfg: RoutingConfig, *, request_blocks: int,
                    matched_blocks: int, tier: str,
                    warm_headroom: float, cold_headroom: float,
                    warm_is_cold: bool = False,
                    warm_prefill_tps: Optional[float] = None,
                    cold_prefill_tps: Optional[float] = None,
                    warm_queue_wait_s: Optional[float] = None,
                    cold_queue_wait_s: Optional[float] = None,
                    migrate_bandwidth: Optional[float] = None,
                    cold_inflight_pulls: int = 0) -> Dict[str, Any]:
    """Choose route-to-warm / migrate-KV / recompute for ONE request.

    Inputs are the router's estimates: ``request_blocks`` = the request's
    routable prefix depth (its fingerprint count), ``matched_blocks`` +
    ``tier`` = the warmest eligible worker's advertised match
    (:meth:`PrefixRegistry.best_match`), and the two graded load headrooms
    ([0, 1] — 1 = idle) of that warm worker and of the load/region-best
    "cold" candidate. Costs (seconds, estimated):

    - warm:      wait(warm) + prefill(unmatched)          — PR 7's choice
    - migrate:   wait(cold) + transfer(matched, tier) + prefill(unmatched)
    - recompute: wait(cold) + prefill(all)

    The five ``*_tps`` / ``*_wait`` / ``migrate_bandwidth`` keywords are
    the calibration overrides: a MEASURED per-worker rate replaces the
    corresponding ``cfg`` prior when given (None — the default, and what
    every call passes while calibration is off or cold — keeps the cost
    arithmetic byte-identical to the static model).
    ``cold_inflight_pulls`` folds the pulls the plane has already steered
    at the cold candidate into its queue estimate: each outstanding pull
    serializes on the worker's ``kv_migrate_budget``, so a target mid-way
    through its budget no longer prices as idle (the burst-race fix —
    without it every request in a storm migrates to the same exporter).

    The decision is advisory, exactly like affinity: a wrong estimate
    costs latency, never correctness (the worker-side pull falls back to
    recompute on any failure). Returns ``{"choice", "costs"}``;
    ``warm_is_cold`` (the score-best candidate IS the warm worker) and
    too-shallow matches short-circuit to warm/recompute."""
    bc = max(1, cfg.block_chars)
    total_tokens = max(request_blocks, matched_blocks, 1) * bc
    matched_tokens = max(0, matched_blocks) * bc

    def _wait(headroom: float, measured: Optional[float]) -> float:
        base = cfg.migrate_queue_wait_s if measured is None else measured
        return (1.0 - max(0.0, min(1.0, headroom))) * base

    def _prefill(tokens: float, measured: Optional[float]) -> float:
        tps = (cfg.migrate_prefill_tokens_per_s if measured is None
               else max(1.0, measured))
        return max(0.0, tokens) / tps

    bw = (cfg.migrate_bandwidth_bytes_per_s if migrate_bandwidth is None
          else max(1.0, migrate_bandwidth))
    transfer_s = (matched_tokens * cfg.migrate_bytes_per_token
                  * MIGRATE_TIER_COST.get(tier, 1.0) / bw)
    costs = {
        "warm": (_wait(warm_headroom, warm_queue_wait_s)
                 + _prefill(total_tokens - matched_tokens,
                            warm_prefill_tps)),
        "migrate": (
            _wait(cold_headroom, cold_queue_wait_s)
            + _prefill(total_tokens - matched_tokens, cold_prefill_tps)
            + transfer_s
            # each pull already in flight at the target serializes ahead
            # of this one on the worker's kv_migrate_budget
            + max(0, cold_inflight_pulls) * transfer_s
        ),
        "recompute": (_wait(cold_headroom, cold_queue_wait_s)
                      + _prefill(total_tokens, cold_prefill_tps)),
    }
    if matched_blocks <= 0:
        return {"choice": "recompute", "costs": costs}
    if warm_is_cold:
        # the load/region-best candidate already holds the KV: nothing to
        # move, nothing to trade off
        return {"choice": "warm", "costs": costs}
    eligible = ["warm", "recompute"]
    if matched_blocks >= cfg.migrate_min_blocks:
        eligible.append("migrate")
    choice = min(eligible, key=lambda c: costs[c])
    return {"choice": choice, "costs": costs}


def route_flight_attrs(choice: str,
                       decision: Optional[Dict[str, Any]] = None,
                       worker_id: Optional[str] = None) -> Dict[str, Any]:
    """Flat scalar attrs for a request's ``server.route`` flight event —
    the one formatter both route paths (direct discovery and the claim
    arbitration) use, so a timeline reader sees the same shape either
    way. Costs are rounded to keep the event wire-lean."""
    out: Dict[str, Any] = {"choice": str(choice)}
    if worker_id:
        out["worker"] = str(worker_id)
    if decision and isinstance(decision.get("costs"), dict):
        for k, v in decision["costs"].items():
            try:
                out[f"cost_{k}"] = round(float(v), 4)
            except (TypeError, ValueError):
                continue
    return out
