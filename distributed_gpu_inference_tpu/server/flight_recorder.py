"""Server-side flight recorder: merge, retain, and surface request timelines.

One :class:`FlightRecorder` lives on the control plane's ``ServerState``.
It accumulates:

- **server events** (``note``): admission decision, route decision, claim,
  completion — stamped with the plane's clock;
- **worker wire payloads** (``ingest_wire``): per-request event lists
  shipped through job results and heartbeat ``engine_stats["flight"]``.
  Each payload carries the FULL event list for its (trace, source), and
  the recorder UNIONS events per source keyed by (name, timestamp) —
  duplicate delivery (retried heartbeat, replayed completion) is
  idempotent by construction, and two timelines sharing one source
  (local PD: prefill + decode stages on the same worker; a retry on the
  same worker) compose instead of clobbering each other.

``finalize`` derives the canonical phase durations from the merged
timeline, feeds the ``request_phase_latency_seconds{phase}`` histograms
(each phase observed at most ONCE per trace, no matter how many times a
completion/heartbeat re-delivers), retains the N slowest traces per phase
in bounded exemplar rings, and emits one retroactive OTel span per phase
when the ``TracingManager`` is live.

Everything here is advisory: a malformed payload is a counted, skipped
sample; the per-trace store is a bounded LRU; no recorder failure can
fail a request (callers wrap in try/except at the boundary)."""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..runtime.flight import (
    BOUNDARY_EVENTS,
    FLIGHT_BOUNDARY_RESERVE,
    FLIGHT_EVENT_CAP,
    PHASES,
    flight_enabled,
    merge_events,
    phase_durations,
)

# server-side events are recorded under this merge-source key
SERVER_SOURCE = "server"

# bounded retention: traces beyond this evict oldest-first (the debug
# endpoint is for "what just happened", not a TSDB)
TRACE_CAP = 2048

# slowest-trace exemplars retained per phase
EXEMPLARS_PER_PHASE = 8


class ExemplarRing:
    """Bounded retention of the N slowest traces for one phase.

    A min-heap of ``(duration, seq, trace_id)`` capped at ``n``: pushing a
    faster-than-minimum sample on a full ring is a no-op, a slower one
    evicts the current minimum — so the ring always holds the N slowest
    samples seen, in O(log n) per push and O(n) memory, forever."""

    def __init__(self, n: int = EXEMPLARS_PER_PHASE) -> None:
        self.n = max(1, int(n))
        self._heap: List[Tuple[float, int, str]] = []
        self._seq = itertools.count()

    def push(self, duration_s: float, trace_id: str) -> None:
        item = (float(duration_s), next(self._seq), str(trace_id))
        if len(self._heap) < self.n:
            heapq.heappush(self._heap, item)
        elif item[0] > self._heap[0][0]:
            heapq.heapreplace(self._heap, item)

    def items(self) -> List[Dict[str, Any]]:
        """Slowest first."""
        return [
            {"trace_id": tid, "duration_s": round(d, 6)}
            for d, _seq, tid in sorted(self._heap, reverse=True)
        ]


class _Trace:
    __slots__ = ("trace_id", "sources", "dropped", "observed",
                 "created_at", "job_ids", "done_sources")

    def __init__(self, trace_id: str) -> None:
        self.trace_id = trace_id
        # source -> raw event list [(name, ts, attrs), ...]
        self.sources: Dict[str, List[Any]] = {}
        self.dropped = 0
        # phases already observed into the histograms (observe-once)
        self.observed: set = set()
        self.created_at = time.time()
        self.job_ids: List[str] = []
        self.done_sources: set = set()


class FlightRecorder:
    """Bounded per-trace event store + the /metrics·OTel·exemplar fan-out."""

    def __init__(self, metrics: Optional[Any] = None,
                 tracing: Optional[Any] = None,
                 trace_cap: int = TRACE_CAP,
                 event_cap: int = FLIGHT_EVENT_CAP,
                 exemplars_per_phase: int = EXEMPLARS_PER_PHASE,
                 calibration: Optional[Any] = None) -> None:
        self._metrics = metrics
        self._tracing = tracing
        # cost-model self-calibration sink (server/calibration.py): done
        # wires carry the full per-source event list, whose queue-wait /
        # prefill spans are the calibration samples. Optional and
        # best-effort — a calibration failure never rejects a wire
        self._calibration = calibration
        self._trace_cap = max(1, int(trace_cap))
        self._event_cap = max(1, int(event_cap))
        self._traces: "OrderedDict[str, _Trace]" = OrderedDict()
        self._by_job: "OrderedDict[str, str]" = OrderedDict()
        # traces evicted AFTER observing phases: the worker heartbeat
        # ring re-ships done wires for up to 8 recent requests per beat,
        # and re-creating an evicted trace with a fresh observed-set
        # would double-count its phases into the histograms/exemplars
        self._retired: "OrderedDict[str, None]" = OrderedDict()
        # one lock: ingest arrives from aiohttp handlers, tests poke from
        # threads — per-call cost is a dict op, contention is irrelevant
        self._lock = threading.Lock()
        self.exemplars: Dict[str, ExemplarRing] = {
            p: ExemplarRing(exemplars_per_phase) for p in PHASES
        }
        self.stats: Dict[str, int] = {
            "traces": 0, "server_events": 0, "wire_ingested": 0,
            "wire_rejected": 0, "events_capped": 0, "finalized": 0,
        }

    # -- internals ----------------------------------------------------------

    def _get(self, trace_id: str, create: bool = True) -> Optional[_Trace]:
        tr = self._traces.get(trace_id)
        if tr is not None:
            self._traces.move_to_end(trace_id)
            return tr
        if not create:
            return None
        tr = _Trace(trace_id)
        self._traces[trace_id] = tr
        self.stats["traces"] += 1
        while len(self._traces) > self._trace_cap:
            old_id, old = self._traces.popitem(last=False)
            for jid in old.job_ids:
                self._by_job.pop(jid, None)
            if old.observed:
                self._retired[old_id] = None
                while len(self._retired) > 4 * self._trace_cap:
                    self._retired.popitem(last=False)
        return tr

    # -- server-side events ---------------------------------------------------

    def note(self, trace_id: Optional[str], event: str,
             job_id: Optional[str] = None, **attrs: Any) -> None:
        """Record one server-side event NOW. Safe to call with a missing
        trace id (no-op) — callers never branch."""
        if not trace_id or not isinstance(trace_id, str) \
                or not flight_enabled():
            return
        with self._lock:
            tr = self._get(trace_id)
            if job_id:
                self.link_job(job_id, trace_id, _locked=True)
            evs = tr.sources.setdefault(SERVER_SOURCE, [])
            # same boundary reserve as Timeline.note: a saturating trace
            # must still land server.completed or e2e never finalizes
            if len(evs) >= self._event_cap or (
                len(evs) >= self._event_cap - FLIGHT_BOUNDARY_RESERVE
                and event not in BOUNDARY_EVENTS
            ):
                tr.dropped += 1
                self.stats["events_capped"] += 1
                return
            evs.append((str(event), time.time(),
                        {k: v for k, v in attrs.items() if v is not None}
                        or None))
            self.stats["server_events"] += 1

    def link_job(self, job_id: str, trace_id: str,
                 _locked: bool = False) -> None:
        """Index a job id onto its trace (PD stage children all link to
        the parent's trace, so one merged timeline answers any of them)."""
        if not job_id or not trace_id:
            return
        if not _locked:
            with self._lock:
                self.link_job(job_id, trace_id, _locked=True)
            return
        tr = self._get(trace_id)
        if job_id not in tr.job_ids:
            tr.job_ids.append(job_id)
        self._by_job[job_id] = trace_id
        while len(self._by_job) > 4 * self._trace_cap:
            self._by_job.popitem(last=False)

    def trace_for_job(self, job_id: str) -> Optional[str]:
        with self._lock:
            return self._by_job.get(job_id)

    # -- worker wire ingest ---------------------------------------------------

    def ingest_wire(self, worker_id: str, wire: Any) -> bool:
        """Adopt one worker-shipped timeline payload (``Timeline.wire()``).

        The payload carries the full event list for its (trace, source);
        per source the recorder UNIONS events keyed by (name, timestamp)
        — re-delivery of the same (or a stale shorter) payload changes
        nothing, which is the whole idempotency contract for the
        at-least-once result and heartbeat channels, while two distinct
        timelines that share a source (local PD stages on one worker, a
        retry on the same worker) compose instead of the later one
        clobbering the earlier. Returns True when the payload CHANGED
        the trace (new events, or a newly-done source) — the heartbeat
        ingest path finalizes only on True, so re-shipped ring entries
        cannot re-finalize a trace."""
        if not flight_enabled():
            return False
        if not isinstance(wire, dict):
            self.stats["wire_rejected"] += 1
            return False
        tid = wire.get("trace_id")
        events = wire.get("events")
        if not tid or not isinstance(tid, str) \
                or not isinstance(events, list):
            self.stats["wire_rejected"] += 1
            return False
        with self._lock:
            if tid in self._retired:
                # already observed and evicted: a re-shipped ring entry
                # must not resurrect it into a fresh double-count
                return False
        source = str(wire.get("source") or worker_id or "worker")
        if source == SERVER_SOURCE:
            source = f"worker:{worker_id}"  # never alias the plane's events
        cleaned: List[Any] = []
        for ev in events[: self._event_cap]:
            try:
                name = str(ev[0])
                ts = float(ev[1])
            except (TypeError, ValueError, IndexError):
                continue
            attrs = ev[2] if len(ev) > 2 and isinstance(ev[2], dict) else None
            cleaned.append((name, ts, attrs))
        with self._lock:
            tr = self._get(tid)
            changed = False
            prior = tr.sources.get(source)
            if prior is None:
                tr.sources[source] = cleaned
                changed = bool(cleaned)
            elif cleaned:
                seen = {(e[0], round(float(e[1]), 6)) for e in prior}
                fresh = [e for e in cleaned
                         if (e[0], round(float(e[1]), 6)) not in seen]
                if fresh:
                    combined = prior + fresh
                    if len(combined) > self._event_cap:
                        # truncate bulk events first — slicing off a
                        # freshly-arrived boundary event (worker.done,
                        # pd.decode.done, ...) would silently shorten
                        # e2e/decode, the exact failure the worker-side
                        # boundary reserve exists to prevent
                        bnd = [e for e in combined
                               if e[0] in BOUNDARY_EVENTS]
                        bulk = [e for e in combined
                                if e[0] not in BOUNDARY_EVENTS]
                        keep = max(0, self._event_cap - len(bnd))
                        combined = sorted(
                            bulk[:keep] + bnd[: self._event_cap],
                            key=lambda e: float(e[1]),
                        )[: self._event_cap]
                    tr.sources[source] = combined
                    changed = True
            try:
                tr.dropped = max(tr.dropped, int(wire.get("dropped") or 0))
            except (TypeError, ValueError):
                pass
            if wire.get("done") and source not in tr.done_sources:
                tr.done_sources.add(source)
                changed = True
            self.stats["wire_ingested"] += 1
        if self._calibration is not None and wire.get("done"):
            # done wires carry the full event list — one calibration
            # sample per (trace, worker), deduped inside the calibrator
            # (the heartbeat ring re-ships recent done wires every beat)
            try:
                self._calibration.ingest_trace(
                    str(worker_id or source), tid, cleaned)
            except Exception:  # noqa: BLE001 — advisory, never fatal
                pass
        return changed

    # -- merged views ---------------------------------------------------------

    def timeline(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """The merged, monotonically-ordered timeline + derived phases."""
        with self._lock:
            tr = self._get(trace_id, create=False)
            if tr is None:
                return None
            sources = {s: list(evs) for s, evs in tr.sources.items()}
            dropped = tr.dropped
            observed = sorted(tr.observed)
            job_ids = list(tr.job_ids)
        merged = merge_events(sources)
        return {
            "trace_id": trace_id,
            "events": merged,
            "phases": {k: round(v, 6)
                       for k, v in phase_durations(merged).items()},
            "sources": sorted(sources),
            "job_ids": job_ids,
            "observed_phases": observed,
            **({"events_dropped": dropped} if dropped else {}),
        }

    def timeline_for_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        tid = self.trace_for_job(job_id)
        return self.timeline(tid) if tid else None

    def slowest(self) -> Dict[str, List[Dict[str, Any]]]:
        """Per-phase exemplar rings: the N slowest traces seen per phase
        (slowest first) — the 'which request blew the p95' index."""
        with self._lock:
            return {p: ring.items() for p, ring in self.exemplars.items()}

    # -- finalize -------------------------------------------------------------

    def finalize(self, trace_id: Optional[str],
                 partial: bool = False) -> Dict[str, float]:
        """Derive phases from the merged timeline and fan out: histogram
        observation (once per phase per trace — re-finalizing after more
        events arrive observes only phases not yet seen, so PD child
        completions and duplicate deliveries compose), exemplar retention,
        and retroactive OTel phase spans. Returns the durations observed
        THIS call.

        ``partial=True`` (a PD prefill child's completion) defers the
        phases whose right edge is the END of the request — e2e, decode,
        and the both-sides handoff span — to the terminal finalize;
        observing them here would lock a prefill-only span into the
        observe-once set and permanently exclude decode time. The same
        deferral applies automatically to a queued job whose worker wire
        arrived by heartbeat before ``complete_job`` stamped
        ``server.completed``."""
        if not trace_id:
            return {}
        with self._lock:
            tr = self._get(trace_id, create=False)
            if tr is None:
                return {}
            sources = {s: list(evs) for s, evs in tr.sources.items()}
            already = set(tr.observed)
        merged = merge_events(sources)
        durations = phase_durations(merged)
        names = {e["event"] for e in merged}
        if partial or ("server.submitted" in names
                       and "server.completed" not in names):
            durations = {p: d for p, d in durations.items()
                         if p not in ("e2e", "decode", "handoff")}
        fresh = {p: d for p, d in durations.items() if p not in already}
        if not fresh:
            return {}
        with self._lock:
            tr = self._get(trace_id, create=False)
            if tr is None:
                return {}
            # re-check under the lock: a concurrent finalize may have won
            fresh = {p: d for p, d in fresh.items() if p not in tr.observed}
            tr.observed.update(fresh)
            self.stats["finalized"] += 1
        m = self._metrics
        for phase, dur in fresh.items():
            if m is not None:
                try:
                    m.record_phase(phase, dur)   # Metrics has its own lock
                except Exception:  # noqa: BLE001 — advisory, never fatal
                    pass
        with self._lock:
            # heap pushes under the recorder lock: concurrent finalizes
            # interleaving heapq ops would break the ring invariant
            for phase, dur in fresh.items():
                ring = self.exemplars.get(phase)
                if ring is not None:
                    ring.push(dur, trace_id)
        tracing = self._tracing
        if tracing is not None and getattr(tracing, "enabled", False):
            self._emit_spans(trace_id, merged, fresh)
        return fresh

    def _emit_spans(self, trace_id: str, merged: List[Dict[str, Any]],
                    fresh: Dict[str, float]) -> None:
        """One retroactive OTel span per freshly-observed phase, anchored
        at the merged timeline's start. Best-effort by contract."""
        if not merged:
            return
        start = float(merged[0]["ts"])
        end = float(merged[-1]["ts"])
        for phase, dur in fresh.items():
            # anchor: e2e/ttft/queue_wait start at the trace start; the
            # rest end where their closing event landed — close enough
            # for a span waterfall, exact durations ride the histogram
            t1 = end if phase == "e2e" else min(start + dur, end)
            try:
                self._tracing.emit_span(
                    f"request.{phase}", t1 - dur, t1,
                    trace_id=trace_id, duration_s=round(dur, 6),
                )
            except Exception:  # noqa: BLE001
                pass
