"""Token issuance, HMAC request signing, lockout policy, audit events.

Behavioral parity with the reference's ``server/app/services/security.py``:
- ``TokenManager`` (:42-66): urlsafe tokens, salted-sha256 at rest,
  constant-time comparison.
- ``RequestSigner`` (:79-138): HMAC-SHA256 over ``METHOD:PATH:BODY_HASH:TS``
  with a 300 s validity window.
- Lockout policy (:256-271): 5 failures → 15 min lock
  (mirrors ``server/app/api/workers.py:55-94``).

Pure stdlib (hashlib/hmac/secrets) — no external crypto needed here.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

TOKEN_BYTES = 32
SIGNATURE_VALIDITY_S = 300.0
MAX_FAILED_ATTEMPTS = 5
LOCKOUT_SECONDS = 15 * 60.0
TOKEN_TTL_S = 7 * 24 * 3600.0


def generate_token() -> str:
    return secrets.token_urlsafe(TOKEN_BYTES)


def hash_token(token: str, salt: str = "") -> str:
    """Salted SHA-256 digest for at-rest storage (never store raw tokens)."""
    return hashlib.sha256(f"{salt}{token}".encode()).hexdigest()


def verify_token(token: str, stored_hash: str, salt: str = "") -> bool:
    return hmac.compare_digest(hash_token(token, salt), stored_hash)


@dataclass
class TokenBundle:
    """What a successful registration hands back to a worker."""

    auth_token: str
    refresh_token: str
    signing_secret: str
    expires_at: float

    def to_dict(self) -> Dict[str, Any]:
        return {
            "auth_token": self.auth_token,
            "refresh_token": self.refresh_token,
            "signing_secret": self.signing_secret,
            "expires_at": self.expires_at,
        }


class TokenManager:
    """Issues and verifies worker credentials; hashes live in the store."""

    def __init__(self, salt: str = "", token_ttl_s: float = TOKEN_TTL_S) -> None:
        self._salt = salt
        self._ttl = token_ttl_s

    def issue(self, now: Optional[float] = None) -> Tuple[TokenBundle, Dict[str, Any]]:
        """Returns (bundle-for-worker, fields-for-store)."""
        now = time.time() if now is None else now
        bundle = TokenBundle(
            auth_token=generate_token(),
            refresh_token=generate_token(),
            signing_secret=secrets.token_hex(32),
            expires_at=now + self._ttl,
        )
        stored = {
            "auth_token_hash": hash_token(bundle.auth_token, self._salt),
            "refresh_token_hash": hash_token(bundle.refresh_token, self._salt),
            "signing_secret": bundle.signing_secret,
            "token_expires_at": bundle.expires_at,
        }
        return bundle, stored

    def verify(
        self,
        token: str,
        stored_hash: Optional[str],
        expires_at: Optional[float] = None,
        now: Optional[float] = None,
    ) -> bool:
        if not token or not stored_hash:
            return False
        now = time.time() if now is None else now
        if expires_at is not None and now > expires_at:
            return False
        return verify_token(token, stored_hash, self._salt)


class RequestSigner:
    """HMAC-SHA256 request signatures over METHOD:PATH:BODY_HASH:TIMESTAMP."""

    def __init__(self, validity_s: float = SIGNATURE_VALIDITY_S) -> None:
        self._validity = validity_s

    @staticmethod
    def canonical(method: str, path: str, body: bytes, timestamp: str) -> str:
        body_hash = hashlib.sha256(body or b"").hexdigest()
        return f"{method.upper()}:{path}:{body_hash}:{timestamp}"

    def sign(self, secret: str, method: str, path: str, body: bytes,
             timestamp: Optional[str] = None) -> Dict[str, str]:
        ts = timestamp or str(int(time.time()))
        msg = self.canonical(method, path, body, ts)
        sig = hmac.new(secret.encode(), msg.encode(), hashlib.sha256).hexdigest()
        return {"X-Timestamp": ts, "X-Signature": sig}

    def verify(self, secret: str, method: str, path: str, body: bytes,
               timestamp: str, signature: str,
               now: Optional[float] = None) -> bool:
        try:
            ts_val = float(timestamp)
        except (TypeError, ValueError):
            return False
        now = time.time() if now is None else now
        if abs(now - ts_val) > self._validity:
            return False
        msg = self.canonical(method, path, body, timestamp)
        expect = hmac.new(secret.encode(), msg.encode(), hashlib.sha256).hexdigest()
        return hmac.compare_digest(expect, signature or "")


@dataclass
class LockoutState:
    failed_attempts: int = 0
    last_failed: Optional[float] = None
    locked_until: Optional[float] = None


class LockoutPolicy:
    """5 strikes → 15 min lock; success resets (reference workers.py:55-94)."""

    def __init__(self, max_attempts: int = MAX_FAILED_ATTEMPTS,
                 lockout_s: float = LOCKOUT_SECONDS) -> None:
        self.max_attempts = max_attempts
        self.lockout_s = lockout_s

    def is_locked(self, state: LockoutState, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        return state.locked_until is not None and now < state.locked_until

    def record_failure(self, state: LockoutState,
                       now: Optional[float] = None) -> LockoutState:
        now = time.time() if now is None else now
        n = state.failed_attempts + 1
        locked_until = state.locked_until
        if n >= self.max_attempts:
            locked_until = now + self.lockout_s
            n = 0
        return LockoutState(n, now, locked_until)

    def record_success(self, state: LockoutState) -> LockoutState:
        return LockoutState()


@dataclass
class AuditEvent:
    ts: float
    event: str
    actor: Optional[str]
    detail: Dict[str, Any] = field(default_factory=dict)


class AuditLogger:
    """In-memory ring of structured audit events; optionally mirrored to a
    Store's audit_log table by the API layer (reference security.py:287-336)."""

    def __init__(self, capacity: int = 4096) -> None:
        self._events: list[AuditEvent] = []
        self._capacity = capacity

    def log(self, event: str, actor: Optional[str] = None,
            **detail: Any) -> AuditEvent:
        ev = AuditEvent(time.time(), event, actor, detail)
        self._events.append(ev)
        if len(self._events) > self._capacity:
            self._events = self._events[-self._capacity:]
        return ev

    def recent(self, n: int = 100) -> list[AuditEvent]:
        return self._events[-n:]


class SecurityService:
    """Facade bundling token manager + signer + lockout + audit."""

    def __init__(self, salt: str = "") -> None:
        self.tokens = TokenManager(salt)
        self.signer = RequestSigner()
        self.lockout = LockoutPolicy()
        self.audit = AuditLogger()
